"""Step 5 — model monitoring (the working ``05_monitoring_wip.py``).

Run: python examples/05_monitoring.py [--root ./dftpu_store]
"""

import argparse

from distributed_forecasting_tpu.tasks.monitor import MonitorTask

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="./dftpu_store")
    args = p.parse_args()

    task = MonitorTask(
        init_conf={
            "env": {"root": args.root},
            "monitor": {
                "name": "finegrain",
                "table": "hackathon.sales.finegrain_forecasts",
                "granularities": ["1 day", "1 week"],
                "slicing_cols": ["store", "item"],
                # score residual z-anomalies against the model's own band
                "anomalies": True,
                # latest window's realized accuracy vs its own history
                "degradation": True,
            },
        }
    )
    out = task.launch()
    print("monitor:", out)
    profile = task.catalog.read_table(
        "hackathon.sales.finegrain_forecasts_profile_metrics"
    )
    overall = profile[profile.slice_key == ":all"]
    print(overall.tail(8).to_string(index=False))

    flagged = task.catalog.read_table(
        "hackathon.sales.finegrain_forecasts_anomalies"
    )
    print(f"\n{len(flagged)} anomalous rows; worst offenders:")
    print(
        flagged.nlargest(5, "anomaly_score")[
            ["ds", "store", "item", "y", "yhat", "anomaly_score"]
        ].to_string(index=False)
    )

    # --- drift: compare against the previous table version (time travel) ---
    from distributed_forecasting_tpu.monitoring import drift_report

    versions = task.catalog.table_versions(
        "hackathon.sales.finegrain_forecasts"
    )
    if len(versions) >= 2:
        drift = drift_report(
            task.catalog, "hackathon.sales.finegrain_forecasts",
            columns=("y", "yhat"), slicing_cols=("store",),
        )
        n = int(drift.drifted.sum())
        print(f"\ndrift vs version {versions[-2]}: "
              f"{n}/{len(drift)} (column, slice) pairs drifted")
        print(drift[drift.slice_key == ":all"][
            ["column", "psi", "ks", "status", "drifted"]
        ].to_string(index=False))
    else:
        print("\ndrift: single table version — scan appears at the next "
              "training snapshot")

    # --- degradation: did the LATEST window break from its history? --------
    deg = task.catalog.read_table(
        "hackathon.sales.finegrain_forecasts_degradation"
    )
    n_deg = int(deg.degraded.sum())
    print(f"\ndegradation: {n_deg}/{len(deg)} slices broke from their "
          f"trailing-window baseline (robust z > 3)")
    show = deg[deg.slice_key == ":all"][
        ["latest_window", "latest_value", "baseline_median", "z_score",
         "degraded"]
    ]
    print(show.to_string(index=False))
