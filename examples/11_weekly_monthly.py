"""Step 11 — non-daily cadences: weekly and monthly grids end to end.

The reference's workload (and dataset) is daily-only; real catalogs mix
cadences — weekly sell-through feeds, monthly wholesale.  Grids here are
pandas Period ordinals at a tensorize-time ``freq``, so the same batched
models run on any cadence: horizons, CV windows, and seasonal periods are
in STEPS of the cadence, and every output frame (and the serving
artifact) renders period-start dates.  In a task YAML this is one line:
``training: {freq: W}``.

Run: python examples/11_weekly_monthly.py
"""

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.engine import (
    CVConfig,
    cross_validate,
    detect_season_length,
    fit_forecast,
    forecast_frame,
)
from distributed_forecasting_tpu.models import HoltWintersConfig
from distributed_forecasting_tpu.serving import BatchForecaster

if __name__ == "__main__":
    rng = np.random.default_rng(0)

    # --- weekly feed: 400 weeks, yearly (52-week) cycle --------------------
    weeks = 400
    t = np.arange(weeks)
    rows = []
    for item in (1, 2, 3):
        y = 200.0 + 0.3 * t + 40.0 * np.sin(2 * np.pi * t / 52 + item) \
            + 8.0 * rng.normal(size=weeks)
        rows.append(pd.DataFrame(
            {"date": pd.date_range("2016-01-03", periods=weeks, freq="W"),
             "store": 1, "item": item, "sales": y}
        ))
    wdf = pd.concat(rows, ignore_index=True)

    batch = tensorize(wdf, freq="W")
    print(f"weekly batch: {batch.n_series} series x {batch.n_time} weeks "
          f"(contiguous — no 6/7 phantom gaps), freq={batch.freq}")

    m = detect_season_length(batch)
    print(f"season_length: auto -> {m} (steps = weeks; true cycle 52)")

    cfg = HoltWintersConfig(season_length=m, n_alpha=4, n_beta=3, n_gamma=3)
    # CV windows in WEEKS: 3 years initial, yearly cutoffs, half-year eval
    cv = cross_validate(batch, model="holt_winters", config=cfg,
                        cv=CVConfig(initial=156, period=52, horizon=26))
    print(f"weekly CV smape: {float(np.mean(np.asarray(cv['smape']))):.4f}  "
          f"mase: {float(np.mean(np.asarray(cv['mase']))):.3f} "
          f"(<1 beats seasonal-naive)")

    params, res = fit_forecast(batch, model="holt_winters", config=cfg,
                               horizon=26)
    table = forecast_frame(batch, res)
    fut = table[table["y"].isna()]
    print(f"26-week forecast: ds {fut['ds'].min().date()} .. "
          f"{fut['ds'].max().date()} (steps of 7 days)")

    # serving carries the cadence in the artifact
    fc = BatchForecaster.from_fit(batch, params, "holt_winters", cfg)
    out = fc.predict(pd.DataFrame({"store": [1], "item": [2]}), horizon=8)
    print("served weekly ds:", [str(d.date()) for d in out["ds"][:3]], "...")

    # --- monthly: a DAILY feed resampled into month buckets at tensorize ---
    T = 1460
    td = np.arange(T)
    ddf = pd.DataFrame({
        "date": pd.date_range("2019-01-01", periods=T), "store": 1, "item": 1,
        "sales": 10.0 + 3.0 * np.sin(2 * np.pi * td / 365.25)
        + 0.5 * rng.normal(size=T),
    })
    mbatch = tensorize(ddf, freq="M")
    print(f"\nmonthly batch from a daily feed: {mbatch.n_time} months "
          f"(rows SUMMED into period buckets)")
    mcfg = HoltWintersConfig(season_length=12, n_alpha=4, n_beta=3, n_gamma=3)
    mparams, mres = fit_forecast(mbatch, model="holt_winters", config=mcfg,
                                 horizon=12)
    mtable = forecast_frame(mbatch, mres)
    mfut = mtable[mtable["y"].isna()]
    print(f"12-month forecast: ds {mfut['ds'].min().date()} .. "
          f"{mfut['ds'].max().date()} (month starts)")
