"""Step 6 — ragged histories: span-bucketed fitting and serving.

Real retail catalogs are ragged: new items have months of history on a grid
built for years.  The shared-grid design (docs/architecture.md) handles
this with masks — correct, but a late-starting series still pays
full-history compute.  ``bucket_by_span`` groups series by observed span
and fits each bucket on a trimmed grid; ``BucketedForecaster`` serves the
result, routing each request key to its bucket (one compiled predict per
bucket present, never per series).

Run: python examples/06_ragged_bucketed.py
"""

import pandas as pd

from distributed_forecasting_tpu.data import (
    bucket_by_span,
    synthetic_store_item_sales,
    tensorize,
)
from distributed_forecasting_tpu.engine import (
    fit_forecast_bucketed,
    forecast_frame,
)
from distributed_forecasting_tpu.serving import BucketedForecaster

if __name__ == "__main__":
    # 500 series over 5 years; items >= 10 only exist for the last ~8 months
    df = synthetic_store_item_sales(n_stores=10, n_items=50, n_days=1826, seed=12)
    dates = pd.to_datetime(df["date"])
    launch = dates.min() + pd.Timedelta(days=1570)
    df = df[(df["item"] < 10) | (dates >= launch)]
    batch = tensorize(df)

    for idx, sub in bucket_by_span(batch):
        print(f"bucket: {sub.n_series:4d} series on a {sub.n_time:4d}-day grid "
              f"(from {sub.start_date})")

    buckets, result = fit_forecast_bucketed(batch, model="prophet", horizon=90)
    print(f"all ok: {bool(result.ok.all())}; "
          f"forecast grid: {int(result.day_all.shape[0])} days")
    table = forecast_frame(batch, result)
    print(f"forecast table: {len(table)} rows")

    forecaster = BucketedForecaster.from_bucketed_fit(buckets, "prophet")
    keys = batch.key_frame()
    request = pd.concat(  # one long-history and one recently-launched item
        [keys[keys["item"] < 10].head(1), keys[keys["item"] >= 10].head(1)]
    ).reset_index(drop=True)
    out = forecaster.predict(request, horizon=14)
    print(out.groupby("item").head(2).to_string(index=False))
