"""Step 2 — ingest + EDA + fine-grained training (the headline workload).

Mirrors the reference's ``notebooks/prophet/02_training.py`` flow: load the
(date, store, item, sales) table, explore it, fit one model per (store,
item) with rolling-origin CV, and write the forecast table — except the 500
fits are one compiled batched program instead of a Spark fan-out.

Run: python examples/02_training.py [--root ./dftpu_store] [--csv train.csv]
"""

import argparse

from distributed_forecasting_tpu.data import eda
from distributed_forecasting_tpu.tasks import IngestTask, TrainTask

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="./dftpu_store")
    p.add_argument("--csv", default=None, help="real train.csv; default synthetic")
    p.add_argument("--model", default="prophet",
                   choices=["prophet", "holt_winters", "arima"])
    p.add_argument("--tune", action="store_true",
                   help="per-series hyperparameter search (AutoML-path mode)")
    args = p.parse_args()
    env = {"env": {"root": args.root}}

    ingest = IngestTask(
        init_conf={
            **env,
            "input": (
                {"path": args.csv} if args.csv
                else {"synthetic": {"n_stores": 10, "n_items": 50, "n_days": 1826}}
            ),
            "output": {"table": "hackathon.sales.raw"},
        }
    )
    ingest.launch()

    raw = ingest.catalog.read_table("hackathon.sales.raw")
    print("dataset:", eda.dataset_stats(raw))
    print(eda.yearly_trend(raw).to_string(index=False))

    train = TrainTask(
        init_conf={
            **env,
            "input": {"table": "hackathon.sales.raw"},
            "output": {"table": "hackathon.sales.finegrain_forecasts"},
            "training": {
                "model": args.model,
                "cv": {"initial": 730, "period": 360, "horizon": 90},
                "horizon": 90,
                "tuning": {"enabled": args.tune, "n_trials": 8},
            },
        }
    )
    summary = train.launch()
    print("training summary:", summary)
