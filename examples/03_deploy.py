"""Step 3 — register the batched forecaster (``03_deploy.py`` equivalent).

Run: python examples/03_deploy.py [--root ./dftpu_store]
"""

import argparse

from distributed_forecasting_tpu.tasks import DeployTask

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="./dftpu_store")
    args = p.parse_args()

    task = DeployTask(
        init_conf={
            "env": {"root": args.root},
            "deploy": {
                "experiment": "finegrain_forecasting",
                "model_name": "ForecastingBatchModel",
                "tags": {"reviewed": "false"},
            },
        }
    )
    out = task.launch()
    v = task.registry.get_version(out["model_name"], out["version"])
    print(f"registered {v.name} v{v.version}; tags: {v.tags}")
