"""Step 7 — exogenous regressors: price and promotion covariates.

The reference's Prophet dependency supports ``add_regressor`` — covariate
columns joined onto the history frame whose future values the caller must
supply at predict time.  The TPU-native equivalent: regressor values ride
as a dense ``xreg`` tensor next to the series batch — ``(T, R)`` for a
calendar shared by all series, ``(S, T, R)`` for per-series covariates
(each store-item's price) — and enter the same one-shot batched ridge fit
as extra design columns (``ops/features.with_regressors``).

Run: python examples/07_regressors.py
"""

import dataclasses

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data import (
    synthetic_store_item_sales,
    tensorize,
    tensorize_regressors,
)
from distributed_forecasting_tpu.engine import fit_forecast, forecast_frame
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig
from distributed_forecasting_tpu.serving import BatchForecaster

HORIZON = 90

if __name__ == "__main__":
    # 50 series, 3 years; demand responds to a known promo calendar
    df = synthetic_store_item_sales(n_stores=5, n_items=10, n_days=1096, seed=3)
    batch = tensorize(df)
    dates = batch.dates()
    all_dates = dates.append(
        pd.date_range(dates[-1] + pd.Timedelta(days=1), periods=HORIZON)
    )

    # promo calendar: a 2-day event every 13 days, known into the future
    promo = (np.arange(len(all_dates)) % 13 < 2).astype(float)
    cal = pd.DataFrame({"date": all_dates, "promo": promo})
    xreg = tensorize_regressors(cal, batch, ["promo"], horizon=HORIZON)

    # inject the promo effect into the observed history (synthetic demand
    # does not know about promos) so the fit has something to find
    lift = 1.0 + 0.25 * xreg[: batch.n_time, 0]  # +25% on promo days
    batch = dataclasses.replace(batch, y=batch.y * lift[None, :])

    cfg = CurveModelConfig(n_regressors=1, regressor_names=("promo",))
    params, res = fit_forecast(
        batch, model="prophet", config=cfg, horizon=HORIZON, xreg=xreg
    )
    table = forecast_frame(batch, res)
    fut = table[table.ds > dates[-1]]
    promo_days = set(all_dates[promo > 0])
    on = fut[fut.ds.isin(promo_days)].yhat.mean()
    off = fut[~fut.ds.isin(promo_days)].yhat.mean()
    print(f"forecast mean on promo days {on:.2f} vs off {off:.2f} "
          f"(+{(on / off - 1) * 100:.1f}% learned lift)")

    # serving: the artifact carries the regressor standardization; requests
    # supply the future calendar exactly like Prophet's future dataframe
    fc = BatchForecaster.from_fit(batch, params, model="prophet", config=cfg)
    req = batch.key_frame().head(3)
    out = fc.predict(req, horizon=HORIZON, xreg=xreg)
    print(out.head(3).to_string(index=False))

    # probabilistic output: one column per quantile level, same request
    qout = fc.predict_quantiles(
        req, quantiles=(0.1, 0.5, 0.9), horizon=HORIZON, xreg=xreg
    )
    print(qout.head(3).to_string(index=False))
