"""Step 9 — calendar effects and curve structure: named holidays,
custom-period seasonality, known changepoints, saturating bounds.

The reference's AutoML trainer turns on US holidays by name alone
(``country_name="US"``, reference ``notebooks/automl/22-09-26…py:118``);
Prophet users add monthly cycles with ``add_seasonality``, pin known
structural breaks with ``changepoints=``, and bound saturating demand with
``cap``/``floor`` columns.  All four ride the same static config here —
one batched fit, no per-series Python.

Run: python examples/09_calendar_effects.py
"""

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.data.holidays import us_holiday_spec_for_range
from distributed_forecasting_tpu.engine import fit_forecast
from distributed_forecasting_tpu.models import prophet_glm
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig

HORIZON = 90

if __name__ == "__main__":
    # --- synthetic series with all four effects baked in -------------------
    rng = np.random.default_rng(0)
    dates = pd.date_range("2019-01-01", "2022-12-31", freq="D")
    T = len(dates)
    t = np.arange(T)
    base = 60 + 0.02 * t
    # slope break at 2021-01-01 (day index 731 of this grid)
    base += np.where(dates.year >= 2021, 0.08 * (t - 730), 0.0)
    monthly = 6.0 * np.sin(2 * np.pi * t / 30.5)
    xmas = ((dates.month == 12) & (dates.day == 25)).astype(float) * 25.0
    y = base + monthly + xmas + rng.normal(0, 1.0, T)
    df = pd.DataFrame({"date": dates, "store": 1, "item": 1, "sales": y})
    batch = tensorize(df)

    # --- conf: everything static, everything batched -----------------------
    break_day = int(
        (pd.Timestamp("2021-01-01") - pd.Timestamp("1970-01-01")).days
    )
    cfg = CurveModelConfig(
        seasonality_mode="additive",
        holidays=us_holiday_spec_for_range("2019-01-01", "2023-12-31"),
        extra_seasonalities=(("monthly", 30.5, 5),),
        changepoint_days=(break_day,),
        changepoint_prior_scale=5.0,
    )
    # (in a task YAML the same conf reads:
    #   model_conf:
    #     holidays: US
    #     extra_seasonalities: [[monthly, 30.5, 5]]
    #     changepoint_days: [<epoch day>]
    # — see conf/tasks/train_config.yml)

    params, result = fit_forecast(
        batch, model="prophet", config=cfg, horizon=HORIZON
    )
    print(f"fit ok: {bool(result.ok.all())}")

    # --- the components tell the story -------------------------------------
    comps = prophet_glm.decompose(params, result.day_all, cfg)
    mon = np.asarray(comps["monthly"])[0]
    hol = np.asarray(comps["holidays"])[0]
    print(f"monthly component amplitude (std): {mon.std():.2f}  (true 6/√2≈4.2)")
    fut = pd.to_datetime(
        np.asarray(result.day_all, "int64"), unit="D"
    )
    xmas_2022 = (fut.year == 2022) & (fut.month == 12) & (fut.day == 25)
    print(f"learned Christmas lift: {hol[xmas_2022][0]:.1f}  (true 25)")

    logged = prophet_glm.extract_params(params, cfg)
    print(
        f"changepoints: {logged['n_changepoints']} explicit site(s) "
        f"(explicit={logged['explicit_changepoints']})"
    )

    # --- saturating bounds: a declining series flattens at its floor --------
    decline = 20 + 70 / (1 + np.exp((t - 800) / 90))
    df2 = pd.DataFrame(
        {"date": dates, "store": 1, "item": 2,
         "sales": decline + rng.normal(0, 0.5, T)}
    )
    b2 = tensorize(df2)
    cfg2 = CurveModelConfig(
        growth="logistic", cap_value=100.0, floor_value=20.0,
        seasonality_mode="additive", yearly_order=0,
    )
    _, r2 = fit_forecast(batch=b2, model="prophet", config=cfg2,
                         horizon=365)
    tail = np.asarray(r2.yhat)[0, -90:]
    print(
        f"bounded decline: forecast tail mean {tail.mean():.1f} "
        f"(floor 20, never below: {bool(tail.min() >= 20 - 1e-3)})"
    )

    # --- AR-on-residuals: short-lead accuracy from autocorrelated noise ----
    ar_noise = np.zeros(T)
    for i in range(1, T):
        ar_noise[i] = 0.85 * ar_noise[i - 1] + rng.normal(0, 1.0)
    df3 = pd.DataFrame(
        {"date": dates, "store": 1, "item": 3,
         "sales": 80 + 0.01 * t + 3.0 * ar_noise}
    )
    b3 = tensorize(df3)
    cfg_ar = CurveModelConfig(seasonality_mode="additive", yearly_order=0,
                              weekly_order=0, ar_order=1)
    p3, r3 = fit_forecast(b3, model="prophet", config=cfg_ar, horizon=30)
    phi = float(p3.ar_phi[0, 0])
    band1 = float(r3.hi[0, b3.n_time] - r3.lo[0, b3.n_time])
    band30 = float(r3.hi[0, -1] - r3.lo[0, -1])
    print(
        f"AR-on-residuals: recovered phi={phi:.2f} (true 0.85); "
        f"1-day band {band1:.1f} vs 30-day band {band30:.1f} "
        f"(narrows by ~sqrt(1-phi^2) near the data, widens to marginal)"
    )
