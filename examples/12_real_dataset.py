"""Step 12 — the committed real-shaped dataset end to end.

The reference's workload is the Kaggle store-item ``train.csv`` (500
series, 2013-2017 daily — ``notebooks/prophet/02_training.py:30-35``).
That file can't be vendored, so the repo commits a fixed-seed dataset
with the same schema/shape and HARDER retail dynamics (negative-binomial
integer demand, ~20% intermittent items, unexplained promos, stockout
zero-runs, holiday closures — ``scripts/make_real_dataset.py``).  This
walkthrough ingests it through the C++ CSV parser, looks at what makes
it hostile, and shows the production answer: per-family CV, the
cross-family blend on a like-for-like holdout, and conformal-calibrated
intervals.  Full 500-series tables: ``scripts/real_accuracy.py`` and
docs/benchmarks.md; the same flow as a deployable DAG:
``dftpu-workflow -f conf/workflows.yml -w real-data-e2e``.

Run: python examples/12_real_dataset.py   (~2 min on CPU)
"""

import dataclasses
import os

import jax
import numpy as np

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.data.dataset import load_sales_csv
from distributed_forecasting_tpu.data.quality import quality_report
from distributed_forecasting_tpu.engine import CVConfig, cross_validate
from distributed_forecasting_tpu.engine.blend import fit_forecast_blend
from distributed_forecasting_tpu.ops import metrics as M

DATASET = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "datasets", "store_item_demand.csv.gz")

if __name__ == "__main__":
    # --- ingest through the native parser (gz -> temp -> C++ parse) --------
    df = load_sales_csv(DATASET)
    print(f"loaded {len(df):,} rows, "
          f"{df.groupby(['store', 'item']).ngroups} series")

    # --- what makes this feed hostile --------------------------------------
    report = quality_report(df, min_days=60)
    print(f"quality: {report.n_rows:,} rows, {report.n_series} series, "
          f"{report.date_min}..{report.date_max}, "
          f"{len(report.issues)} issue(s)")
    zero_frac = (df.assign(z=df["sales"] == 0)
                 .groupby(["store", "item"])["z"].mean())
    print(f"zeros: {float((df['sales'] == 0).mean()):.1%} of observations; "
          f"{int((zero_frac > 0.4).sum())} series are zero-heavy "
          f"(Croston regime)")

    # --- one store's items: CV per family, blend on a shared holdout -------
    sub = df[df["store"] == 3]
    batch = tensorize(sub)
    cv = CVConfig()  # the reference's 730/360/90
    key = jax.random.PRNGKey(0)

    print("\nrolling-origin CV (3 cutoffs), 50 series of store 3:")
    for fam in ("prophet", "croston", "theta"):
        m = cross_validate(batch, model=fam, cv=cv, key=key)
        mape = np.asarray(m["mape"])
        mase = np.asarray(m["mase"])
        print(f"  {fam:9s} MAPE {np.nanmean(mape[np.isfinite(mape)]):.3f}  "
              f"MASE {np.nanmean(mase[np.isfinite(mase)]):.3f}")

    # like-for-like: every model fit on history minus 90 d, scored there
    H, T = 90, batch.n_time
    hist = dataclasses.replace(
        batch, y=batch.y[:, : T - H], mask=batch.mask[:, : T - H],
        day=batch.day[: T - H],
    )
    params, blend, res = fit_forecast_blend(
        hist, models=("prophet", "croston", "theta"), horizon=H, key=key,
        cv=cv,
    )
    y_hold = batch.y[:, T - H:]
    m_hold = batch.mask[:, T - H:]
    mape_b = np.asarray(M.mape(y_hold, res.yhat[:, T - H: T], m_hold))
    print(f"\nblend on the final-90-day holdout: "
          f"MAPE {np.nanmean(mape_b[np.isfinite(mape_b)]):.3f} "
          f"(weights: {blend.mean_weights()})")
    print("\nfull 500-series tables: scripts/real_accuracy.py; "
          "deployable DAG: conf/workflows.yml real-data-e2e")
