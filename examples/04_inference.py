"""Step 4 — distributed batched inference (``04_inference.py`` equivalent).

Loads the registered model ONCE and forecasts every requested (store, item)
in one compiled call — no per-group model downloads, no sleep throttle.

Run: python examples/04_inference.py [--root ./dftpu_store]
"""

import argparse

from distributed_forecasting_tpu.tasks import InferenceTask

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="./dftpu_store")
    args = p.parse_args()

    task = InferenceTask(
        init_conf={
            "env": {"root": args.root},
            "input": {"table": "hackathon.sales.raw"},
            "output": {"table": "hackathon.sales.test_finegrain_forecasts"},
            "inference": {
                "model_name": "ForecastingBatchModel",
                "horizon": 90,
                "promote_to": "Staging",
            },
        }
    )
    out = task.launch()
    print("inference:", out)
    fc = task.catalog.read_table("hackathon.sales.test_finegrain_forecasts")
    print(fc.head().to_string(index=False))
