"""Step 1 — bootstrap the dataset catalog (the Unity-Catalog-equivalent).

Mirrors the reference's ``notebooks/prophet/01_unity_catalog.py`` flow:
create catalog + schema, apply grants, show what exists.

Run: python examples/01_catalog_setup.py [--root ./dftpu_store]
"""

import argparse

from distributed_forecasting_tpu.tasks import CatalogTask

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="./dftpu_store")
    args = p.parse_args()

    task = CatalogTask(
        init_conf={
            "env": {"root": args.root},
            "output": {"catalog_name": "hackathon", "schema_name": "sales"},
        }
    )
    task.launch()
    print("catalogs:", task.catalog.catalogs())
    print("schemas:", task.catalog.schemas("hackathon"))
    print("grants:", task.catalog.grants("hackathon"))
