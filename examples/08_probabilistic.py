"""Step 8 — probabilistic forecasting: quantiles, pinball loss, components.

M5-uncertainty-style workflow: hold out the last 28 days, fit on the rest,
price a 9-level quantile fan, score it with pinball loss against the
holdout, and decompose the point path into trend/seasonal components —
all from the same closed-form predictive distribution (no posterior
sampling; docs/architecture.md "Covariates and probabilistic output").

Run: python examples/08_probabilistic.py
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from distributed_forecasting_tpu.data import synthetic_store_item_sales, tensorize
from distributed_forecasting_tpu.engine import fit_forecast
from distributed_forecasting_tpu.models import prophet_glm
from distributed_forecasting_tpu.ops import metrics as M

HOLDOUT = 28
LEVELS = (0.005, 0.025, 0.165, 0.25, 0.5, 0.75, 0.835, 0.975, 0.995)  # M5

if __name__ == "__main__":
    df = synthetic_store_item_sales(n_stores=5, n_items=10, n_days=1096, seed=9)
    full = tensorize(df)
    T_fit = full.n_time - HOLDOUT

    train = dataclasses.replace(
        full, y=full.y[:, :T_fit], mask=full.mask[:, :T_fit],
        day=full.day[:T_fit],
    )
    # ONE config for fit and pricing — customizing it keeps both consistent
    cfg = prophet_glm.CurveModelConfig()
    params, res = fit_forecast(train, model="prophet", config=cfg,
                               horizon=HOLDOUT)

    yq = prophet_glm.forecast_quantiles(
        params, res.day_all, jnp.float32(train.day[-1]), cfg, LEVELS
    )  # (S, Q, T_fit + HOLDOUT)

    # pinball loss per level over the TRUE holdout days
    y_hold = full.y[:, T_fit:]
    m_hold = full.mask[:, T_fit:]
    print(f"{full.n_series} series, {HOLDOUT}-day holdout; pinball by level:")
    total = 0.0
    for i, q in enumerate(LEVELS):
        loss = float(jnp.mean(M.pinball(y_hold, yq[:, i, T_fit:], m_hold, q)))
        total += loss
        print(f"  q={q:<6} pinball={loss:.3f}")
    print(f"mean pinball (the M5-uncertainty score shape): {total/len(LEVELS):.3f}")

    # empirical coverage of the outer fan vs its nominal 99%
    cov = float(jnp.mean(M.coverage(
        y_hold, yq[:, 0, T_fit:], yq[:, -1, T_fit:], m_hold
    )))
    print(f"99% fan empirical coverage: {cov:.3f}")

    # component view of the first series (what drives the forecast)
    comps = prophet_glm.decompose(params, res.day_all, cfg)
    parts = {k: float(np.std(np.asarray(v[0]))) for k, v in comps.items()}
    print("component std (series 0):",
          {k: round(v, 2) for k, v in parts.items()})

    # --- split-conformal calibration (engine/calibrate) ------------------
    # The CV residuals become a calibration set: each series' band is
    # scaled by the rank-quantile factor that would have covered
    # interval_width of them.  In a pipeline this is one conf line
    # (training: {calibrate_intervals: true}); here the standalone entry:
    from distributed_forecasting_tpu.engine import (
        CVConfig,
        apply_interval_scale,
        conformal_interval_scale,
    )

    scale = conformal_interval_scale(
        train, model="prophet", config=cfg,
        cv=CVConfig(initial=730, period=180, horizon=HOLDOUT),
    )
    print(f"conformal band scales: mean {float(jnp.mean(scale)):.3f}, "
          f"range [{float(jnp.min(scale)):.3f}, {float(jnp.max(scale)):.3f}]")
    _, lo_c, hi_c = apply_interval_scale(res.yhat, res.lo, res.hi, scale)
    for label, (lo_b, hi_b) in {
        "raw   ": (res.lo, res.hi), "conformal": (lo_c, hi_c)
    }.items():
        cov95 = float(jnp.mean(M.coverage(
            y_hold, lo_b[:, T_fit:], hi_b[:, T_fit:], m_hold
        )))
        print(f"  95% band holdout coverage ({label}): {cov95:.3f}")
