"""Step 13 — hierarchical coherent forecasts, scored the M5 way.

The reference's only cross-series arithmetic is top-down allocation by
historical share (``notebooks/prophet/02_training.py:237-247``).  This
framework carries the full coherent-hierarchy toolkit
(``reconcile/hierarchy.py``), and docs/benchmarks.md measures which
configuration wins under the published M5 WRMSSE protocol: **theta fit
at every hierarchy node + MinT reconciliation with CV-error-variance
weights** — better than bottom-up, better than any blend/selection mix.
This walkthrough is that recipe, runnable:

  1. aggregate the committed 500-series dataset into its 561 hierarchy
     nodes (total / 10 stores / 50 items / 500 store-items);
  2. fit theta on ALL nodes as ONE batched program — an aggregate
     series is just another row on the same day grid;
  3. weight by each node's rolling-origin CV error variance and
     MinT-reconcile, so every level's forecast benefits from the
     levels that are easiest to predict;
  4. score with the M5 competition's WRMSSE against its own Naive and
     sNaive benchmark methods (``scripts/m5_protocol.py`` is the shared
     scorer — the committed table in docs/benchmarks.md comes from it).

Run: python examples/13_hierarchical_m5.py   (~1 min on CPU)
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.data.dataset import load_sales_csv
from distributed_forecasting_tpu.engine import CVConfig, cross_validate, fit_forecast
from distributed_forecasting_tpu.reconcile.hierarchy import (
    Hierarchy,
    coherency_error,
    reconcile_forecasts,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
from m5_protocol import (  # noqa: E402  (shared scorer + benchmark methods)
    H,
    naive_forecast,
    snaive_forecast,
    wrmsse,
)

DATASET = os.path.join(REPO, "datasets", "store_item_demand.csv.gz")

if __name__ == "__main__":
    batch = tensorize(load_sales_csv(DATASET))
    T = batch.n_time
    yb = np.asarray(batch.y * batch.mask)       # observed sales, zeros kept
    keys = np.asarray(batch.keys)

    # --- 1. the hierarchy as a static summing matrix -----------------------
    h = Hierarchy.from_keys(keys)
    print(f"hierarchy: {h.n_nodes} nodes over {h.n_bottom} bottom series "
          f"({len(h.stores)} stores x {len(h.items)} items)")

    # --- 2. every node is just another series: one batched theta fit -------
    y_tr_all = np.asarray(h.S_mat) @ yb[:, : T - H]      # (561, T_tr)
    agg = dataclasses.replace(
        batch,
        y=jnp.asarray(y_tr_all, jnp.float32),
        mask=jnp.ones(y_tr_all.shape, jnp.float32),
        day=batch.day[: T - H],
        keys=np.stack([np.arange(h.n_nodes), np.zeros(h.n_nodes)], 1)
        .astype(np.int64),
    )
    key = jax.random.PRNGKey(0)
    _, res = fit_forecast(agg, model="theta", horizon=H, key=key)
    base = res.yhat[:, T - H :]                           # (561, 28) incoherent
    incoh = float(jnp.max(coherency_error(h, base)))
    print(f"base forecasts: 561 nodes x {H} d in one dispatch; "
          f"max coherency error {incoh:.1f} units (levels disagree)")

    # --- 3. CV-variance weights + MinT: coherent, accuracy-sharing ---------
    m = cross_validate(agg, model="theta", cv=CVConfig(), key=key)
    var = np.asarray(m["mse"])
    var = np.where(np.isfinite(var) & (var > 0), var, np.nanmedian(var))
    coherent = reconcile_forecasts(h, base, error_var=jnp.asarray(var))
    print(f"reconciled: max coherency error "
          f"{float(jnp.max(coherency_error(h, coherent))):.2e} (exact)")

    # --- 4. M5 scoring vs the competition's own benchmarks -----------------
    bottom = np.maximum(np.asarray(coherent[-h.n_bottom :]), 0.0)
    ours, lv = wrmsse(yb[:, : T - H], yb[:, T - H :], bottom,
                      keys[:, 0], keys[:, 1])
    n_sc, _ = wrmsse(yb[:, : T - H], yb[:, T - H :],
                     naive_forecast(yb[:, : T - H]), keys[:, 0], keys[:, 1])
    s_sc, _ = wrmsse(yb[:, : T - H], yb[:, T - H :],
                     snaive_forecast(yb[:, : T - H]), keys[:, 0], keys[:, 1])
    print(f"\nM5 WRMSSE — theta+MinT: {ours:.4f}  "
          f"(levels: " + ", ".join(f"{k} {v:.3f}" for k, v in lv.items())
          + ")")
    print(f"             naive: {n_sc:.4f}   snaive: {s_sc:.4f}   "
          f"(competition benchmark methods)")
    assert ours < s_sc < n_sc, "theta+MinT must beat both M5 benchmarks"
    print("recipe beats both M5 benchmark methods — the configuration "
          "docs/benchmarks.md recommends for M5-style deployments")
