"""Step 10 — production robustness: spikes, miscalibrated bands, unknown
cadence.

Three things real retail feeds do that the clean reference dataset never
shows: promo/glitch spikes that drag an L2 fit, bands whose nominal 95%
is fiction out of sample, and mixed cadences where "weekly" is a guess.
This walkthrough runs the three countermeasures together — Huber-robust
fitting (``loss='huber'``), split-conformal band calibration
(``engine/calibrate``), and auto seasonality detection
(``engine/season``) — on a contaminated monthly-cadence batch.  In a task
YAML this is three conf lines (``model_conf: {loss: huber, season_length:
auto}``, ``calibrate_intervals: true``); here the library calls run
directly so each effect is visible in isolation.

Run: python examples/10_robust_production.py
"""

import numpy as np
import pandas as pd

import jax.numpy as jnp

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.engine import (
    CVConfig,
    apply_interval_scale,
    conformal_interval_scale,
    detect_season_length,
    fit_forecast,
)
from distributed_forecasting_tpu.models import HoltWintersConfig
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig
from distributed_forecasting_tpu.ops import metrics as M

HOLDOUT = 60

if __name__ == "__main__":
    # --- a hostile batch: monthly cycle, trend, 3% spike days, breaks ------
    rng = np.random.default_rng(0)
    T = 900
    t = np.arange(T)
    rows, clean = [], []
    for item in range(1, 9):
        base = 80.0 + 0.04 * t + 15.0 * np.sin(2 * np.pi * t / 30 + item)
        level = np.where(t > 600, base + 12.0, base)  # a mid-life break
        y = level + 2.0 * rng.normal(size=T)
        spikes = rng.random(T) < 0.03
        y = np.where(spikes, y * rng.uniform(5.0, 10.0, T), y)
        clean.append(level)
        rows.append(pd.DataFrame(
            {"date": pd.date_range("2020-01-01", periods=T), "store": 1,
             "item": item, "sales": y}
        ))
    df = pd.concat(rows, ignore_index=True)
    clean = np.stack(clean)
    full = tensorize(df)

    # --- 1. cadence is detected, not guessed -------------------------------
    m = detect_season_length(full)
    print(f"season_length: auto -> detected period {m} (true: 30)")

    # --- 2. robust vs L2 under contamination (curve model) -----------------
    for loss in ("l2", "huber"):
        cfg = CurveModelConfig(seasonality_mode="additive", loss=loss,
                               extra_seasonalities=(("monthly", float(m), 5),))
        params, res = fit_forecast(full, model="prophet", config=cfg,
                                   horizon=0)
        rmse = float(np.sqrt(np.mean(
            (np.asarray(res.yhat)[:, :T] - clean) ** 2
        )))
        width = float(np.mean(np.asarray(res.hi - res.lo)))
        print(f"  loss={loss:<6} clean-signal RMSE {rmse:7.2f}   "
              f"mean band width {width:8.1f}")

    # --- 3. conformal calibration closes the coverage gap ------------------
    # A separate hostile regime: recurring level shifts WITHOUT spikes
    # (spike days belong to the robust-fit story above — their 5-10x
    # excursions are outliers no honest band should chase).  The one-step
    # sigma the HW band is built from cannot anticipate shifts, so the
    # parametric band under-covers at h-step; the CV residuals see the
    # shifts and the conformal scale widens the band accordingly.
    rows_b = []
    for item in range(1, 9):
        level = np.zeros(T)
        cur = 80.0
        for i in range(T):
            # one shift lands INSIDE the holdout window (day 865) — the
            # out-of-sample surprise the calibrated band must absorb
            if i % 165 == 40:
                cur += rng.choice([-1, 1]) * rng.uniform(8, 15)
            level[i] = cur
        yb = level + 10.0 * np.sin(2 * np.pi * t / 7 + item) \
            + 1.5 * rng.normal(size=T)
        rows_b.append(pd.DataFrame(
            {"date": pd.date_range("2020-01-01", periods=T), "store": 1,
             "item": item, "sales": yb}
        ))
    df_b = pd.concat(rows_b, ignore_index=True)
    full_b = tensorize(df_b)
    cut_date = df_b["date"].min() + pd.Timedelta(days=T - HOLDOUT - 1)
    train = tensorize(df_b[df_b["date"] <= cut_date])
    hw = HoltWintersConfig(n_alpha=4, n_beta=3, n_gamma=3)
    scale = conformal_interval_scale(
        train, model="holt_winters", config=hw,
        cv=CVConfig(initial=360, period=120, horizon=HOLDOUT),
    )
    params, res = fit_forecast(train, model="holt_winters", config=hw,
                               horizon=HOLDOUT)
    y_hold = jnp.asarray(full_b.y[:, -HOLDOUT:])
    mask_hold = jnp.ones_like(y_hold)
    for label, (lo_b, hi_b) in {
        "raw      ": (res.lo, res.hi),
        "conformal": apply_interval_scale(res.yhat, res.lo, res.hi, scale)[1:],
    }.items():
        cov = float(jnp.mean(M.coverage(
            y_hold, lo_b[:, -HOLDOUT:], hi_b[:, -HOLDOUT:], mask_hold
        )))
        print(f"  95% band holdout coverage ({label}): {cov:.3f}")
    print(f"conformal band scales: mean {float(jnp.mean(scale)):.2f} "
          f"(shiftier series get wider bands)")
