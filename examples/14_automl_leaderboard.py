"""Step 14 — a budgeted cross-family AutoML sweep, leaderboard included.

The reference's AutoML notebook tunes Prophet hyperparameters per series
with one Spark task per trial (``notebooks/automl/22-09-26...py``).
This framework races whole FAMILIES — each one a single compiled batched
CV program — under a device-seconds budget with successive halving
(``engine/select.successive_halving_select``, docs/automl.md#sweep):

  1. load the committed 500-series store-item dataset and keep an
     evenly-strided 64-series slice (every demand regime represented);
  2. race six families — including ``arnet``, the batched-gradient
     AR-Net member (docs/automl.md#family) — on cheap early rungs
     (series subsets, last-N CV cutoffs), halving the roster each rung;
  3. every evaluation is timed to completion and charged to the
     cost-attribution counters; the budget is a LAUNCH GATE — no new
     evaluation starts once the meter crosses it;
  4. print the leaderboard: accuracy (rung-mean smape) against
     cumulative device-seconds, then the final per-series assignment.

Run: python examples/14_automl_leaderboard.py   (~2 min on CPU)
"""

import dataclasses
import os

import numpy as np

from distributed_forecasting_tpu.data import tensorize
from distributed_forecasting_tpu.data.dataset import load_sales_csv
from distributed_forecasting_tpu.engine import CVConfig
from distributed_forecasting_tpu.engine.hyper import AutoMLConfig
from distributed_forecasting_tpu.engine.select import successive_halving_select
from distributed_forecasting_tpu.models import ArnetConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATASET = os.path.join(REPO, "datasets", "store_item_demand.csv.gz")

if __name__ == "__main__":
    batch = tensorize(load_sales_csv(DATASET))

    # evenly-strided 64-series slice: representative, and a pow2 bucket
    S_keep = 64
    idx = (np.arange(S_keep) * batch.n_series) // S_keep
    batch = dataclasses.replace(
        batch, y=batch.y[idx], mask=batch.mask[idx],
        keys=np.asarray(batch.keys)[idx],
    )
    print(f"dataset: {batch.n_series} series x {batch.n_time} days")

    cfg = AutoMLConfig(
        enabled=True,
        families=("prophet", "holt_winters", "theta", "croston",
                  "arima", "arnet"),
        budget_device_seconds=120.0,
        eta=2,
        rungs=3,
        base_series=8,     # rung 0: 8 series, 1 cutoff; rung 2: 32, 4
        base_cutoffs=1,
        metric="smape",
    )
    cv = CVConfig(initial=730, period=360, horizon=90)
    # a lighter arnet for the race: the sweep scores generalization, not
    # the last 0.1% of training convergence
    configs = {"arnet": ArnetConfig(lags=14, epochs=10)}

    res = successive_halving_select(batch, config=cfg, configs=configs,
                                    cv=cv)

    print(f"\n=== leaderboard (budget {cfg.budget_device_seconds:.0f} "
          f"device-seconds, spent {res.spent_device_seconds:.1f}, "
          f"exhausted={res.budget_exhausted}) ===")
    cols = ["rung", "family", "n_series", "n_cutoffs", "mean_smape",
            "device_seconds", "cumulative_device_seconds"]
    with np.printoptions(precision=3):
        print(res.leaderboard[cols].to_string(
            index=False, float_format=lambda v: f"{v:.3f}"))

    print(f"\nsurvivors after the rungs: {res.survivors}")
    print("final per-series assignment:")
    for fam, n in sorted(res.selection.counts().items(),
                         key=lambda kv: -kv[1]):
        print(f"  {fam:>14}: {n:3d} series")
    best = res.leaderboard.sort_values("mean_smape").iloc[0]
    print(f"\nbest rung-mean smape: {best.mean_smape:.3f} "
          f"({best.family}, rung {best.rung})")
