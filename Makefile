# Developer workflow — the reference drives deploy/test through a Makefile
# (its Makefile:1-5 wraps dbx execute/deploy/launch); same shape, no cluster.

.PHONY: install lint tsan test test-tpu native bench e2e clean

install:
	pip install -e ".[local,test]"

# pure-AST static analysis (docs/static-analysis.md) — seconds, CPU-only,
# never initializes a device; exit 1 on any error-severity finding.
# scripts/ is in scope for the dfproto client-side contract extraction
# (bench/chaos call sites) and docs/ for the endpoint-table drift rule.
lint:
	python scripts/dflint.py distributed_forecasting_tpu/ scripts/ docs/

# dynamic layer (docs/static-analysis.md "Dynamic layer"): run the
# threaded test subset under the runtime concurrency sanitizer with
# seeded schedule perturbation, then cross-check the observed lock graph
# and guarded-attribute accesses against dflint's static model.  Exit 1
# on any unsuppressed error-severity finding.
TSAN_REPORT_DIR ?= /tmp/dftpu-tsan-reports
tsan:
	rm -rf $(TSAN_REPORT_DIR) && mkdir -p $(TSAN_REPORT_DIR)
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  DFTPU_TSAN=1 DFTPU_TSAN_REPORT_DIR=$(TSAN_REPORT_DIR) \
	  DFTPU_FAILPOINTS="sanitizer.yield=sleep 1:0.05" \
	  DFTPU_FAILPOINTS_SEED=42 \
	  python -m pytest tests/unit/test_batcher.py tests/unit/test_ingest.py \
	    tests/unit/test_forecast_cache.py tests/unit/test_fleet.py \
	    tests/unit/test_dataplane.py \
	    -q -m 'not slow' -p no:cacheprovider
	# own process, NOT instrumented: these tests arm/reset the sanitizer
	# themselves, which would wipe the recorder the run above is filling
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/unit/test_dftsan.py tests/unit/test_dflint_v3.py \
	    -q -p no:cacheprovider
	python scripts/dftsan.py $(TSAN_REPORT_DIR)

native:
	$(MAKE) -C native

test: lint native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/unit -x -q

# no -x: hardware windows are scarce — one red test must not blind the rest
# of the suite (round-3 ran 5/9, round-4 stopped at the first failure)
test-tpu:
	DFTPU_TEST_PLATFORM=tpu python -m pytest tests/integration -q

bench:
	python bench.py

e2e:
	env -u PALLAS_AXON_POOL_IPS DFTPU_PLATFORM=cpu \
	python -m distributed_forecasting_tpu.workflows.runner \
	  -f conf/workflows.yml -w forecasting-e2e

clean:
	rm -rf dftpu_store build dist *.egg-info
	$(MAKE) -C native clean
