# Developer workflow — the reference drives deploy/test through a Makefile
# (its Makefile:1-5 wraps dbx execute/deploy/launch); same shape, no cluster.

.PHONY: install lint test test-tpu native bench e2e clean

install:
	pip install -e ".[local,test]"

# pure-AST static analysis (docs/static-analysis.md) — seconds, CPU-only,
# never initializes a device; exit 1 on any error-severity finding
lint:
	python scripts/dflint.py distributed_forecasting_tpu/

native:
	$(MAKE) -C native

test: lint native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/unit -x -q

# no -x: hardware windows are scarce — one red test must not blind the rest
# of the suite (round-3 ran 5/9, round-4 stopped at the first failure)
test-tpu:
	DFTPU_TEST_PLATFORM=tpu python -m pytest tests/integration -q

bench:
	python bench.py

e2e:
	env -u PALLAS_AXON_POOL_IPS DFTPU_PLATFORM=cpu \
	python -m distributed_forecasting_tpu.workflows.runner \
	  -f conf/workflows.yml -w forecasting-e2e

clean:
	rm -rf dftpu_store build dist *.egg-info
	$(MAKE) -C native clean
