"""Hierarchical forecast reconciliation (BASELINE config #5).

The reference's only cross-series arithmetic is its allocation path: item
forecasts scaled to stores by historical share (``notebooks/prophet/
02_training.py:237-247``) — a top-down method.  This module provides the full
coherent-hierarchy toolkit over batched base forecasts:

  * :class:`Hierarchy` — the store x item two-level hierarchy as a static
    summing matrix ``S_mat`` (rows: total, per-store, per-item, bottom);
  * bottom-up aggregation (sum bottom forecasts to every level);
  * top-down allocation by historical proportions (the reference's method);
  * MinT-diagonal (WLS) reconciliation: given base forecasts at EVERY level,
    the trace-minimizing coherent revision
    ``y~ = S (S' W^-1 S)^-1 S' W^-1 y^`` with diagonal W from base-forecast
    error variances — one batched solve, MXU-friendly.

All ops are pure jnp over (n_nodes, H) arrays; under a series-sharded mesh
the bottom level is gathered with ``jax.lax.all_gather`` first (aggregation
is a cross-shard reduction — the one place this workload genuinely needs a
collective beyond metric psums, SURVEY.md §2.4 backend row).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Hierarchy:
    """Two-level (store, item) hierarchy over S bottom series.

    Node order: [total, stores..., items..., bottom...].
    """

    keys: np.ndarray          # (S, 2) int64 (store, item) per bottom series
    stores: np.ndarray        # unique store ids (sorted)
    items: np.ndarray         # unique item ids (sorted)
    S_mat: np.ndarray         # (n_nodes, S) float32 summing matrix

    @classmethod
    def from_keys(cls, keys: np.ndarray) -> "Hierarchy":
        keys = np.asarray(keys)
        S = keys.shape[0]
        stores = np.unique(keys[:, 0])
        items = np.unique(keys[:, 1])
        rows = [np.ones((1, S), np.float32)]
        rows.append((keys[None, :, 0] == stores[:, None]).astype(np.float32))
        rows.append((keys[None, :, 1] == items[:, None]).astype(np.float32))
        rows.append(np.eye(S, dtype=np.float32))
        return cls(keys=keys, stores=stores, items=items,
                   S_mat=np.concatenate(rows, axis=0))

    @property
    def n_bottom(self) -> int:
        return self.keys.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.S_mat.shape[0]

    def node_labels(self) -> list:
        labels = ["total"]
        labels += [f"store_{s}" for s in self.stores]
        labels += [f"item_{i}" for i in self.items]
        labels += [f"store_{s}_item_{i}" for s, i in self.keys.tolist()]
        return labels


def aggregate_bottom_up(h: Hierarchy, bottom: jnp.ndarray) -> jnp.ndarray:
    """(S, H) bottom forecasts -> (n_nodes, H) coherent forecasts by summing.
    One matmul with the summing matrix (the MXU path)."""
    return jnp.asarray(h.S_mat) @ bottom


def top_down_allocate(
    h: Hierarchy, total: jnp.ndarray, proportions: jnp.ndarray
) -> jnp.ndarray:
    """(H,) total forecast + (S,) historical proportions -> coherent
    (n_nodes, H).  The reference's allocation method generalized to the full
    hierarchy (its ratio join, ``02_training.py:237-247``)."""
    p = proportions / jnp.maximum(jnp.sum(proportions), 1e-12)
    bottom = p[:, None] * total[None, :]
    return aggregate_bottom_up(h, bottom)


def reconcile_forecasts(
    h: Hierarchy,
    base_all_levels: jnp.ndarray,
    error_var: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """MinT-diagonal (WLS) reconciliation.

    base_all_levels: (n_nodes, H) independent base forecasts at every level
    (incoherent in general); error_var: (n_nodes,) base-error variances
    (defaults to structural variances = row sums of S_mat, i.e. WLS-struct).
    Returns coherent (n_nodes, H) revised forecasts.
    """
    S_mat = jnp.asarray(h.S_mat)  # (m, n)
    if error_var is None:
        error_var = jnp.sum(S_mat, axis=1)  # WLS-struct
    w_inv = 1.0 / jnp.maximum(error_var, 1e-12)  # (m,)
    SW = S_mat * w_inv[:, None]  # rows scaled: W^-1 S  (m, n)
    G = S_mat.T @ SW  # (n, n) = S' W^-1 S
    rhs = SW.T @ base_all_levels  # (n, H)
    chol = jax.scipy.linalg.cho_factor(
        G + 1e-8 * jnp.eye(G.shape[0]), lower=True
    )
    bottom_tilde = jax.scipy.linalg.cho_solve(chol, rhs)  # (n, H)
    return S_mat @ bottom_tilde


def coherency_error(h: Hierarchy, all_levels: jnp.ndarray) -> jnp.ndarray:
    """Max absolute violation of the aggregation constraints (0 = coherent)."""
    bottom = all_levels[-h.n_bottom :]
    return jnp.max(jnp.abs(all_levels - aggregate_bottom_up(h, bottom)))


def gather_bottom_sharded(bottom_sharded: jnp.ndarray, mesh, axis_name: str):
    """All-gather the series-sharded bottom forecasts so every chip holds the
    full bottom level for aggregation — the ICI collective of this module."""
    from jax.sharding import PartitionSpec as P

    def body(x):
        return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)

    return jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P(axis_name, None),
            out_specs=P(None, None), check_vma=False,
        )
    )(bottom_sharded)


def reconciliation_report(
    h: Hierarchy, bottom_forecast: jnp.ndarray, bottom_actual: jnp.ndarray,
    mask: jnp.ndarray,
) -> Dict[str, float]:
    """Accuracy of coherent aggregates vs aggregated actuals (smoke-level
    observability for the reconcile step)."""
    from distributed_forecasting_tpu.ops import metrics as M

    agg_f = aggregate_bottom_up(h, bottom_forecast)
    agg_a = aggregate_bottom_up(h, bottom_actual)
    agg_m = (aggregate_bottom_up(h, mask) > 0).astype(jnp.float32)
    return {
        "total_mape": float(M.mape(agg_a[:1], agg_f[:1], agg_m[:1])[0]),
        "store_mape": float(
            jnp.mean(M.mape(agg_a[1 : 1 + len(h.stores)],
                            agg_f[1 : 1 + len(h.stores)],
                            agg_m[1 : 1 + len(h.stores)]))
        ),
        "item_mape": float(
            jnp.mean(
                M.mape(
                    agg_a[1 + len(h.stores) : 1 + len(h.stores) + len(h.items)],
                    agg_f[1 + len(h.stores) : 1 + len(h.stores) + len(h.items)],
                    agg_m[1 + len(h.stores) : 1 + len(h.stores) + len(h.items)],
                )
            )
        ),
    }
