from distributed_forecasting_tpu.reconcile.hierarchy import (
    Hierarchy,
    aggregate_bottom_up,
    reconcile_forecasts,
)

__all__ = ["Hierarchy", "aggregate_bottom_up", "reconcile_forecasts"]
