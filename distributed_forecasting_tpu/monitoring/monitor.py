"""Model monitoring — the working version of the reference's WIP.

The reference sketches Databricks model monitoring (``notebooks/prophet/
05_monitoring_wip.py``): ``create_monitor`` over a logging table with
granularities, id/timestamp columns and slicing expressions, plus cleanup
helpers for monitors and registered models — but the notebook is
non-functional (undefined variables, classifier model type for a forecaster,
SURVEY.md §2.3-6).  This module implements that intent for real:

  * :class:`MonitorConfig` — what to monitor: a forecast table (the
    ``[ds, keys..., y, yhat, ...]`` schema), timestamp column, granularities
    (e.g. ``1 day``/``1 week``/``1 month``), slicing columns (store, item);
  * :class:`MonitorRegistry` — monitor lifecycle (create/get/list/delete)
    persisted as JSON next to the warehouse;
  * :func:`run_monitor` — computes the profile-metrics table: per
    (window, granularity, slice) forecast-quality metrics (mape, smape,
    bias, rmse, coverage) over rows where actuals exist, written back to the
    dataset catalog as ``<table>_profile_metrics`` for dashboards/alerts.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.catalog import DatasetCatalog

_GRANULARITY_FREQ = {"1 day": "D", "1 week": "W", "1 month": "M"}  # Period freqs


@dataclasses.dataclass
class MonitorConfig:
    name: str
    table: str                        # catalog table with forecasts+actuals
    timestamp_col: str = "ds"
    prediction_col: str = "yhat"
    label_col: str = "y"
    granularities: tuple = ("1 day", "1 week")
    slicing_cols: tuple = ("store", "item")
    interval_cols: tuple = ("yhat_lower", "yhat_upper")

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "MonitorConfig":
        d = dict(d)
        for k in ("granularities", "slicing_cols", "interval_cols"):
            if k in d and isinstance(d[k], list):
                d[k] = tuple(d[k])
        return cls(**d)


class MonitorRegistry:
    """Create/list/delete monitors (the reference's ``create_monitor`` /
    ``cleanup_existing_monitor`` lifecycle, ``05_monitoring_wip.py:20-78``)."""

    def __init__(self, root: str):
        self.root = os.path.join(root, "monitors")
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.json")

    def create_monitor(self, config: MonitorConfig, exist_ok: bool = True) -> None:
        path = self._path(config.name)
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(f"monitor {config.name!r} exists")
        with open(path, "w") as f:
            # human-readable provenance only, never numerics
            json.dump({**config.to_dict(),
                       "created_at": time.time()},  # dflint: disable=nondeterminism
                      f, indent=2)

    def get_monitor(self, name: str) -> MonitorConfig:
        path = self._path(name)
        if not os.path.exists(path):
            raise KeyError(f"monitor {name!r} not found")
        with open(path) as f:
            d = json.load(f)
        d.pop("created_at", None)
        return MonitorConfig.from_dict(d)

    def list_monitors(self) -> List[str]:
        return sorted(
            f[:-5] for f in os.listdir(self.root) if f.endswith(".json")
        )

    def delete_monitor(self, name: str) -> None:
        path = self._path(name)
        if os.path.exists(path):
            os.remove(path)


def _row_metrics(df: pd.DataFrame, cfg: MonitorConfig) -> pd.DataFrame:
    """Per-row metric terms; every window/slice metric is then a plain
    groupby mean over these (rmse via sqrt of the err2 mean), which turns
    the profile computation into a handful of vectorized groupbys instead
    of a Python loop over every slice value."""
    y = df[cfg.label_col].to_numpy(dtype=float)
    yhat = df[cfg.prediction_col].to_numpy(dtype=float)
    err = yhat - y
    denom = np.where(np.abs(y) > 1e-9, y, np.nan)
    out = pd.DataFrame(
        {
            "_ape": np.abs(err / denom),  # NaN rows skipped by mean()
            "_sape": np.abs(err)
            / np.maximum((np.abs(y) + np.abs(yhat)) / 2, 1e-9),
            "_err2": err**2,
            "_err": err,
            # missing predictions must surface, not shrink the denominator:
            # groupby mean skips NaN, so carry an indicator and NaN out
            # rmse/bias for any window that contains one (the old np.mean
            # semantics)
            "_prednan": np.isnan(err).astype(float),
        },
        index=df.index,
    )
    lo_c, hi_c = cfg.interval_cols
    if lo_c in df.columns and hi_c in df.columns:
        out["_inside"] = (
            (y >= df[lo_c].to_numpy(float)) & (y <= df[hi_c].to_numpy(float))
        ).astype(float)
    return out


def _grouped_metrics(terms: pd.DataFrame, keys: list) -> pd.DataFrame:
    g = terms.groupby(keys, observed=True)  # dropna default: a NaN slice
    # value never formed a group in the per-value loop this replaces
    agg = g.mean()
    agg["n_obs"] = g.size()
    agg["rmse"] = np.sqrt(agg.pop("_err2"))
    bad = agg.pop("_prednan") > 0
    agg.loc[bad, ["rmse", "_err"]] = np.nan
    agg = agg.rename(
        columns={"_ape": "mape", "_sape": "smape", "_err": "bias",
                 "_inside": "coverage"}
    )
    return agg.reset_index()


def run_monitor(
    catalog: DatasetCatalog,
    config: MonitorConfig,
    output_table: Optional[str] = None,
    df: Optional[pd.DataFrame] = None,
) -> pd.DataFrame:
    """Compute the profile-metrics table and persist it.

    Output rows: one per (window_start, granularity, slice_key, slice_value)
    plus un-sliced ``:all`` rows; written to ``<table>_profile_metrics``.
    ``df``: optional pre-loaded table (a caller running several monitoring
    passes over the same snapshot reads it once).
    """
    if df is None:
        df = catalog.read_table(config.table)
    df = df[~df[config.label_col].isna()].copy()
    if df.empty:
        raise ValueError(f"no labeled rows in {config.table} to monitor")
    ts = pd.to_datetime(df[config.timestamp_col])

    terms = _row_metrics(df, config)
    parts = []
    for gran in config.granularities:
        freq = _GRANULARITY_FREQ.get(gran)
        if freq is None:
            raise ValueError(
                f"unknown granularity {gran!r}; valid: {sorted(_GRANULARITY_FREQ)}"
            )
        window = ts.dt.to_period(freq).dt.start_time.rename("window_start")
        for col in [None, *[c for c in config.slicing_cols if c in df.columns]]:
            keys = [window] if col is None else [df[col], window]
            agg = _grouped_metrics(terms, keys)
            agg["granularity"] = gran
            agg["slice_key"] = col or ":all"
            agg["slice_value"] = (
                agg.pop(col).astype(str) if col is not None else ":all"
            )
            parts.append(agg)
    lead = ["window_start", "granularity", "slice_key", "slice_value",
            "n_obs"]
    if parts:
        profile = pd.concat(parts, ignore_index=True)
        profile = profile[lead + [c for c in profile.columns if c not in lead]]
    else:  # e.g. granularities=() in a hand-edited monitor spec
        profile = pd.DataFrame(columns=lead)
    out_name = output_table or f"{config.table}_profile_metrics"
    catalog.save_table(out_name, profile)
    return profile


def detect_anomalies(
    catalog: DatasetCatalog,
    table: str,
    interval_width: float = 0.95,
    score_threshold: Optional[float] = None,
    label_col: str = "y",
    prediction_col: str = "yhat",
    interval_cols: Tuple[str, str] = ("yhat_lower", "yhat_upper"),
    output_table: Optional[str] = None,
    df: Optional[pd.DataFrame] = None,
) -> pd.DataFrame:
    """Score a forecast table's labeled rows for anomalies.

    Residual z-scores against the model's own predictive band: the
    per-row sigma is recovered from the UPPER half-band, ``(hi - yhat) /
    z_w`` for the ``interval_width`` the model was fit with (the lower
    bound may be clamped — croston floors it at 0, multiplicative/logistic
    bands are asymmetric in data space — so the full width underestimates
    sigma), making the score comparable across series with different
    scales and across lead times (the band widens with horizon).  A row is
    flagged when its score exceeds ``score_threshold`` (default: the z of
    the interval — for symmetric bands that is y outside the band; below a
    clamped lower bound intentionally flags only past the same sigma
    distance).  This is the alerting half the reference's
    WIP monitoring notebook never got to — built on the forecast table the
    training pipeline already writes, no extra model pass needed.

    Returns all scored rows with ``anomaly_score``/``is_anomaly`` columns;
    the flagged subset is persisted to ``<table>_anomalies``.  ``df``: a
    pre-loaded table (MonitorTask shares one read between the profile and
    anomaly passes).
    """
    # jax is a hard dependency; the same z-for-width inverse-normal the
    # model modules use (no scipy in install_requires)
    from jax.scipy.special import ndtri as _ndtri

    if df is None:
        df = catalog.read_table(table)
    lo_c, hi_c = interval_cols
    for c in (label_col, prediction_col, lo_c, hi_c):
        if c not in df.columns:
            raise ValueError(f"column {c!r} not in {table}")
    df = df[~df[label_col].isna()].copy()
    if df.empty:
        raise ValueError(f"no labeled rows in {table} to score")
    z_w = float(_ndtri(0.5 + interval_width / 2.0))
    if score_threshold is None:
        score_threshold = z_w
    y = df[label_col].to_numpy(float)
    yhat = df[prediction_col].to_numpy(float)
    # sigma from the UPPER half-band only: lower bounds get clamped (croston
    # floors yhat_lower at 0; multiplicative/logistic bands are asymmetric
    # in data space), so (hi-lo)/(2z) under-estimates sigma for
    # intermittent/near-zero series and inflates scores — same rationale as
    # models/base.gaussian_quantiles.  Approximation for transformed bands:
    # the upper half-width is read as one z_w of spread in data space.
    sigma = (df[hi_c].to_numpy(float) - yhat) / z_w
    sigma = np.maximum(sigma, 1e-9)
    df["anomaly_score"] = np.abs(y - yhat) / sigma
    df["is_anomaly"] = df["anomaly_score"] > score_threshold
    out_name = output_table or f"{table}_anomalies"
    catalog.save_table(out_name, df[df["is_anomaly"]])
    return df


def drift_report(
    catalog: DatasetCatalog,
    table: str,
    baseline_version: Optional[str] = None,
    current_version: Optional[str] = None,
    columns: Tuple[str, ...] = ("y", "yhat"),
    slicing_cols: Tuple[str, ...] = (),
    n_bins: int = 10,
    psi_threshold: float = 0.2,
    ks_threshold: float = 0.2,
    output_table: Optional[str] = None,
    df: Optional[pd.DataFrame] = None,
) -> pd.DataFrame:
    """Distribution drift between two versions of a monitored table.

    The third leg of the monitoring triad (profiles, anomalies, drift) the
    reference's WIP monitor gestured at.  The catalog's time travel makes
    the baseline free: compare the current snapshot against an explicit
    ``baseline_version`` (default: the previous version).  Per column and
    per slice it reports:

    * **PSI** (population stability index) over ``n_bins`` quantile bins
      FIXED FROM THE BASELINE (the standard credit-scoring construction):
      <0.1 stable, 0.1-0.25 moderate, >0.25 major by the usual rule of
      thumb; ``drifted`` flags PSI > ``psi_threshold``;
    * **KS**: the Kolmogorov-Smirnov sup-distance between the empirical
      CDFs — consulted for the ``drifted`` flag too (``ks_threshold``),
      because PSI degenerates when the baseline's quantile edges collapse
      on tied values (e.g. intermittent demand that is mostly zeros);
    * segments that VANISH from or are NEW in the current snapshot (slice
      values on one side only) get a row with ``status`` vanished/new and
      ``drifted=True`` — a missing store is the strongest drift there is.

    Returns one row per (column, slice_key, slice_value) incl. ``:all``
    rows, persisted to ``<table>_drift`` (or ``output_table``).  ``df``:
    pre-loaded CURRENT snapshot (a caller sharing one read across
    monitoring passes), only valid when ``current_version`` is None.
    """
    versions = catalog.table_versions(table)
    if baseline_version is None:
        if len(versions) < 2:
            raise ValueError(
                f"{table} has {len(versions)} version(s); drift needs a "
                f"baseline — write a new snapshot or pass baseline_version"
            )
        baseline_version = versions[-2]
    if df is not None and current_version is None:
        cur = df
    else:
        cur = catalog.read_table(table, version=current_version)
    base = catalog.read_table(table, version=baseline_version)

    def _one(col: str, b: np.ndarray, c: np.ndarray) -> Dict:
        b = b[np.isfinite(b)]
        c = c[np.isfinite(c)]
        if b.size < n_bins or c.size < n_bins:
            return {"psi": float("nan"), "ks": float("nan"),
                    "n_baseline": int(b.size), "n_current": int(c.size)}
        # quantile bin edges from the BASELINE; open outer edges
        qs = np.linspace(0, 1, n_bins + 1)[1:-1]
        edges = np.unique(np.quantile(b, qs))
        pb = np.histogram(b, bins=[-np.inf, *edges, np.inf])[0] / b.size
        pc = np.histogram(c, bins=[-np.inf, *edges, np.inf])[0] / c.size
        eps = 1e-4
        pb = np.clip(pb, eps, None)
        pc = np.clip(pc, eps, None)
        pb, pc = pb / pb.sum(), pc / pc.sum()
        psi = float(np.sum((pc - pb) * np.log(pc / pb)))
        # KS over the pooled support
        grid = np.sort(np.concatenate([b, c]))
        cdf_b = np.searchsorted(np.sort(b), grid, side="right") / b.size
        cdf_c = np.searchsorted(np.sort(c), grid, side="right") / c.size
        ks = float(np.abs(cdf_b - cdf_c).max())
        return {"psi": psi, "ks": ks,
                "n_baseline": int(b.size), "n_current": int(c.size)}

    rows = []
    # UNION of slice values: a segment on one side only is itself drift
    slice_plan = [(None, None)] + [
        (sc, v)
        for sc in slicing_cols
        if sc in cur.columns and sc in base.columns
        for v in sorted(set(cur[sc].unique()) | set(base[sc].unique()))
    ]
    for col in columns:
        if col not in cur.columns or col not in base.columns:
            raise ValueError(f"column {col!r} not in both versions of {table}")
        for sc, v in slice_plan:
            bsel = base if sc is None else base[base[sc] == v]
            csel = cur if sc is None else cur[cur[sc] == v]
            nb, nc = len(bsel), len(csel)
            if nb > 0 and nc == 0:
                status, drifted = "vanished", True
            elif nb == 0 and nc > 0:
                status, drifted = "new", True
            else:
                status = "compared"
                drifted = None  # from the stats below
            stats = _one(col, bsel[col].to_numpy(float),
                         csel[col].to_numpy(float))
            if drifted is None:
                psi_hit = (
                    np.isfinite(stats["psi"])
                    and stats["psi"] > psi_threshold
                )
                ks_hit = (
                    np.isfinite(stats["ks"]) and stats["ks"] > ks_threshold
                )
                drifted = bool(psi_hit or ks_hit)
            rows.append({
                "column": col,
                "slice_key": sc or ":all",
                "slice_value": str(v) if sc is not None else ":all",
                "baseline_version": baseline_version,
                "current_version": current_version or versions[-1],
                "status": status,
                **stats,
                "drifted": drifted,
            })
    out = pd.DataFrame(rows)
    catalog.save_table(output_table or f"{table}_drift", out)
    return out


def degradation_report(
    catalog: DatasetCatalog,
    config: MonitorConfig,
    profile: Optional[pd.DataFrame] = None,
    metric: str = "mape",
    granularity: str = "1 week",
    min_windows: int = 6,
    z_threshold: float = 3.0,
    output_table: Optional[str] = None,
) -> pd.DataFrame:
    """Flag slices whose LATEST window's realized accuracy degraded vs
    their own history — the alerting layer over the profile table.

    The profile (:func:`run_monitor`) already tracks per-window quality;
    this closes the loop the reference's WIP monitor gestured at
    ("model quality monitoring"): for every (slice_key, slice_value), the
    trailing windows (all but the latest) form a robust baseline —
    median + MAD — and the latest window is scored one-sided,

        z = (latest - median) / (1.4826 * MAD)

    (one-sided because only WORSE matters: a metric improving is not an
    alert).  ``degraded`` is z > z_threshold; slices with fewer than
    ``min_windows`` windows report ``insufficient_history`` instead of a
    verdict, and a zero-MAD baseline (flat history) falls back to a small
    fraction of the median so a genuinely flat-then-broken slice still
    alerts.  Output persists to ``<table>_degradation``.
    """
    if metric not in ("mape", "smape", "rmse", "bias", "coverage"):
        raise ValueError(f"unknown degradation metric {metric!r}")
    if profile is None:
        profile = run_monitor(catalog, config, df=None)
    if metric not in profile.columns:
        # coverage is only profiled when the table carries interval columns
        raise ValueError(
            f"profile has no {metric!r} column — for 'coverage' the "
            f"monitored table must carry the interval columns "
            f"{config.interval_cols}"
        )
    part = profile[profile.granularity == granularity]
    if part.empty:
        raise ValueError(
            f"profile has no rows at granularity {granularity!r} "
            f"(monitor granularities: {config.granularities})"
        )
    rows = []
    for (skey, sval), grp in part.groupby(["slice_key", "slice_value"]):
        grp = grp.sort_values("window_start")
        vals = grp[metric].to_numpy(dtype=float)
        # orient so LARGER always means worse: coverage degrades down;
        # bias degrades in BOTH directions (a severe under-forecast is as
        # broken as an over-forecast), so its score is the absolute
        # deviation from the baseline median
        if metric == "coverage":
            series = -vals
        elif metric == "bias":
            base_med = float(np.nanmedian(vals[:-1])) if len(vals) > 1 else 0.0
            series = np.abs(vals - base_med)
        else:
            series = vals
        latest_raw = series[-1] if len(series) else np.nan
        base = series[:-1][np.isfinite(series[:-1])]
        n = base.size + int(np.isfinite(latest_raw))
        row = {
            "slice_key": skey,
            "slice_value": sval,
            "metric": metric,
            "granularity": granularity,
            "n_windows": int(n),
            "latest_window": grp["window_start"].iloc[-1],
            "latest_value": float(vals[-1]) if len(vals) else np.nan,
            "baseline_median": float(np.nanmedian(vals[:-1]))
            if len(vals) > 1 else np.nan,
        }
        if not np.isfinite(latest_raw):
            # the latest window was unmeasurable (e.g. rmse NaN'd by a
            # missing prediction): say so — scoring an OLDER window as
            # "latest" would let a broken-and-unmeasurable window pass
            row.update(z_score=np.nan, degraded=False,
                       insufficient_history=False, latest_unmeasured=True)
            rows.append(row)
            continue
        if n < min_windows:
            row.update(z_score=np.nan, degraded=False,
                       insufficient_history=True, latest_unmeasured=False)
            rows.append(row)
            continue
        med = float(np.median(base))
        mad = float(np.median(np.abs(base - med)))
        scale = 1.4826 * mad
        if scale <= 0:
            # flat history: a relative floor keeps z finite and still
            # catches a break (1% of |median|, or epsilon for ~zero bases)
            scale = max(0.01 * abs(med), 1e-9)
        z = (latest_raw - med) / scale
        row.update(
            z_score=float(z),
            degraded=bool(z > z_threshold),
            insufficient_history=False,
            latest_unmeasured=False,
        )
        rows.append(row)
    report = pd.DataFrame(rows)
    out_name = output_table or f"{config.table}_degradation"
    catalog.save_table(out_name, report)
    return report


# ---------------------------------------------------------------------------
# Live process metrics (counters/gauges/histograms + Prometheus exposition)
#
# The table-based monitors above close the loop on MODEL quality, offline.
# The serving path needs the other half of the reference's monitoring story:
# live process telemetry — request counters, queue depth, latency and
# coalesced-batch-size distributions — scraped from the scorer itself
# (serving/server.py's GET /metrics).  These are deliberately tiny,
# dependency-free, thread-safe primitives in the Prometheus data model, not
# a client-library vendoring: the image carries no prometheus_client, and a
# scorer needs exactly counters, gauges and fixed-bucket histograms.
# ---------------------------------------------------------------------------


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral floats render as integers."""
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def escape_label_value(value) -> str:
    """Escape a label VALUE per the text exposition format 0.0.4: backslash,
    double-quote and newline must be escaped inside the quoted value, in
    this order (escaping the escape character first).  Label values are the
    one place arbitrary strings (model families, AOT entry names, span
    kinds) reach the exposition, so un-escaped quotes or newlines would let
    one hostile or merely unlucky name corrupt the whole scrape."""
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP text escaping (format 0.0.4): backslash and newline only —
    a newline in help text would otherwise terminate the comment line and
    inject whatever follows as a sample line."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def render_labels(labels: Dict[str, str]) -> str:
    """``{a="x",b="y"}`` with escaped values; empty dict renders nothing."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing counter (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self, name: str) -> List[str]:
        return [f"{name} {_fmt_value(self.value)}"]

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Settable instantaneous value (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self, name: str) -> List[str]:
        return [f"{name} {_fmt_value(self.value)}"]

    def snapshot(self) -> float:
        return self.value


class LabeledCounter:
    """Counter family keyed by label values (thread-safe).

    The plain :class:`Counter` covers fixed-name telemetry; this is the
    labeled variant for low-cardinality breakdowns (AOT entry × outcome,
    span kinds).  Values render with :func:`escape_label_value`, so family
    members named with quotes/backslashes/newlines cannot corrupt the
    exposition.  Keep label cardinality bounded by construction — every
    distinct label combination is a live time series.
    """

    def __init__(self, label_names: Tuple[str, ...]) -> None:
        if not label_names:
            raise ValueError("labeled counter needs at least one label")
        self._label_names = tuple(label_names)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        if set(labels) != set(self._label_names):
            raise ValueError(
                f"expected labels {self._label_names}, got {sorted(labels)}")
        key = tuple(str(labels[k]) for k in self._label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = tuple(str(labels[k]) for k in self._label_names)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self, name: str) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            name
            + render_labels(dict(zip(self._label_names, key)))
            + f" {_fmt_value(v)}"
            for key, v in items
        ]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            items = sorted(self._values.items())
        return {
            ",".join(f"{k}={v}" for k, v in zip(self._label_names, key)): val
            for key, val in items
        }


class Histogram:
    """Fixed-bucket histogram in the Prometheus cumulative-``le`` model.

    Buckets are upper bounds; every observation also lands in the implicit
    ``+Inf`` bucket, and ``sum``/``count`` ride along so scrapers can derive
    means and quantile estimates.
    """

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._uppers = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._uppers) + 1)  # +1 = +Inf
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self._uppers)
        for j, ub in enumerate(self._uppers):
            if v <= ub:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v

    def _state(self) -> Tuple[List[int], float]:
        """One consistent (counts, sum) pair; every read path derives from a
        single locked snapshot so bucket counts and _sum never tear against
        a concurrent observe()."""
        with self._lock:
            return list(self._counts), self._sum

    @property
    def count(self) -> int:
        counts, _ = self._state()
        return sum(counts)

    @property
    def sum(self) -> float:
        _, total = self._state()
        return total

    def _cumulative(self, counts: List[int]) -> List[Tuple[str, int]]:
        out, running = [], 0
        for ub, c in zip(self._uppers, counts):
            running += c
            out.append((f"{ub:g}", running))
        out.append(("+Inf", running + counts[-1]))
        return out

    def cumulative_buckets(self) -> List[Tuple[str, int]]:
        counts, _ = self._state()
        return self._cumulative(counts)

    def render(self, name: str) -> List[str]:
        counts, total = self._state()
        lines = [
            f'{name}_bucket{{le="{le}"}} {c}'
            for le, c in self._cumulative(counts)
        ]
        lines.append(f"{name}_sum {_fmt_value(total)}")
        lines.append(f"{name}_count {sum(counts)}")
        return lines

    def snapshot(self) -> Dict:
        counts, total = self._state()
        return {
            "count": sum(counts),
            "sum": total,
            "buckets": dict(self._cumulative(counts)),
        }

    def snapshot_quantiles(
        self, qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
    ) -> Dict[float, float]:
        """Quantile estimates from ONE locked (counts, sum) snapshot — the
        shared derivation the SLO evaluator and report scripts use instead
        of re-deriving quantiles from bucket text ad hoc.

        Prometheus ``histogram_quantile`` convention: each quantile reports
        the upper bound of the bucket its rank falls in (no intra-bucket
        interpolation — fixed buckets cannot support it honestly), clamped
        to the highest FINITE bound when the rank lands in +Inf.  An empty
        histogram reports NaN for every level, which no threshold compares
        true against — an SLO on an idle endpoint stays quiet.
        """
        counts, _ = self._state()
        total = sum(counts)
        out: Dict[float, float] = {}
        for q in qs:
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"quantile {q} outside [0, 1]")
            if total == 0:
                out[q] = float("nan")
                continue
            rank = q * total
            running = 0
            value = self._uppers[-1]  # +Inf rank clamps to top finite bound
            for ub, c in zip(self._uppers, counts):
                running += c
                if running >= rank and c:
                    value = ub
                    break
            out[q] = float(value)
        return out


class LabeledGauge:
    """Gauge family keyed by label values (thread-safe) — the settable
    counterpart of :class:`LabeledCounter`, for per-rule/per-family live
    values (SLO burn rates, rolling quality per model family).  Same
    escaping and cardinality caveats as the labeled counter."""

    def __init__(self, label_names: Tuple[str, ...]) -> None:
        if not label_names:
            raise ValueError("labeled gauge needs at least one label")
        self._label_names = tuple(label_names)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict) -> Tuple[str, ...]:
        if set(labels) != set(self._label_names):
            raise ValueError(
                f"expected labels {self._label_names}, got {sorted(labels)}")
        return tuple(str(labels[k]) for k in self._label_names)

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self, name: str) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            name
            + render_labels(dict(zip(self._label_names, key)))
            + f" {_fmt_value(v)}"
            for key, v in items
        ]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            items = sorted(self._values.items())
        return {
            ",".join(f"{k}={v}" for k, v in zip(self._label_names, key)): val
            for key, val in items
        }


class MetricsRegistry:
    """Named metrics + Prometheus text exposition (format 0.0.4).

    One registry per scorer process; ``render_prometheus()`` is what the
    ``GET /metrics`` endpoint returns, ``snapshot()`` is the JSON-friendly
    view tests and in-process consumers use.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, Tuple[str, str, object]] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, kind: str, help_text: str, metric):
        with self._lock:
            if name in self._metrics:
                raise ValueError(f"metric {name!r} already registered")
            self._metrics[name] = (kind, help_text, metric)
        return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(name, "counter", help_text, Counter())

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(name, "gauge", help_text, Gauge())

    def histogram(
        self, name: str, buckets: Tuple[float, ...], help_text: str = ""
    ) -> Histogram:
        return self._register(name, "histogram", help_text, Histogram(buckets))

    def labeled_counter(
        self, name: str, label_names: Tuple[str, ...], help_text: str = ""
    ) -> LabeledCounter:
        return self._register(
            name, "counter", help_text, LabeledCounter(label_names))

    def labeled_gauge(
        self, name: str, label_names: Tuple[str, ...], help_text: str = ""
    ) -> LabeledGauge:
        return self._register(
            name, "gauge", help_text, LabeledGauge(label_names))

    def items(self) -> List[Tuple[str, str, object]]:
        """(name, kind, metric) triples from one locked registry snapshot —
        the public walk the scrape loop uses (the metric objects are
        themselves thread-safe, only the registry dict needs the lock)."""
        with self._lock:
            return [(n, k, m) for n, (k, _, m) in self._metrics.items()]

    def render_prometheus(self) -> str:
        with self._lock:
            items = list(self._metrics.items())
        lines: List[str] = []
        for name, (kind, help_text, metric) in items:
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(metric.render(name))
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict:
        with self._lock:
            items = list(self._metrics.items())
        return {name: metric.snapshot() for name, (_, _, metric) in items}


# ---------------------------------------------------------------------------
# training-pipeline metrics (engine/executor.py)
# ---------------------------------------------------------------------------

#: stage latencies span ~1 ms closures in tests to multi-second artifact
#: serialization in production — log-spaced like the serving buckets
_STAGE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)


class PipelineMetrics:
    """Occupancy instrumentation for the pipelined training executor.

    One registry per process, appended to the serving ``GET /metrics``
    exposition next to the compile-cache registry.  All attribute writes
    happen in ``__init__``; the metric objects are themselves thread-safe,
    so the executor's writer thread and caller thread can observe freely.
    """

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.experiments_total = self.registry.counter(
            "pipeline_experiments_total",
            "experiments submitted to the training executor")
        self.errors_total = self.registry.counter(
            "pipeline_errors_total",
            "experiments whose completion stage raised")
        self.in_flight = self.registry.gauge(
            "pipeline_in_flight",
            "experiments dispatched but not yet completed")
        self.device_idle_fraction = self.registry.gauge(
            "pipeline_device_idle_fraction",
            "fraction of the dispatch window the device sat idle "
            "(lower bound; see docs/pipeline.md)")
        self.stage_seconds = {
            stage: self.registry.histogram(
                f"pipeline_stage_{stage}_seconds", _STAGE_BUCKETS,
                f"wall seconds spent in pipeline stage '{stage}' per "
                f"experiment")
            for stage in ("prep", "dispatch", "pull", "complete")
        }

    def inc_experiments(self) -> None:
        self.experiments_total.inc()

    def inc_errors(self) -> None:
        self.errors_total.inc()

    def set_in_flight(self, value: float) -> None:
        self.in_flight.set(float(value))

    def set_device_idle_fraction(self, value: float) -> None:
        self.device_idle_fraction.set(float(value))

    def observe_stage(self, stage: str, seconds: float) -> None:
        hist = self.stage_seconds.get(stage)
        if hist is not None:
            hist.observe(seconds)


_pipeline_metrics_lock = threading.Lock()
_pipeline_metrics: Optional[PipelineMetrics] = None


def pipeline_metrics() -> PipelineMetrics:
    """Process-wide :class:`PipelineMetrics` singleton (lazy)."""
    global _pipeline_metrics
    with _pipeline_metrics_lock:
        if _pipeline_metrics is None:
            _pipeline_metrics = PipelineMetrics()
        return _pipeline_metrics


class IngestMetrics:
    """Telemetry for the streaming ingest path (``dftpu_ingest_*``).

    One instance per :class:`serving.ingest.IngestRuntime`, its registry
    appended to the serving ``GET /metrics`` exposition.  Same discipline
    as :class:`PipelineMetrics`: attributes are created once here, the
    metric objects themselves are thread-safe, so the HTTP handler
    threads, the WAL follower, and the refit scheduler observe freely.

    Fleet note: ``wal_bytes`` / ``wal_segments`` / ``applied_day`` describe
    SHARED state when replicas converge over one WAL directory — the fleet
    aggregator max-merges them (serving/fleet.aggregate_prometheus) instead
    of summing, or a 3-replica fleet would report its WAL three times over.
    """

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.points_total = self.registry.counter(
            "dftpu_ingest_points_total",
            "observation points accepted into the WAL")
        self.late_points_total = self.registry.counter(
            "dftpu_ingest_late_points_total",
            "points at or before the applied day (history-only until the "
            "next full refit)")
        self.unknown_series_total = self.registry.counter(
            "dftpu_ingest_unknown_series_total",
            "points dropped because their key matches no fitted series")
        self.out_of_range_total = self.registry.counter(
            "dftpu_ingest_out_of_range_total",
            "points dropped before the WAL because their day falls before "
            "the training grid or beyond the max_pending_days horizon")
        self.wal_appends_total = self.registry.counter(
            "dftpu_ingest_wal_appends_total",
            "WAL append batches written (one O_APPEND write each)")
        self.applied_points_total = self.registry.counter(
            "dftpu_ingest_applied_points_total",
            "points applied to model state via batched update dispatches")
        self.refits_total = self.registry.counter(
            "dftpu_ingest_refits_total",
            "background full refits completed and swapped in")
        self.tail_window_refits_total = self.registry.counter(
            "dftpu_ingest_tail_window_refits_total",
            "windowed refits that re-fit only the tail window, reusing "
            "frozen per-window stats for the untouched prefix "
            "(engine.windowed streaming path)")
        self.wal_bytes = self.registry.gauge(
            "dftpu_ingest_wal_bytes",
            "total bytes across WAL segments (shared in fleet mode: "
            "max-merged by the aggregator)")
        self.wal_segments = self.registry.gauge(
            "dftpu_ingest_wal_segments",
            "number of WAL segment files (shared in fleet mode: "
            "max-merged by the aggregator)")
        self.dirty_series = self.registry.gauge(
            "dftpu_ingest_dirty_series",
            "series with pending unapplied points")
        self.pending_days = self.registry.gauge(
            "dftpu_ingest_pending_days",
            "distinct future days waiting in the pending buffer")
        self.applied_day = self.registry.gauge(
            "dftpu_ingest_applied_day",
            "absolute day ordinal the model state is current through "
            "(shared in fleet mode: max-merged by the aggregator)")
        self.refit_backlog = self.registry.gauge(
            "dftpu_ingest_refit_backlog",
            "points applied incrementally since the last full refit")
        self.update_seconds = self.registry.histogram(
            "dftpu_ingest_update_seconds", _STAGE_BUCKETS,
            "wall seconds per batched state-update dispatch")
        self.refit_seconds = self.registry.histogram(
            "dftpu_ingest_refit_seconds", _STAGE_BUCKETS,
            "wall seconds per background full refit (fit + replay + swap)")
        self.ingest_shutdown_stuck_total = self.registry.counter(
            "dftpu_ingest_shutdown_stuck_total",
            "shutdowns where the WAL follower thread outlived its join "
            "timeout and was leaked (daemon) instead of drained")
        self.refit_shutdown_stuck_total = self.registry.counter(
            "dftpu_refit_shutdown_stuck_total",
            "shutdowns where the refit scheduler thread outlived its join "
            "timeout and was leaked (daemon) instead of drained")
