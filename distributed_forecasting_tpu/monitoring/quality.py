"""Forecast-quality monitor: rolling accuracy + calibration from actuals.

The stack can explain one request end to end (``monitoring/trace.py``) and
expose point-in-time process metrics, but nothing watches whether the
forecasts themselves are still any good.  This module closes that loop,
ARIMA_PLUS-style: actuals arrive (the serving ``POST /observe`` endpoint or
a batch script), get aligned against what the model SERVED for those dates
— including the conformal-scaled interval from ``engine/calibrate.py``,
because ``BatchForecaster.predict`` applies ``interval_scale`` to its bands
— and update per-series rolling WAPE / RMSSE / calibration-coverage
accumulators.

Batching contract (the acceptance bar): one ``observe()`` call runs ONE
batched device dispatch for the whole observation set — the forecaster's
own batched ``predict`` plus the elementwise term kernel
(``ops/metrics.quality_terms``) over a dense ``(k, T)`` layout.  No
per-series Python loop anywhere.  Reductions happen as ONE vectorized
float64 host sum so the accumulators are bitwise equal to a NumPy
reference and stable over unbounded observation streams (float32 device
sums are neither — XLA reassociates).

Per-family aggregates and per-series rows land in the
:class:`~distributed_forecasting_tpu.monitoring.store.TimeSeriesStore`
(write OUTSIDE the accumulator lock), and live gauges
(``dftpu_quality_*``) ride the serving ``/metrics`` exposition.

Conf block ``monitoring.quality`` (strict)::

    monitoring:
      quality:
        enabled: true
        max_horizon: 365        # observations beyond day1+this are skipped
        nominal_coverage: 0.0   # 0 -> the model config's interval_width
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

import jax
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import period_ordinals
from distributed_forecasting_tpu.engine.calibrate import config_interval_width
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.ops.metrics import quality_terms
from distributed_forecasting_tpu.utils import get_logger

#: accumulator columns, in the order _terms_to_host returns them
_ACC_FIELDS = ("abs_err", "abs_y", "sq_err", "inside", "n",
               "naive_sq", "naive_n")

_terms_jit = jax.jit(quality_terms)


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """The ``monitoring.quality`` conf block."""

    enabled: bool = False
    max_horizon: int = 365        # bounds the predict grid an observe can force
    nominal_coverage: float = 0.0  # 0 -> config_interval_width(fc.config)

    def __post_init__(self):
        if self.max_horizon < 1:
            raise ValueError("max_horizon must be >= 1")
        if not 0.0 <= self.nominal_coverage < 1.0:
            raise ValueError("nominal_coverage must be in [0, 1)")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "QualityConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            raise ValueError(
                f"unknown monitoring.quality conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


def _pow2(n: int) -> int:
    """Next power of two — the observe dense layout buckets both axes so a
    stream of ragged observation batches compiles O(log^2) term kernels,
    the same policy as the serving request buckets."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _metrics_from_acc(acc: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Accumulator sums -> WAPE/RMSSE/coverage arrays (NaN where the
    denominator is degenerate — same convention as ``ops/metrics``)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        wape = np.where(acc["abs_y"] > 0, acc["abs_err"] / acc["abs_y"],
                        np.nan)
        mse = np.where(acc["n"] > 0, acc["sq_err"] / acc["n"], np.nan)
        naive = np.where(acc["naive_n"] > 0,
                         acc["naive_sq"] / acc["naive_n"], np.nan)
        rmsse = np.where(naive > 0, np.sqrt(mse / naive), np.nan)
        cov = np.where(acc["n"] > 0, acc["inside"] / acc["n"], np.nan)
    return {"wape": wape, "rmsse": rmsse, "coverage": cov}


class QualityMonitor:
    """Rolling per-series forecast quality from arriving actuals.

    Thread-safety: ``_lock`` guards the accumulator arrays (plain numpy
    float64, resized never — sized to the forecaster's series count at
    construction).  The device dispatch, pandas alignment, and store
    append all run OUTSIDE the lock; only the ``np.add.at`` accumulation
    and snapshot reads hold it.
    """

    def __init__(
        self,
        forecaster,
        config: Optional[QualityConfig] = None,
        store=None,
    ):
        self.forecaster = forecaster
        self.config = config or QualityConfig(enabled=True)
        self.store = store
        self.logger = get_logger("QualityMonitor")
        n = int(forecaster.n_series)
        self._lock = threading.Lock()
        self._acc = {f: np.zeros(n, dtype=np.float64) for f in _ACC_FIELDS}
        # key -> accumulator slot, built once (predict guarantees observed
        # keys are trained keys); composites have no top-level key table and
        # grow _extra_index lazily under the lock instead
        self._slot_index: Optional[Dict[tuple, int]] = (
            {tuple(k): i
             for i, k in enumerate(map(tuple, forecaster.keys.tolist()))}
            if hasattr(forecaster, "keys") else None
        )
        self._extra_index: Dict[tuple, int] = {}
        self._nominal = (
            self.config.nominal_coverage
            or config_interval_width(getattr(forecaster, "config", None))
        )
        # quality telemetry registry, appended to the serving /metrics body
        r = MetricsRegistry()
        self.registry = r
        self.observe_requests = r.counter(
            "dftpu_quality_observe_requests_total",
            "POST /observe calls (incl. batch scripts)")
        self.observations_total = r.counter(
            "dftpu_quality_observations_total",
            "actuals scored against served forecasts")
        self.observations_skipped = r.counter(
            "dftpu_quality_observations_skipped_total",
            "actuals dropped: unknown series, unmatched dates, or beyond "
            "max_horizon")
        self.series_observed = r.gauge(
            "dftpu_quality_series_observed",
            "distinct series with at least one scored actual")
        self.family_metrics = r.labeled_gauge(
            "dftpu_quality_metric", ("family", "metric"),
            "rolling forecast quality per model family "
            "(wape | rmsse | coverage)")
        self.nominal_gauge = r.gauge(
            "dftpu_quality_nominal_coverage",
            "the interval width the served bands target "
            "(engine/calibrate.py)")
        self.nominal_gauge.set(self._nominal)

    # -- core ----------------------------------------------------------------
    @property
    def nominal_coverage(self) -> float:
        return float(self._nominal)

    def observe(self, observations: pd.DataFrame,
                on_missing: str = "skip") -> Dict:
        """Score a batch of actuals; returns the per-family summary.

        ``observations``: long frame with the forecaster's key columns,
        ``ds`` (date-like) and ``y``.  Series unknown to the artifact
        follow ``on_missing`` (predict's contract: "skip" drops them,
        "raise" 404s the request); observations whose date falls outside
        the day0..day1+max_horizon grid are counted as skipped.
        """
        fc = self.forecaster
        tracer = get_tracer()
        self.observe_requests.inc()
        key_names = list(fc.key_names)
        need = key_names + ["ds", "y"]
        missing = [c for c in need if c not in observations.columns]
        if missing:
            raise ValueError(f"observations missing column(s) {missing}")
        obs = observations[need].copy()
        obs["ds"] = pd.to_datetime(obs["ds"])
        obs["y"] = pd.to_numeric(obs["y"], errors="coerce")
        n_in = len(obs)
        freq = getattr(fc, "freq", "D")
        # snap to period ordinals: daily feeds align exactly; a coarser
        # grid buckets each date to its period (tensorize's GROUP BY rule)
        obs["_ord"] = period_ordinals(obs["ds"], freq)

        # locked snapshot where the forecaster has one: this runs on HTTP
        # handler threads concurrently with streaming swap_state writers
        if hasattr(fc, "_state_snapshot"):
            day1 = fc._state_snapshot()[1]
        else:  # composite artifacts have no swap path (nor a day1)
            day1 = getattr(fc, "day1", None)
        if day1 is not None:
            horizon = int(np.clip(obs["_ord"].max() - day1, 1,
                                  self.config.max_horizon))
            in_grid = obs["_ord"] <= day1 + self.config.max_horizon
            obs = obs[in_grid]
        else:  # composite artifacts: serve whatever predict covers
            horizon = self.config.max_horizon
        if obs.empty:
            self.observations_skipped.inc(n_in)
            return self.snapshot(series=False)

        with tracer.span("quality.observe", rows=n_in):
            req = obs[key_names].drop_duplicates()
            pred = fc.predict(req, horizon=horizon, include_history=True,
                              on_missing=on_missing)
            pred = pred[key_names + ["ds", "yhat", "yhat_lower",
                                     "yhat_upper"]]
            merged = obs.merge(
                pred.assign(_ord=period_ordinals(pred["ds"], freq))
                    .drop(columns=["ds"]),
                on=key_names + ["_ord"], how="inner")
            scored = self._score(merged, key_names)
        self.observations_total.inc(scored)
        self.observations_skipped.inc(n_in - scored)
        # worst offenders ride both the response and the store, so the
        # quality report can render per-series degradation from history
        summary = self.snapshot(series=True, top=20)
        self._publish(summary)
        return summary

    def _score(self, merged: pd.DataFrame, key_names: List[str]) -> int:
        """Dense layout + ONE device dispatch + float64 host reduction +
        locked accumulation.  Returns the number of scored observations."""
        fc = self.forecaster
        if merged.empty:
            return 0
        merged = merged.sort_values(key_names + ["_ord"], kind="stable")
        sid, uniq = pd.factorize(
            pd.MultiIndex.from_frame(merged[key_names]), sort=False)
        pos = merged.groupby(sid).cumcount().to_numpy()
        k = len(uniq)
        T = int(pos.max()) + 1
        kb, Tb = _pow2(k), max(_pow2(T), 2)

        def dense(col, fill, dtype):
            out = np.full((kb, Tb), fill, dtype=dtype)
            out[sid, pos] = merged[col].to_numpy(dtype=dtype)
            return out

        y = dense("y", np.nan, np.float32)
        yhat = dense("yhat", np.nan, np.float32)
        lo = dense("yhat_lower", 0.0, np.float32)
        hi = dense("yhat_upper", 0.0, np.float32)
        step = dense("_ord", -10, np.int32)  # pad never looks consecutive
        mask = np.zeros((kb, Tb), dtype=bool)
        mask[sid, pos] = True

        terms = _terms_jit(y, yhat, lo, hi, step, mask)  # ONE dispatch
        # vectorized float64 reduction on host: bitwise-stable vs a NumPy
        # reference, and safe for unbounded accumulation (see module doc)
        sums = {
            f: np.sum(np.asarray(terms[f], dtype=np.float64), axis=-1)[:k]
            for f in _ACC_FIELDS
        }
        scored = int(sums["n"].sum())
        # map the k dense rows back to trained-series slots and accumulate;
        # slot resolution for composites mutates _extra_index, so the whole
        # mapping+accumulation step sits under the one lock
        with self._lock:
            if self._slot_index is not None:
                slots = np.asarray([self._slot_index[tuple(u)]
                                    for u in uniq])
            else:  # composites: dense slots per observed series, capped
                idx = self._extra_index
                for u in uniq:
                    idx.setdefault(tuple(u),
                                   len(idx) % self.forecaster.n_series)
                slots = np.asarray([idx[tuple(u)] for u in uniq])
            for f in _ACC_FIELDS:
                np.add.at(self._acc[f], slots, sums[f])
            self.series_observed.set(int(np.count_nonzero(self._acc["n"])))
        return scored

    # -- reads ---------------------------------------------------------------
    def snapshot(self, series: bool = True, top: int = 50) -> Dict:
        """JSON-friendly state for ``/debug/quality`` and the SLO
        evaluator: family-level rolling metrics (+ the worst ``top``
        series by WAPE when ``series``)."""
        with self._lock:
            acc = {f: self._acc[f].copy() for f in _ACC_FIELDS}
        observed = acc["n"] > 0
        fam_acc = {f: np.array([float(acc[f].sum())]) for f in _ACC_FIELDS}
        fam = {m: float(v[0]) for m, v in _metrics_from_acc(fam_acc).items()}
        out = {
            "family": getattr(self.forecaster, "family", "unknown"),
            "n_series": int(self.forecaster.n_series),
            "series_observed": int(np.count_nonzero(observed)),
            "observations": int(acc["n"].sum()),
            "nominal_coverage": self.nominal_coverage,
            "metrics": fam,
        }
        if series and observed.any() and hasattr(self.forecaster, "keys"):
            per = _metrics_from_acc(acc)
            wape_rank = np.where(np.isnan(per["wape"]), -np.inf, per["wape"])
            order = np.argsort(-wape_rank)[: int(top)]
            keys = self.forecaster.keys
            key_names = list(self.forecaster.key_names)
            rows = []
            for i in order:
                if not observed[i]:
                    continue
                rows.append({
                    **dict(zip(key_names,
                               (int(v) for v in keys[i]))),
                    "n": int(acc["n"][i]),
                    "wape": _nanround(per["wape"][i]),
                    "rmsse": _nanround(per["rmsse"][i]),
                    "coverage": _nanround(per["coverage"][i]),
                })
            out["worst_series"] = rows
        return out

    def coverage(self) -> float:
        """Lifetime family-level coverage (NaN before any observation) —
        the SLI the coverage SLO rule reads."""
        with self._lock:
            n = float(self._acc["n"].sum())
            inside = float(self._acc["inside"].sum())
        return inside / n if n > 0 else float("nan")

    # -- publication ---------------------------------------------------------
    def _publish(self, summary: Dict) -> None:
        """Gauges + store rows from a snapshot; all I/O outside the lock."""
        fam = summary["family"]
        for metric, value in summary["metrics"].items():
            if value == value:  # skip NaN: a gauge must not lie with 0
                self.family_metrics.set(value, family=fam, metric=metric)
        if self.store is None:
            return
        at = time.time()  # dflint: disable=nondeterminism — store rows are wall-clock telemetry
        points = [{
            "ts": at, "name": f"dftpu_quality_{metric}",
            "labels": {"family": fam}, "value": value,
        } for metric, value in summary["metrics"].items() if value == value]
        points.append({
            "ts": at, "name": "dftpu_quality_observations",
            "labels": {"family": fam}, "value": summary["observations"]})
        for row in summary.get("worst_series", []):
            labels = {"family": fam}
            labels.update({k: str(v) for k, v in row.items()
                           if k not in ("n", "wape", "rmsse", "coverage")})
            for metric in ("wape", "rmsse", "coverage"):
                if row.get(metric) is not None:
                    points.append({
                        "ts": at, "name": f"dftpu_quality_series_{metric}",
                        "labels": labels, "value": row[metric]})
        try:
            # the store synchronizes internally (one atomic O_APPEND write);
            # holding the accumulator lock across disk I/O is the exact
            # anti-pattern the blocking-under-lock rule exists to catch
            self.store.append(points)  # dflint: disable=unlocked-shared-state — TimeSeriesStore is internally synchronized; deliberately outside _lock
        except OSError:
            self.logger.exception("quality store append failed")


def _nanround(v: float, nd: int = 6) -> Optional[float]:
    v = float(v)
    return None if v != v else round(v, nd)


class QualityRuntime:
    """The wired quality stack one serving process owns: monitor + store +
    scrape loop + SLO evaluator, with one lifecycle and one exposition.

    Built by :func:`build_quality_runtime`; the server mounts
    ``runtime.observe`` behind ``POST /observe``, appends
    ``runtime.render_metrics()`` to the ``/metrics`` body, serves
    ``runtime.snapshot()`` at ``/debug/quality``, and calls
    ``start()``/``stop()`` around its own lifetime.
    """

    def __init__(self, monitor=None, store=None, scrape=None, slo=None):
        self.monitor = monitor
        self.store = store
        self.scrape = scrape
        self.slo = slo

    def observe(self, observations: pd.DataFrame,
                on_missing: str = "skip") -> Dict:
        if self.monitor is None:
            raise RuntimeError("quality monitoring is not enabled "
                               "(monitoring.quality.enabled)")
        return self.monitor.observe(observations, on_missing=on_missing)

    def render_metrics(self) -> str:
        parts = []
        if self.monitor is not None:
            parts.append(self.monitor.registry.render_prometheus())
        if self.slo is not None:
            parts.append(self.slo.registry.render_prometheus())
        return "".join(parts)

    def snapshot(self) -> Dict:
        out: Dict = {}
        if self.monitor is not None:
            out["quality"] = self.monitor.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.store is not None:
            out["store"] = self.store.stats()
        return out

    def attach_server_metrics(self, serving_metrics) -> None:
        """Late-bind the serving telemetry the runtime cannot see at build
        time (the latency histogram the latency SLO reads, and the serving
        registry the scrape loop persists) — called by ``ForecastServer``
        before ``start()``."""
        if self.slo is not None:
            self.slo.bind_latency(serving_metrics.latency)
        if self.scrape is not None:
            self.scrape.add_source({}, lambda: serving_metrics.registry)

    def start(self) -> None:
        if self.scrape is not None:
            self.scrape.start()
        if self.slo is not None:
            self.slo.start()

    def stop(self) -> None:
        if self.slo is not None:
            self.slo.stop()
        if self.scrape is not None:
            self.scrape.stop(final_scrape=True)


def build_quality_runtime(
    conf: Optional[dict],
    forecaster,
    latency_histogram=None,
    extra_registries=None,
    tracking_root: Optional[str] = None,
    default_store_dir: Optional[str] = None,
) -> Optional[QualityRuntime]:
    """Wire a :class:`QualityRuntime` from the top-level ``monitoring:``
    conf block; None when nothing in it is enabled.

    ``extra_registries``: ``(labels, registry_fn)`` pairs the scrape loop
    should persist alongside the quality registry (the serving registry,
    compile-cache, pipeline metrics).  ``tracking_root`` feeds the
    staleness SLO; ``default_store_dir`` backs an empty
    ``quality_store.directory`` (replicas pass a port-suffixed path so two
    processes never share an append cursor).
    """
    from distributed_forecasting_tpu.monitoring.slo import (
        SLOConfig,
        SLOEvaluator,
        latest_run_timestamp,
    )
    from distributed_forecasting_tpu.monitoring.store import (
        QualityStoreConfig,
        ScrapeLoop,
        TimeSeriesStore,
    )

    from distributed_forecasting_tpu.monitoring.cost import (
        CostConfig,
        configure_cost,
        cost_metrics,
    )

    conf = dict(conf or {})
    known = {"quality", "quality_store", "slo", "tracking_root", "cost"}
    unknown = set(conf) - known
    if unknown:
        raise ValueError(
            f"unknown monitoring conf key(s) {sorted(unknown)}; "
            f"valid: {sorted(known)}")
    # conf wins over the caller's default: tasks inject the env's tracking
    # root, but an explicit monitoring.tracking_root pins the staleness SLO
    # at a different registry (e.g. the production one from a canary)
    tracking_root = conf.get("tracking_root") or tracking_root
    qconf = QualityConfig.from_conf(conf.get("quality"))
    sconf = QualityStoreConfig.from_conf(conf.get("quality_store"))
    slo_conf = SLOConfig.from_conf(conf.get("slo"))
    # the cost layer applies even when nothing below creates a runtime:
    # attribution counters and /debug/cost work store-less
    cconf = CostConfig.from_conf(conf.get("cost"))
    configure_cost(cconf)
    if not (qconf.enabled or sconf.enabled or slo_conf.enabled):
        return None
    if slo_conf.enabled and not sconf.enabled:
        raise ValueError(
            "monitoring.slo needs monitoring.quality_store.enabled: "
            "burn-rate windows are means over STORED good/bad samples")

    store = None
    scrape = None
    if sconf.enabled:
        directory = sconf.directory or default_store_dir
        if not directory:
            raise ValueError(
                "monitoring.quality_store.directory is empty and the "
                "caller supplied no default root")
        store = TimeSeriesStore(
            directory, retention_s=sconf.retention_s,
            max_segment_bytes=sconf.max_segment_bytes)

    monitor = None
    if qconf.enabled:
        monitor = QualityMonitor(forecaster, config=qconf, store=store)

    slo = None
    if slo_conf.enabled:
        slo = SLOEvaluator(
            slo_conf, store,
            latency_histogram=latency_histogram,
            coverage_fn=(monitor.coverage if monitor is not None else None),
            nominal_fn=(
                (lambda: monitor.nominal_coverage)
                if monitor is not None else None),
            staleness_fn=(
                (lambda: latest_run_timestamp(tracking_root))
                if tracking_root else None),
        )

    if store is not None:
        sources = list(extra_registries or [])
        if monitor is not None:
            sources.append(({}, lambda: monitor.registry))
        if slo is not None:
            sources.append(({}, lambda: slo.registry))
        if cconf.enabled:
            # host-RSS / device-memory watermarks refresh on the scrape
            # tick, so the store keeps queryable capacity history
            def _cost_source():
                cm = cost_metrics()
                cm.sample_watermarks()
                return cm.registry

            sources.append(({}, _cost_source))
        scrape = ScrapeLoop(
            store, sources,
            scrape_interval_s=sconf.scrape_interval_s,
            compact_interval_s=sconf.compact_interval_s)

    return QualityRuntime(monitor=monitor, store=store, scrape=scrape,
                          slo=slo)
