"""Append-only on-disk time-series store: `/metrics` gains history.

Every metric surface so far is point-in-time — a scrape of ``GET /metrics``
says what the counters read NOW, and the moment the process restarts the
story is gone.  The quality/SLO layer needs history: burn rates are window
averages, degradation tables compare the latest window against the past,
and a post-mortem wants the coverage curve AROUND the incident.  This
module is the smallest store that serves those reads:

  * :class:`TimeSeriesStore` — points ``(ts, name, labels, value)`` as JSON
    lines in numbered segment files.  Appends are a single
    ``os.write(O_APPEND)`` of whole lines (atomic on POSIX regular files),
    so concurrent writers never interleave mid-record and the store's lock
    only ever guards in-memory segment bookkeeping — NO file I/O happens
    under it (the blocking-under-lock discipline ``dflint`` enforces;
    serving/fleet.py's supervisor set the pattern).
  * retention + compaction — ``compact()`` rewrites SEALED segments (never
    the live append target) dropping points older than ``retention_s``,
    via write-tmp-then-``os.replace`` so a crash mid-compaction loses
    nothing.
  * :class:`ScrapeLoop` — a background thread that snapshots
    ``MetricsRegistry`` objects (their own internal locks, held only for
    the in-memory copy) and appends the flattened samples OUTSIDE any lock:
    counters/gauges as-is, histograms as ``_count``/``_sum`` plus
    p50/p95/p99 from ``Histogram.snapshot_quantiles()``.

Conf block ``monitoring.quality_store`` (strict — unknown keys raise, the
``FleetConfig.from_conf`` convention)::

    monitoring:
      quality_store:
        enabled: true
        directory: null          # default <env.root>/quality_store
        retention_s: 604800      # 7 days of history
        compact_interval_s: 3600
        scrape_interval_s: 30
        max_segment_bytes: 4194304
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.utils import get_logger

_SEG_RE = re.compile(r"^seg-(\d{8})\.jsonl$")


# -- segment-file machinery (module level: shared with serving/ingest's WAL) --

def segment_path(directory: str, index: int) -> str:
    """Path of numbered segment ``index`` under ``directory``."""
    return os.path.join(directory, f"seg-{index:08d}.jsonl")


def segment_indices(directory: str) -> List[int]:
    """Sorted indices of the on-disk ``seg-NNNNNNNN.jsonl`` files."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def read_segments_from(
    directory: str, cursor: Optional[Dict[int, int]] = None,
) -> Tuple[List[str], Dict[int, int]]:
    """Follower read: every COMPLETE line appended past ``cursor``.

    ``cursor`` maps segment index -> consumed byte offset; the returned
    cursor is the input advanced past every fully ``\\n``-terminated line
    read this poll.  A torn tail (a writer's ``os.write`` still in flight,
    or a crash mid-write) is left unconsumed — the next poll re-reads it
    once the newline lands — so a follower never sees a partial record.
    This is the replay half of the WAL contract (serving/ingest): appends
    are single ``O_APPEND`` writes of whole lines, reads consume whole
    lines, and the pair is torn-line tolerant end to end.
    """
    failpoint("wal.read")
    new_cursor = dict(cursor or {})
    lines: List[str] = []
    for idx in segment_indices(directory):
        path = segment_path(directory, idx)
        offset = new_cursor.get(idx, 0)
        try:
            if os.path.getsize(path) <= offset:
                continue
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            continue  # unlinked between listdir and open
        end = chunk.rfind(b"\n")
        if end < 0:
            continue  # only a torn tail so far; retry next poll
        complete = chunk[:end + 1]
        new_cursor[idx] = offset + len(complete)
        for raw in complete.split(b"\n"):
            if raw.strip():
                lines.append(raw.decode("utf-8", "replace"))
    return lines, new_cursor


@dataclasses.dataclass(frozen=True)
class QualityStoreConfig:
    """The ``monitoring.quality_store`` conf block."""

    enabled: bool = False
    directory: str = ""              # "" -> caller supplies a default root
    retention_s: float = 604800.0    # 7 days
    compact_interval_s: float = 3600.0
    scrape_interval_s: float = 30.0
    max_segment_bytes: int = 4194304

    def __post_init__(self):
        if self.retention_s <= 0:
            raise ValueError("retention_s must be > 0")
        if self.scrape_interval_s <= 0:
            raise ValueError("scrape_interval_s must be > 0")
        if self.compact_interval_s <= 0:
            raise ValueError("compact_interval_s must be > 0")
        if self.max_segment_bytes < 1024:
            raise ValueError("max_segment_bytes must be >= 1024")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "QualityStoreConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like retension_s must not silently disable retention
            raise ValueError(
                f"unknown monitoring.quality_store conf key(s) "
                f"{sorted(unknown)}; valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


class TimeSeriesStore:
    """Append-only JSONL segments with retention-driven compaction.

    Thread-safety contract: ``_lock`` guards ONLY the in-memory segment
    cursor (``_seg``, ``_seg_bytes``) and the compaction flag; every file
    operation — append, query read, compaction rewrite — runs outside it.
    Appends are safe concurrently because each is one ``os.write`` with
    ``O_APPEND``; compaction is safe concurrently with appends because it
    only touches segments strictly below the live cursor.
    """

    def __init__(self, directory: str,
                 retention_s: float = 604800.0,
                 max_segment_bytes: int = 4194304):
        if retention_s <= 0:
            raise ValueError("retention_s must be > 0")
        self.directory = directory
        self.retention_s = float(retention_s)
        self.max_segment_bytes = int(max_segment_bytes)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._compacting = False
        segs = self._segment_indices()
        self._seg = (segs[-1] if segs else 1)
        path = self._seg_path(self._seg)
        self._seg_bytes = os.path.getsize(path) if os.path.exists(path) else 0

    # -- layout (delegates to the module-level segment machinery) ------------
    def _seg_path(self, index: int) -> str:
        return segment_path(self.directory, index)

    def _segment_indices(self) -> List[int]:
        return segment_indices(self.directory)

    # -- writes --------------------------------------------------------------
    def append(self, points: List[Dict]) -> int:
        """Append ``{"ts", "name", "labels", "value"}`` dicts; returns the
        number written.  One serialized payload, one atomic ``os.write``."""
        if not points:
            return 0
        payload = "".join(
            json.dumps({
                "ts": float(p["ts"]),
                "name": str(p["name"]),
                "labels": dict(p.get("labels") or {}),
                "value": float(p["value"]),
            }, separators=(",", ":")) + "\n"
            for p in points
        ).encode()
        with self._lock:
            # cursor bookkeeping only — the write itself happens below,
            # outside the critical section (snapshot-then-write)
            if self._seg_bytes >= self.max_segment_bytes:
                self._seg += 1
                self._seg_bytes = 0
            path = self._seg_path(self._seg)
            self._seg_bytes += len(payload)
        failpoint("store.append")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
        return len(points)

    # -- reads ---------------------------------------------------------------
    def query(
        self,
        name: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> List[Dict]:
        """Time-ordered points matching the filters.  ``labels`` is a
        SUBSET match (every given pair must be present).  Malformed lines
        (a crash mid-``os.write`` can truncate at most the final line of a
        segment) are skipped, not raised — history must stay readable."""
        out: List[Dict] = []
        for idx in self._segment_indices():
            path = self._seg_path(idx)
            try:
                with open(path) as f:
                    text = f.read()
            except OSError:
                continue  # compaction unlinked it between listdir and open
            for line in text.splitlines():
                if not line.strip():
                    continue
                try:
                    p = json.loads(line)
                    ts = float(p["ts"])
                except (ValueError, TypeError, KeyError):
                    continue
                if name is not None and p.get("name") != name:
                    continue
                if since is not None and ts < since:
                    continue
                if until is not None and ts > until:
                    continue
                if labels:
                    have = p.get("labels") or {}
                    if any(have.get(k) != v for k, v in labels.items()):
                        continue
                out.append(p)
        out.sort(key=lambda p: p["ts"])
        return out

    def names(self) -> List[str]:
        return sorted({p["name"] for p in self.query()})

    # -- compaction ----------------------------------------------------------
    def compact(self, now: Optional[float] = None) -> int:
        """Drop points older than ``retention_s`` from SEALED segments and
        merge the survivors into the lowest sealed segment; returns points
        dropped.  The live append segment is never touched, so appends
        proceed concurrently; a second concurrent compact() is a no-op."""
        with self._lock:
            if self._compacting:
                return 0
            self._compacting = True
            live = self._seg
        try:
            if now is None:
                now = time.time()  # dflint: disable=nondeterminism — retention horizon is wall-clock by definition
            floor = now - self.retention_s
            sealed = [i for i in self._segment_indices() if i < live]
            if not sealed:
                return 0
            kept_lines: List[str] = []
            dropped = 0
            for idx in sealed:
                try:
                    with open(self._seg_path(idx)) as f:
                        text = f.read()
                except OSError:
                    continue
                for line in text.splitlines():
                    if not line.strip():
                        continue
                    try:
                        ts = float(json.loads(line)["ts"])
                    except (ValueError, TypeError, KeyError):
                        dropped += 1  # truncated tail of a crashed write
                        continue
                    if ts >= floor:
                        kept_lines.append(line)
                    else:
                        dropped += 1
            target = self._seg_path(sealed[0])
            tmp = target + ".tmp"
            with open(tmp, "w") as f:
                f.write("".join(ln + "\n" for ln in kept_lines))
            os.replace(tmp, target)  # crash-safe: old data until the rename
            for idx in sealed[1:]:
                try:
                    os.remove(self._seg_path(idx))
                except OSError:
                    pass
            if not kept_lines:
                try:
                    os.remove(target)
                except OSError:
                    pass
            return dropped
        finally:
            with self._lock:
                self._compacting = False

    def stats(self) -> Dict:
        segs = self._segment_indices()
        return {
            "directory": self.directory,
            "segments": len(segs),
            "bytes": sum(
                os.path.getsize(self._seg_path(i))
                for i in segs if os.path.exists(self._seg_path(i))
            ),
            "retention_s": self.retention_s,
        }


def flatten_registry_snapshot(
    registry, at: float, prefix_labels: Optional[Dict[str, str]] = None
) -> List[Dict]:
    """One ``MetricsRegistry`` -> flat store points, shared by the scrape
    loop and tests.  Histograms flatten to ``_count``/``_sum`` plus
    ``_p50/_p95/_p99`` (from :meth:`Histogram.snapshot_quantiles`, one
    locked snapshot per histogram); labeled families carry their label
    string parsed back into the point's labels."""
    from distributed_forecasting_tpu.monitoring.monitor import (
        Histogram,
        LabeledCounter,
        LabeledGauge,
    )

    base = dict(prefix_labels or {})
    points: List[Dict] = []
    for name, _, metric in registry.items():
        if isinstance(metric, Histogram):
            snap = metric.snapshot()
            qs = metric.snapshot_quantiles((0.5, 0.95, 0.99))
            points.append({"ts": at, "name": f"{name}_count",
                           "labels": base, "value": snap["count"]})
            points.append({"ts": at, "name": f"{name}_sum",
                           "labels": base, "value": snap["sum"]})
            for q, v in qs.items():
                if v == v:  # NaN (empty histogram) has no point to store
                    points.append({
                        "ts": at, "name": f"{name}_p{int(round(q * 100))}",
                        "labels": base, "value": v})
        elif isinstance(metric, (LabeledCounter, LabeledGauge)):
            for label_str, v in metric.snapshot().items():
                labels = dict(base)
                for part in label_str.split(","):
                    k, _, val = part.partition("=")
                    labels[k] = val
                points.append({"ts": at, "name": name,
                               "labels": labels, "value": v})
        else:
            points.append({"ts": at, "name": name,
                           "labels": base, "value": metric.snapshot()})
    return points


class ScrapeLoop:
    """Background thread feeding the store from live registries.

    ``sources``: ``(labels, registry_fn)`` pairs — the callable indirection
    lets a source registry appear lazily (e.g. the compile-cache registry
    materializes on first use).  Each tick snapshots every registry (their
    own locks, in-memory only) and THEN appends the batch to disk, so no
    metric lock is ever held across file I/O; compaction piggybacks on the
    same thread at ``compact_interval_s``.
    """

    def __init__(
        self,
        store: TimeSeriesStore,
        sources: List[Tuple[Dict[str, str], Callable[[], object]]],
        scrape_interval_s: float = 30.0,
        compact_interval_s: float = 3600.0,
    ):
        self._store = store
        self._sources = list(sources)
        self._interval = float(scrape_interval_s)
        self._compact_interval = float(compact_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_compact = 0.0
        self.logger = get_logger("ScrapeLoop")

    def add_source(self, labels: Dict[str, str],
                   registry_fn: Callable[[], object]) -> None:
        """Register a late-appearing registry (e.g. the serving metrics
        that only exist once the server constructs) — call before
        ``start()``."""
        self._sources.append((dict(labels), registry_fn))

    def scrape_once(self, now: Optional[float] = None) -> int:
        """One snapshot-then-write pass; returns points written."""
        if now is None:
            now = time.time()  # dflint: disable=nondeterminism — store rows are wall-clock telemetry, not numerics
        points: List[Dict] = []
        for labels, registry_fn in self._sources:
            try:
                registry = registry_fn()
            except Exception:  # noqa: BLE001 — one dead source must not stop the scrape
                self.logger.exception("scrape source failed")
                continue
            if registry is not None:
                points.extend(
                    flatten_registry_snapshot(registry, now, labels))
        written = self._store.append(points)
        if now - self._last_compact >= self._compact_interval:
            self._last_compact = now
            dropped = self._store.compact(now)
            if dropped:
                self.logger.info("compaction dropped %d point(s)", dropped)
        return written

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must outlive one bad tick
                self.logger.exception("scrape tick failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="quality-scrape", daemon=True)
        self._thread.start()

    def stop(self, final_scrape: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_scrape:
            # flush the last window so short-lived processes (tests, the
            # CI smoke) leave their history behind
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001
                self.logger.exception("final scrape failed")
