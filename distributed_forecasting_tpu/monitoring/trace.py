"""End-to-end tracing: correlated spans + flight recorder + profiler hooks.

The serving and training paths are metered in aggregate (Prometheus
counters/histograms on ``GET /metrics``), but aggregates cannot answer the
operational question ARIMA_PLUS (arXiv 2510.24452) treats as table stakes
for large-scale forecasting: when ONE request is slow or ONE experiment
stalls, *where did the time go* — batcher queue?  AOT miss?  stage-C writer
backlog?  This module is the per-request decomposition layer:

* :class:`Tracer` — a thread-safe span API.  ``with tracer.span("name",
  k=v):`` opens a timed span correlated to the enclosing one via a
  per-thread context stack; ``tracer.context(ctx)`` adopts a request's
  :class:`TraceContext` on another thread (the batcher's scheduler thread,
  the executor's writer thread), so one trace id follows a request from the
  HTTP handler through the merged device dispatch;
* :class:`FlightRecorder` — a bounded ring buffer of the most recent
  completed spans, always cheap to append to (one short lock, no I/O), so
  the last seconds of system history are dumpable after the fact — slow
  requests and 5xx responses trigger :func:`dump_flight_recorder`;
* exporters — a streaming JSONL event log (``jsonl_path``, OFF by default;
  writes happen on a dedicated writer thread, never under a lock) and a
  Chrome-trace/Perfetto JSON rendering (:func:`to_chrome_trace`,
  ``chrome://tracing``- and https://ui.perfetto.dev-loadable, one lane per
  thread);
* device correlation — :func:`device_annotation` wraps
  ``jax.profiler.TraceAnnotation`` so host spans appear as named regions on
  the device timeline of a profiler capture, and :class:`ProfilerSession`
  runs an on-demand, single-flight programmatic ``jax.profiler`` trace
  (the server's ``/debug/profile?seconds=N`` endpoint).

Span timestamps are **monotonic** (``time.monotonic()`` — the one trace
clock, shared with the batcher's queue timestamps) so cross-thread span
arithmetic is meaningful; wall-clock time appears only in dump file names
and metadata, never in span math.  The module is import-light (stdlib only;
jax is imported lazily and only for profiler features), and the span fast
path takes no lock across any I/O — dflint's blocking-under-lock rule runs
over this file like any other.

Conf (``serving.tracing``, parsed by ``tasks/serve.py``)::

    tracing:
      enabled: true            # span recording into the flight recorder
      ring_size: 4096          # flight-recorder capacity (completed spans)
      jsonl_path: null         # streaming JSONL export (off by default)
      dump_dir: null           # auto flight-recorder dumps on 5xx/timeouts
      debug_endpoints: false   # /debug/trace + /debug/profile
      profile_dir: null        # jax.profiler capture root for /debug/profile
      max_profile_seconds: 60

Env activation for conf-less process trees (bench children, CI smoke):
``DFTPU_TRACE_DIR=<dir>`` + :func:`enable_from_env` — JSONL lands in
``<dir>/trace.jsonl``, auto-dumps and profiler captures under ``<dir>``.
"""

from __future__ import annotations

import dataclasses
import datetime
import itertools
import json
import os
import queue
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: THE trace clock.  Everything that feeds span start/end times must read
#: this clock (the batcher's ``enqueued_at`` does), so explicitly-timed
#: spans (queue waits) line up with context-manager spans on one timeline.
clock = time.monotonic

_span_ids = itertools.count(1)
_dump_ids = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique, collision-safe trace id (16 hex chars)."""
    return uuid.uuid4().hex[:16]


class TraceContext(Tuple):
    """Immutable (trace_id, span_id) pair — what crosses thread boundaries."""

    __slots__ = ()

    def __new__(cls, trace_id: str, span_id: Optional[str]):
        return super().__new__(cls, (trace_id, span_id))

    @property
    def trace_id(self) -> str:
        return self[0]

    @property
    def span_id(self) -> Optional[str]:
        return self[1]


@dataclasses.dataclass
class SpanRecord:
    """One completed span: the unit the recorder stores and exporters emit."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float            # trace-clock seconds (time.monotonic)
    end: float
    thread_id: int
    thread_name: str
    attrs: Dict[str, Any]
    status: str = "ok"

    @property
    def duration_s(self) -> float:
        return self.end - self.start

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_ms": round(1e3 * (self.end - self.start), 4),
            "thread_id": self.thread_id,
            "thread": self.thread_name,
            "status": self.status,
            "attrs": self.attrs,
        }


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """The ``serving.tracing`` conf block (tasks/serve.py)."""

    enabled: bool = True
    ring_size: int = 4096
    jsonl_path: Optional[str] = None
    dump_dir: Optional[str] = None
    debug_endpoints: bool = False
    profile_dir: Optional[str] = None
    max_profile_seconds: float = 60.0

    def __post_init__(self):
        if self.ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {self.ring_size}")
        if self.max_profile_seconds <= 0:
            raise ValueError(
                f"max_profile_seconds must be > 0, got "
                f"{self.max_profile_seconds}")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "TraceConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like dumpdir must not silently disable auto-dumps
            raise ValueError(
                f"unknown tracing conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        return cls(
            enabled=bool(conf.get("enabled", True)),
            ring_size=int(conf.get("ring_size", 4096)),
            jsonl_path=conf.get("jsonl_path"),
            dump_dir=conf.get("dump_dir"),
            debug_endpoints=bool(conf.get("debug_endpoints", False)),
            profile_dir=conf.get("profile_dir"),
            max_profile_seconds=float(conf.get("max_profile_seconds", 60.0)),
        )


class FlightRecorder:
    """Bounded ring buffer of the most recent completed spans.

    Append is one short lock around a ``deque`` push — never I/O — so it is
    safe on every hot path.  ``snapshot()`` copies under the lock and all
    serialization happens on the copy, outside it.
    """

    def __init__(self, ring_size: int = 4096):
        self._ring: deque = deque(maxlen=int(ring_size))
        self._lock = threading.Lock()

    def record(self, span: SpanRecord) -> None:
        with self._lock:
            self._ring.append(span)

    def snapshot(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_JSONL_STOP = object()


class _JsonlWriter:
    """Streaming JSONL exporter: spans go through an unbounded queue to one
    daemon writer thread, which owns the file handle — producers never touch
    the filesystem (and never block: the queue is unbounded, so a slow disk
    backs up memory, not the serving path)."""

    def __init__(self, path: str):
        self.path = path
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="dftpu-trace-jsonl", daemon=True)
        self._thread.start()

    def submit(self, span: SpanRecord) -> None:
        self._q.put(span)

    def _run(self) -> None:
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with open(self.path, "a") as f:
            while True:
                item = self._q.get()
                if item is _JSONL_STOP:
                    f.flush()
                    return
                f.write(json.dumps(item.to_json()) + "\n")

    def close(self, timeout: float = 5.0) -> None:
        self._q.put(_JSONL_STOP)
        self._thread.join(timeout)


class _NoopSpan:
    """Shared do-nothing span: the entire disabled-tracing fast path."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class Span:
    """A live span: context manager that records itself when it closes."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "start", "status")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{next(_span_ids):x}"
        self.parent_id = parent_id
        self.attrs = attrs
        self.start = 0.0
        self.status = "ok"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        self.start = clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end = clock()
        self._tracer._pop(self)
        if exc_type is not None:
            self.status = f"error:{exc_type.__name__}"
        t = threading.current_thread()
        self._tracer._finish(SpanRecord(
            name=self.name,
            trace_id=self.trace_id,
            span_id=self.span_id,
            parent_id=self.parent_id,
            start=self.start,
            end=end,
            thread_id=t.ident or 0,
            thread_name=t.name,
            attrs=self.attrs,
            status=self.status,
        ))
        return False


class _ContextFrame:
    """Adopting another thread's TraceContext: pushes a parent-only frame."""

    __slots__ = ("_tracer", "_ctx")

    def __init__(self, tracer: "Tracer", ctx: Optional[TraceContext]):
        self._tracer = tracer
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        if self._ctx is not None:
            self._tracer._push(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> bool:
        if self._ctx is not None:
            self._tracer._pop(self._ctx)
        return False


class Tracer:
    """Thread-safe span factory + flight recorder + optional JSONL export.

    All cross-thread state lives in the recorder and the exporter queue;
    span nesting is a per-thread stack (``threading.local``), so opening
    and closing spans takes no shared lock at all — only the recorder
    append at close does, and that lock never covers I/O.
    """

    def __init__(self, config: Optional[TraceConfig] = None):
        self.config = config if config is not None else TraceConfig()
        self.recorder = FlightRecorder(self.config.ring_size)
        self._local = threading.local()
        self._exporter = (
            _JsonlWriter(self.config.jsonl_path)
            if (self.config.enabled and self.config.jsonl_path) else None
        )
        self.profiler = ProfilerSession(
            self.config.profile_dir,
            max_seconds=self.config.max_profile_seconds,
        )

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    # -- per-thread context stack -----------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def _push(self, frame) -> None:
        self._stack().append(frame)

    def _pop(self, frame) -> None:
        stack = self._stack()
        # tolerate exotic unwind orders (generators closing late): remove
        # the frame wherever it sits instead of corrupting the stack
        if stack and stack[-1] is frame:
            stack.pop()
        elif frame in stack:
            stack.remove(frame)

    def current(self) -> Optional[TraceContext]:
        """The calling thread's (trace_id, span_id) — what ``submit``-style
        handoffs capture and the receiving thread adopts via ``context``."""
        stack = self._stack()
        if not stack:
            return None
        top = stack[-1]
        if isinstance(top, TraceContext):
            return top
        return TraceContext(top.trace_id, top.span_id)

    def context(self, ctx: Optional[TraceContext]) -> _ContextFrame:
        """Adopt ``ctx`` as the calling thread's current trace context for
        the duration of the ``with`` block (no-op for ``ctx=None``)."""
        if not self.config.enabled:
            return _ContextFrame(self, None)
        return _ContextFrame(self, ctx)

    # -- spans --------------------------------------------------------------

    def span(self, name: str, ctx: Optional[TraceContext] = None, **attrs):
        """Open a span.  Parent/trace id come from ``ctx`` when given, else
        from the thread's current context; a fresh trace id is minted when
        neither exists (the span becomes a root)."""
        if not self.config.enabled:
            return _NOOP_SPAN
        parent = ctx if ctx is not None else self.current()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id, attrs)
        return Span(self, name, new_trace_id(), None, attrs)

    def root_span(self, name: str, trace_id: Optional[str] = None, **attrs):
        """Open a root span with an explicit (e.g. header-supplied) trace
        id — the HTTP handler's entry point."""
        if not self.config.enabled:
            return _NOOP_SPAN
        return Span(self, name, trace_id or new_trace_id(), None, attrs)

    def record_span(self, name: str, start: float, end: float,
                    ctx: Optional[TraceContext] = None, **attrs) -> None:
        """Record an explicitly-timed span (both endpoints already read from
        :data:`clock`) — queue waits, post-hoc stage timings."""
        if not self.config.enabled:
            return
        parent = ctx if ctx is not None else self.current()
        t = threading.current_thread()
        self._finish(SpanRecord(
            name=name,
            trace_id=parent.trace_id if parent else new_trace_id(),
            span_id=f"{next(_span_ids):x}",
            parent_id=parent.span_id if parent else None,
            start=start,
            end=end,
            thread_id=t.ident or 0,
            thread_name=t.name,
            attrs=attrs,
        ))

    def _finish(self, record: SpanRecord) -> None:
        self.recorder.record(record)
        exporter = self._exporter
        if exporter is not None:
            exporter.submit(record)

    def close(self) -> None:
        """Flush and stop the JSONL writer (spans keep recording to the
        ring; close is about releasing the file)."""
        exporter = self._exporter
        if exporter is not None:
            exporter.close()


# -- Chrome-trace / Perfetto export ------------------------------------------

def to_chrome_trace(spans: Iterable[SpanRecord],
                    metadata: Optional[Dict[str, Any]] = None) -> Dict:
    """Render spans as a Chrome Trace Event Format object.

    Loadable by ``chrome://tracing`` and https://ui.perfetto.dev: complete
    ("X") events with microsecond timestamps relative to the earliest span,
    one lane per thread (thread-name metadata events included), span
    attributes + trace/span ids in ``args`` so Perfetto's flow/search finds
    every span of one request by its trace id.
    """
    spans = list(spans)
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    threads: Dict[int, str] = {}
    origin = min((s.start for s in spans), default=0.0)
    for s in spans:
        threads.setdefault(s.thread_id, s.thread_name)
        events.append({
            "name": s.name,
            "cat": s.name.split(".", 1)[0],
            "ph": "X",
            "ts": round(1e6 * (s.start - origin), 3),
            "dur": round(1e6 * (s.end - s.start), 3),
            "pid": pid,
            "tid": s.thread_id,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "status": s.status,
                **s.attrs,
            },
        })
    meta_events = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": name}}
        for tid, name in sorted(threads.items())
    ]
    return {
        "traceEvents": meta_events + sorted(events, key=lambda e: e["ts"]),
        "displayTimeUnit": "ms",
        "otherData": metadata or {},
    }


def write_chrome_trace(path: str, spans: Iterable[SpanRecord],
                       metadata: Optional[Dict[str, Any]] = None) -> str:
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans, metadata), f)
    return path


def dump_flight_recorder(reason: str = "manual",
                         directory: Optional[str] = None,
                         tracer: Optional[Tracer] = None) -> Optional[str]:
    """Write the flight recorder's recent spans to a timestamped file.

    The file is itself a Perfetto-loadable Chrome trace (the dump reason and
    wall-clock time ride in ``otherData``).  Returns the path, or None when
    dumping is not configured (no ``dump_dir``) or the ring is empty.
    """
    tr = tracer if tracer is not None else get_tracer()
    directory = directory or tr.config.dump_dir
    if not directory:
        return None
    spans = tr.recorder.snapshot()
    if not spans:
        return None
    slug = "".join(ch if ch.isalnum() or ch in "._-" else "-"
                   for ch in reason)[:48]
    stamp = datetime.datetime.now().strftime("%Y%m%dT%H%M%S")
    path = os.path.join(
        directory, f"flight-{stamp}-{next(_dump_ids)}-{slug}.trace.json")
    write_chrome_trace(path, spans, metadata={
        "reason": reason,
        "dumped_at": datetime.datetime.now().isoformat(),
        "n_spans": len(spans),
    })
    return path


# -- device correlation (jax.profiler) ---------------------------------------

_annotation_cls: Optional[Any] = None


def device_annotation(name: str):
    """``jax.profiler.TraceAnnotation(name)`` when jax is importable, a
    shared no-op otherwise.  Cheap when no profiler session is active, and
    during one it stamps ``name`` onto the device timeline — how a host
    span (merged dispatch, executor stage B) is matched to the device
    compute it launched."""
    global _annotation_cls
    cls = _annotation_cls
    if cls is None:
        try:
            from jax.profiler import TraceAnnotation as cls
        except Exception:
            cls = _NoopAnnotation
        _annotation_cls = cls
    return cls(name)


class _NoopAnnotation:
    __slots__ = ()

    def __init__(self, name: str):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False


class ProfilerBusyError(RuntimeError):
    """A capture is already running (the endpoint maps this to HTTP 409)."""


class ProfilerSession:
    """Single-flight programmatic ``jax.profiler`` capture.

    One capture at a time per process (concurrent ``start_trace`` calls
    corrupt each other); the busy flag flips under a short lock and the
    capture itself — start, sleep, stop, all slow — runs with no lock held.
    """

    def __init__(self, log_dir: Optional[str],
                 max_seconds: float = 60.0):
        self.log_dir = log_dir
        self.max_seconds = float(max_seconds)
        self._flag_lock = threading.Lock()
        self._active = False

    @property
    def available(self) -> bool:
        return self.log_dir is not None

    def capture(self, seconds: float) -> str:
        """Run one ``jax.profiler.trace`` session for ``seconds`` (clamped
        to ``max_seconds``); returns the capture directory."""
        if self.log_dir is None:
            raise RuntimeError("profiler capture not configured "
                               "(tracing.profile_dir is unset)")
        seconds = max(0.1, min(float(seconds), self.max_seconds))
        with self._flag_lock:
            if self._active:
                raise ProfilerBusyError(
                    "a profiler capture is already in flight")
            self._active = True
        try:
            import jax.profiler
            stamp = datetime.datetime.now().strftime("%Y%m%dT%H%M%S")
            out = os.path.join(self.log_dir, f"capture-{stamp}")
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            return out
        finally:
            with self._flag_lock:
                self._active = False


# -- process-global tracer ---------------------------------------------------

_state_lock = threading.Lock()
_tracer: Optional[Tracer] = None


def configure_tracing(config: TraceConfig) -> Tracer:
    """Install a tracer built from ``config`` process-wide; the previous
    tracer's exporter is flushed and closed (outside the state lock — close
    joins a thread doing file I/O)."""
    global _tracer
    tracer = Tracer(config)
    with _state_lock:
        old, _tracer = _tracer, tracer
    if old is not None:
        old.close()
    return tracer


def get_tracer() -> Tracer:
    """The process-wide tracer (created on first use with defaults:
    recording on, exporters and debug endpoints off)."""
    global _tracer
    with _state_lock:
        if _tracer is None:
            _tracer = Tracer(TraceConfig())
        return _tracer


def enable_from_env() -> Optional[Tracer]:
    """Activate full tracing from ``DFTPU_TRACE_DIR=<dir>`` — the conf-less
    hook for bench subprocesses and CI smoke runs.  No-op when unset."""
    directory = os.environ.get("DFTPU_TRACE_DIR")
    if not directory:
        return None
    return configure_tracing(TraceConfig(
        enabled=True,
        jsonl_path=os.path.join(directory, "trace.jsonl"),
        dump_dir=directory,
        profile_dir=os.path.join(directory, "profile"),
        debug_endpoints=True,
    ))


__all__ = [
    "FlightRecorder",
    "ProfilerBusyError",
    "ProfilerSession",
    "Span",
    "SpanRecord",
    "TraceConfig",
    "TraceContext",
    "Tracer",
    "clock",
    "configure_tracing",
    "device_annotation",
    "dump_flight_recorder",
    "enable_from_env",
    "get_tracer",
    "new_trace_id",
    "to_chrome_trace",
    "write_chrome_trace",
]
