from distributed_forecasting_tpu.monitoring.monitor import (
    MonitorConfig,
    MonitorRegistry,
    run_monitor,
)

__all__ = ["MonitorConfig", "MonitorRegistry", "run_monitor"]
