from distributed_forecasting_tpu.monitoring.monitor import (
    Counter,
    Gauge,
    Histogram,
    LabeledCounter,
    LabeledGauge,
    MetricsRegistry,
    MonitorConfig,
    MonitorRegistry,
    detect_anomalies,
    degradation_report,
    drift_report,
    escape_label_value,
    render_labels,
    run_monitor,
)
from distributed_forecasting_tpu.monitoring.quality import (
    QualityConfig,
    QualityMonitor,
    QualityRuntime,
    build_quality_runtime,
)
from distributed_forecasting_tpu.monitoring.slo import (
    SLOConfig,
    SLOEvaluator,
    SLORule,
    latest_run_timestamp,
)
from distributed_forecasting_tpu.monitoring.store import (
    QualityStoreConfig,
    ScrapeLoop,
    TimeSeriesStore,
    flatten_registry_snapshot,
)
from distributed_forecasting_tpu.monitoring.trace import (
    FlightRecorder,
    ProfilerSession,
    SpanRecord,
    TraceConfig,
    TraceContext,
    Tracer,
    configure_tracing,
    device_annotation,
    dump_flight_recorder,
    get_tracer,
    to_chrome_trace,
    write_chrome_trace,
)

__all__ = ["MonitorConfig", "MonitorRegistry", "detect_anomalies",
           "drift_report", "degradation_report", "run_monitor",
           "Counter", "Gauge", "Histogram", "LabeledCounter", "LabeledGauge",
           "MetricsRegistry", "escape_label_value", "render_labels",
           "QualityConfig", "QualityMonitor", "QualityRuntime",
           "build_quality_runtime",
           "SLOConfig", "SLOEvaluator", "SLORule", "latest_run_timestamp",
           "QualityStoreConfig", "ScrapeLoop", "TimeSeriesStore",
           "flatten_registry_snapshot",
           "FlightRecorder", "ProfilerSession", "SpanRecord", "TraceConfig",
           "TraceContext", "Tracer", "configure_tracing",
           "device_annotation", "dump_flight_recorder", "get_tracer",
           "to_chrome_trace", "write_chrome_trace"]
