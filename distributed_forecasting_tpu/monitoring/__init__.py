from distributed_forecasting_tpu.monitoring.monitor import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MonitorConfig,
    MonitorRegistry,
    detect_anomalies,
    degradation_report,
    drift_report,
    run_monitor,
)

__all__ = ["MonitorConfig", "MonitorRegistry", "detect_anomalies",
           "drift_report", "degradation_report", "run_monitor",
           "Counter", "Gauge", "Histogram", "MetricsRegistry"]
