"""Runtime cost & capacity observability (``dftpu_cost_*``).

The roofline math lived only in the offline ``scripts/mfu_roofline.py``:
nobody serving traffic could answer "what did that dispatch cost in
device-seconds, FLOPs and HBM, and how much headroom does the fleet
have?".  This module productionizes that analysis into the runtime:

  * **Program cost registry** — at AOT compile time the compile cache
    (``engine/compile_cache.py``) extracts ``compiled.cost_analysis()`` +
    ``memory_analysis()`` through :func:`extract_cost_analysis` and records
    it here per entry x shape-bucket (the bucket rides as a key-prefix
    label); the numbers are persisted beside the serialized executable, so
    a warm process repopulates the registry at load time without ever
    compiling.  Exposed as ``dftpu_cost_program_*`` labeled gauges —
    REPLICATED across a fleet (every replica shares one AOT store, so the
    aggregator keeps one copy instead of summing).
  * **Device-time attribution** — the serving predictor, the batcher, and
    the training executor stamp each dispatch's device interval (dispatch
    through host pull, on the span clock) into per-entry/per-family
    device-seconds counters (summed fleet-wide), and a sliding window
    turns them into ``dftpu_cost_device_saturation`` = device-seconds
    consumed per wall-second — the fleet's capacity gauge (sums across
    replicas: 2.0 means two devices' worth of work).
  * **Memory watermarks** — ``dftpu_cost_watermark_*`` gauges for host RSS
    (+ peak) from ``/proc/self/status`` and device bytes-in-use (+ peak)
    from ``device.memory_stats()`` where the backend provides it; the
    quality scrape loop (``monitoring/store.py``) samples them on every
    tick so the store keeps queryable history.  Max-merged across a fleet
    (the worst replica is the capacity signal).

Conf block ``monitoring.cost`` (strict — unknown keys raise)::

    monitoring:
      cost:
        enabled: true
        peak_flops: 0.0          # backend peak FLOP/s; 0 disables the
        peak_bytes_per_s: 0.0    # roofline placement in /debug/cost
        saturation_window_s: 60

``GET /debug/cost`` (behind ``tracing.debug_endpoints``, like the other
debug surfaces) renders the registry as a per-entry table with
achieved-vs-peak roofline placement when the peaks are configured.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import clock

#: cost_analysis / memory_analysis fields captured per compiled program,
#: in the order the /debug/cost table shows them.  Each becomes a
#: ``dftpu_cost_program_<field>`` labeled gauge.
PROGRAM_FIELDS = (
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "peak_bytes",
    "alias_bytes",
)


def extract_cost_analysis(compiled) -> Dict[str, float]:
    """FLOPs / bytes / memory footprint of a compiled XLA program.

    Tolerant by construction — ``cost_analysis()`` may return a per-device
    list (take the first), either analysis may be missing on a backend, and
    any failure yields an empty dict (cost capture is telemetry, never an
    error).  ``peak_bytes`` falls back to argument+output+temp when the
    backend reports no explicit peak.  The single shared extraction point:
    the compile cache and ``scripts/mfu_roofline.py`` both call this, so
    the two can never drift on how the numbers are read.
    """
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed")):
            v = float(ca.get(key, float("nan")))
            if math.isfinite(v):
                out[field] = v
    except Exception:  # noqa: BLE001 — backends without cost analysis
        pass
    try:
        ma = compiled.memory_analysis()
        for field, attr in (
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
            ("peak_bytes", "peak_memory_in_bytes"),
            # donated arguments: bytes XLA aliases input->output instead of
            # copying.  argument_size does NOT shrink under donation on
            # XLA:CPU — the alias is how donation proves it took effect
            ("alias_bytes", "alias_size_in_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v is not None and math.isfinite(float(v)):
                out[field] = float(v)
    except Exception:  # noqa: BLE001
        pass
    if "peak_bytes" not in out:
        parts = [out.get(k) for k in
                 ("argument_bytes", "output_bytes", "temp_bytes")]
        if any(p is not None for p in parts):
            out["peak_bytes"] = sum(p for p in parts if p is not None)
    return out


@dataclasses.dataclass(frozen=True)
class CostConfig:
    """The ``monitoring.cost`` conf block."""

    enabled: bool = True
    peak_flops: float = 0.0        # 0: no roofline placement
    peak_bytes_per_s: float = 0.0  # 0: no roofline placement
    saturation_window_s: float = 60.0

    def __post_init__(self):
        if self.saturation_window_s <= 0:
            raise ValueError(
                f"saturation_window_s must be > 0, got "
                f"{self.saturation_window_s}")
        if self.peak_flops < 0:
            raise ValueError(
                f"peak_flops must be >= 0, got {self.peak_flops}")
        if self.peak_bytes_per_s < 0:
            raise ValueError(
                f"peak_bytes_per_s must be >= 0, got "
                f"{self.peak_bytes_per_s}")

    @property
    def ridge_intensity(self) -> float:
        """FLOP/byte at which the roofline bends; 0 when peaks unset."""
        if self.peak_flops > 0 and self.peak_bytes_per_s > 0:
            return self.peak_flops / self.peak_bytes_per_s
        return 0.0

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "CostConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like peak_flop must not silently disable the roofline
            raise ValueError(
                f"unknown monitoring.cost conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


def _read_host_rss() -> Dict[str, float]:
    """Current and peak RSS of THIS process, in bytes.

    ``/proc/self/status`` (VmRSS/VmHWM) where available; the ``resource``
    module's maxrss as the peak fallback elsewhere.  No psutil — the
    container doesn't ship it.
    """
    out: Dict[str, float] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss_bytes"] = float(line.split()[1]) * 1024.0
                elif line.startswith("VmHWM:"):
                    out["rss_peak_bytes"] = float(line.split()[1]) * 1024.0
    except OSError:
        pass
    if "rss_peak_bytes" not in out:
        try:
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            # linux reports KiB, macOS bytes; this fallback only runs
            # where /proc is absent, i.e. the latter
            out["rss_peak_bytes"] = float(ru.ru_maxrss)
        except Exception:  # noqa: BLE001
            pass
    return out


def _read_device_memory() -> Dict[str, float]:
    """bytes_in_use / peak_bytes_in_use of the first local device, where
    the backend exposes ``memory_stats()`` (TPU/GPU; CPU returns None)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — no backend / no stats on CPU
        return {}
    if not stats:
        return {}
    out: Dict[str, float] = {}
    if "bytes_in_use" in stats:
        out["device_bytes"] = float(stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        out["device_peak_bytes"] = float(stats["peak_bytes_in_use"])
    return out


class CostMetrics:
    """The ``dftpu_cost_*`` registry, one per process.

    Same discipline as :class:`monitor.PipelineMetrics`: every attribute is
    created in ``__init__`` and the metric objects are themselves
    thread-safe.  The only mutable state beyond them is the saturation
    window (``_recent``/``_recent_sum``), guarded by ``_lock`` — readers
    snapshot under the lock, never touch the deque unlocked.

    Fleet merge semantics (serving/fleet.aggregate_prometheus):

      * ``dftpu_cost_device_seconds_total`` / ``_dispatches_total``
        counters and the ``device_saturation`` gauge SUM — work is
        additive across replicas;
      * ``dftpu_cost_watermark_*`` gauges MAX — capacity headroom is set
        by the worst replica;
      * ``dftpu_cost_program_*`` gauges REPLICATE (first replica wins) —
        the fleet shares one AOT store, so every replica reports the same
        program fingerprints and summing would multiply FLOPs by the
        replica count;
      * ``dftpu_cost_padding_waste`` gauges MAX — the pad-row fraction is
        a ratio, so summing would be meaningless; the worst replica is the
        signal (the underlying ``_padding_rows_total`` counters SUM).
    """

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.device_seconds_total = self.registry.labeled_counter(
            "dftpu_cost_device_seconds_total", ("entry", "family"),
            "device-seconds attributed per AOT entry and model family "
            "(dispatch through host pull, span clock)")
        self.dispatches_total = self.registry.labeled_counter(
            "dftpu_cost_dispatches_total", ("entry", "family"),
            "attributed device dispatches per AOT entry and model family")
        self.device_saturation = self.registry.gauge(
            "dftpu_cost_device_saturation",
            "device-seconds consumed per wall-second over the saturation "
            "window (fleet-summed: 2.0 = two devices' worth of work)")
        self.program = {
            field: self.registry.labeled_gauge(
                f"dftpu_cost_program_{field}", ("entry", "key"),
                f"XLA {field.replace('_', ' ')} of the compiled program, "
                f"per AOT entry and shape-bucket key (replicated across a "
                f"fleet sharing one store)")
            for field in PROGRAM_FIELDS
        }
        self.host_rss_bytes = self.registry.gauge(
            "dftpu_cost_watermark_host_rss_bytes",
            "resident set size of this process (fleet: max-merged)")
        self.host_rss_peak_bytes = self.registry.gauge(
            "dftpu_cost_watermark_host_rss_peak_bytes",
            "high-water resident set size of this process "
            "(fleet: max-merged)")
        self.device_bytes = self.registry.gauge(
            "dftpu_cost_watermark_device_bytes",
            "device memory in use, first local device; 0 where the "
            "backend reports none (fleet: max-merged)")
        self.device_peak_bytes = self.registry.gauge(
            "dftpu_cost_watermark_device_peak_bytes",
            "peak device memory in use, first local device "
            "(fleet: max-merged)")
        self.padding_rows_total = self.registry.labeled_counter(
            "dftpu_cost_padding_rows_total", ("entry", "kind"),
            "dispatched batch rows per AOT entry, split kind=real|pad "
            "(pad rows are bucket-ladder fill whose FLOPs are pure waste)")
        self.padding_waste = self.registry.labeled_gauge(
            "dftpu_cost_padding_waste", ("entry",),
            "cumulative fraction of dispatched rows that were bucket "
            "padding, per AOT entry (fleet: max-merged)")
        self.saturation_window_s = 60.0
        self._lock = threading.Lock()
        self._recent: deque = deque()   # (span-clock ts, device_seconds)
        self._recent_sum = 0.0
        self._padding: Dict[str, List[float]] = {}  # entry -> [real+pad, pad]
        self._t0 = clock()
        self._tls = threading.local()

    # -- attribution ---------------------------------------------------------
    def record_dispatch(self, entry: str, family: str,
                        device_seconds: float) -> None:
        """Attribute one dispatch's device interval; updates the counters,
        the saturation window, and any open :meth:`attribution` scope on
        this thread.  Two clock reads and a few dict ops — cheap enough for
        the request path (the <2% overhead bar PR 6 set for tracing)."""
        dev = max(float(device_seconds), 0.0)
        self.device_seconds_total.inc(dev, entry=entry, family=family)
        self.dispatches_total.inc(1.0, entry=entry, family=family)
        acc = getattr(self._tls, "acc", None)
        if acc is not None:
            acc["device_seconds"] += dev
            acc["dispatches"] += 1
        now = clock()
        window = self.saturation_window_s
        with self._lock:
            self._recent.append((now, dev))
            self._recent_sum += dev
            floor = now - window
            while self._recent and self._recent[0][0] < floor:
                _, old = self._recent.popleft()
                self._recent_sum -= old
            # a young process has observed less than a full window;
            # dividing by the window would understate load during warmup
            elapsed = min(window, max(now - self._t0, 1e-9))
            saturation = self._recent_sum / elapsed
        self.device_saturation.set(saturation)

    def record_padding(self, entry: str, rows: int, pad_rows: int) -> None:
        """Attribute one dispatch's bucket-ladder padding: ``rows`` total
        batch rows dispatched, of which ``pad_rows`` were ladder fill.
        Updates the split counters and the per-entry cumulative waste
        fraction — the number the kernel round drives down by tightening
        the ladder (pow2 -> pow2x3)."""
        rows = max(int(rows), 0)
        pad = min(max(int(pad_rows), 0), rows)
        if rows == 0:
            return
        self.padding_rows_total.inc(rows - pad, entry=entry, kind="real")
        self.padding_rows_total.inc(pad, entry=entry, kind="pad")
        with self._lock:
            acc = self._padding.setdefault(entry, [0.0, 0.0])
            acc[0] += rows
            acc[1] += pad
            frac = acc[1] / acc[0]
        self.padding_waste.set(frac, entry=entry)

    @contextlib.contextmanager
    def attribution(self):
        """Scope that accumulates this THREAD's recorded dispatches —
        the batcher wraps a merged dispatch in one so the total device
        time lands on its ``batcher.dispatch`` span without threading a
        value through the predictor's return."""
        prev = getattr(self._tls, "acc", None)
        acc = {"device_seconds": 0.0, "dispatches": 0}
        self._tls.acc = acc
        try:
            yield acc
        finally:
            self._tls.acc = prev

    # -- program registry ----------------------------------------------------
    def record_program(self, entry: str, costs: Dict[str, float],
                       key: str = "") -> None:
        """Publish one compiled program's cost analysis.  ``key`` is the
        store fingerprint prefix distinguishing shape buckets of the same
        entry; empty for callers without one (offline tools)."""
        if not costs:
            return
        for field, gauge in self.program.items():
            v = costs.get(field)
            if v is not None and math.isfinite(float(v)):
                gauge.set(float(v), entry=entry, key=key)

    # -- watermarks ----------------------------------------------------------
    def sample_watermarks(self) -> None:
        """Refresh the RSS/device-memory gauges.  All file I/O happens
        before any metric is touched and no CostMetrics lock is held —
        the scrape loop calls this on its tick."""
        host = _read_host_rss()
        dev = _read_device_memory()
        if "rss_bytes" in host:
            self.host_rss_bytes.set(host["rss_bytes"])
        if "rss_peak_bytes" in host:
            self.host_rss_peak_bytes.set(host["rss_peak_bytes"])
        if "device_bytes" in dev:
            self.device_bytes.set(dev["device_bytes"])
        if "device_peak_bytes" in dev:
            self.device_peak_bytes.set(dev["device_peak_bytes"])

    # -- the /debug/cost view ------------------------------------------------
    def cost_table(self, config: Optional[CostConfig] = None) -> List[Dict]:
        """Per-(entry, shape-bucket) rows joining the program registry with
        the attribution counters, plus roofline placement when the config
        carries backend peaks.

        Device seconds are attributed per ENTRY (the predictor doesn't see
        the store key), so rows of a multi-bucket entry share the entry's
        dispatch totals and the achieved-FLOP/s estimate uses each row's
        own program FLOPs — an estimate, exact when one bucket dominates.
        """
        config = config or get_cost_config()
        programs: Dict[tuple, Dict[str, float]] = {}
        for field, gauge in self.program.items():
            for label_str, v in gauge.snapshot().items():
                labels = dict(
                    part.partition("=")[::2] for part in label_str.split(","))
                programs.setdefault(
                    (labels.get("entry", ""), labels.get("key", "")), {},
                )[field] = v
        per_entry: Dict[str, Dict[str, float]] = {}
        for counter, out_field in ((self.device_seconds_total,
                                    "device_seconds"),
                                   (self.dispatches_total, "dispatches")):
            for label_str, v in counter.snapshot().items():
                labels = dict(
                    part.partition("=")[::2] for part in label_str.split(","))
                agg = per_entry.setdefault(
                    labels.get("entry", ""),
                    {"device_seconds": 0.0, "dispatches": 0.0,
                     "family": labels.get("family", "")})
                agg[out_field] += v
        rows: List[Dict] = []
        for (entry, key) in sorted(set(programs) | {
                (e, "") for e in per_entry if not any(
                    pe == e for pe, _ in programs)}):
            row: Dict[str, Any] = {"entry": entry, "key": key}
            row.update(programs.get((entry, key), {}))
            stats = per_entry.get(entry)
            if stats:
                row["family"] = stats["family"]
                row["device_seconds"] = stats["device_seconds"]
                row["dispatches"] = stats["dispatches"]
            flops = row.get("flops")
            byts = row.get("bytes_accessed")
            if flops and byts:
                row["operational_intensity"] = flops / byts
            if (stats and stats["device_seconds"] > 0 and flops
                    and stats["dispatches"] > 0):
                row["achieved_flops_per_s"] = (
                    flops * stats["dispatches"] / stats["device_seconds"])
            ridge = config.ridge_intensity
            if ridge and "operational_intensity" in row:
                oi = row["operational_intensity"]
                row["bound"] = "compute" if oi >= ridge else "memory"
                attainable = min(config.peak_flops,
                                 oi * config.peak_bytes_per_s)
                row["attainable_flops_per_s"] = attainable
                if "achieved_flops_per_s" in row and attainable > 0:
                    row["fraction_of_attainable"] = (
                        row["achieved_flops_per_s"] / attainable)
            rows.append(row)
        return rows

    def snapshot(self, config: Optional[CostConfig] = None) -> Dict:
        """The ``GET /debug/cost`` body: config echo, live saturation and
        watermarks, and the per-entry cost table."""
        config = config or get_cost_config()
        self.sample_watermarks()
        return {
            "config": {
                "peak_flops": config.peak_flops,
                "peak_bytes_per_s": config.peak_bytes_per_s,
                "ridge_intensity": config.ridge_intensity,
                "saturation_window_s": self.saturation_window_s,
            },
            "device_saturation": self.device_saturation.value,
            "watermarks": {
                "host_rss_bytes": self.host_rss_bytes.value,
                "host_rss_peak_bytes": self.host_rss_peak_bytes.value,
                "device_bytes": self.device_bytes.value,
                "device_peak_bytes": self.device_peak_bytes.value,
            },
            "entries": self.cost_table(config),
        }


_state_lock = threading.Lock()
_cost_metrics: Optional[CostMetrics] = None
_active_config: Optional[CostConfig] = None


def cost_metrics() -> CostMetrics:
    """Process-wide :class:`CostMetrics` singleton (lazy)."""
    global _cost_metrics
    with _state_lock:
        if _cost_metrics is None:
            _cost_metrics = CostMetrics()
        return _cost_metrics


def configure_cost(config: CostConfig) -> CostMetrics:
    """Apply the ``monitoring.cost`` conf block process-wide (peaks feed
    the /debug/cost roofline; the window resizes the saturation gauge)."""
    global _active_config
    with _state_lock:
        _active_config = config
    cm = cost_metrics()
    cm.saturation_window_s = float(config.saturation_window_s)
    return cm


def get_cost_config() -> CostConfig:
    """The active config; defaults (enabled, no peaks) when no conf block
    has been parsed — attribution is on unless explicitly disabled."""
    with _state_lock:
        return _active_config if _active_config is not None else CostConfig()


__all__ = [
    "PROGRAM_FIELDS",
    "CostConfig",
    "CostMetrics",
    "configure_cost",
    "cost_metrics",
    "extract_cost_analysis",
    "get_cost_config",
]
