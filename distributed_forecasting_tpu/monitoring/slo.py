"""Declarative SLOs with multi-window burn-rate alerting.

The quality layer (``monitoring/quality.py``) measures; this module
decides.  A strict ``monitoring.slo`` conf block declares objectives over
three SLI kinds the serving stack already produces:

  * ``latency_quantile`` — a quantile of the serving latency histogram
    (``Histogram.snapshot_quantiles``, one locked snapshot) must stay at or
    under ``objective`` seconds;
  * ``coverage`` — the quality monitor's rolling calibration coverage must
    stay within ``±tolerance`` of the nominal interval width
    (``engine/calibrate.py``'s ``config_interval_width``, or the conf
    override);
  * ``staleness`` — the age of the newest FINISHED tracking run
    (``tracking/filestore.py`` run ``end_time``/``start_time`` stamps)
    must stay under ``objective`` seconds: a model nobody retrains is a
    quality incident waiting to be measured.

Alerting follows the multi-window burn-rate construction (the SRE-workbook
shape): each evaluation tick appends a good/bad sample to the time-series
store, the burn rate over window W is ``mean(bad over W) / error_budget``,
and a rule FIRES only when every configured window burns past its
threshold — the short window proves it's happening NOW, the long window
proves it's not a blip.  It CLEARS when the shortest window recovers.
Results surface as ``dftpu_slo_*`` gauges on ``/metrics`` (the fleet front
door max-merges them: an SLO firing anywhere is firing fleet-wide).

Conf::

    monitoring:
      slo:
        enabled: true
        evaluation_interval_s: 30
        error_budget: 0.05           # allowed bad-tick fraction
        windows: [[300, 2.0], [3600, 1.0]]   # [window_s, burn_threshold]
        rules:
          - {name: predict_latency_p95, kind: latency_quantile,
             quantile: 0.95, objective: 0.5}
          - {name: calibration_coverage, kind: coverage, tolerance: 0.05}
          - {name: model_staleness, kind: staleness, objective: 604800}

Every rule evaluation is exception-isolated;
``dftpu_slo_evaluation_errors_total`` counts failures (the CI quality
smoke gates on it staying zero).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.utils import get_logger

_KINDS = ("latency_quantile", "coverage", "staleness")
_BAD_SERIES = "dftpu_slo_bad"      # 0/1 per (rule, tick) in the store
_SLI_SERIES = "dftpu_slo_sli"


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One objective.  ``objective`` means: max seconds for
    ``latency_quantile`` and ``staleness``; target coverage for
    ``coverage`` (0 -> the monitor's nominal width)."""

    name: str
    kind: str
    objective: float = 0.0
    quantile: float = 0.95       # latency_quantile only
    tolerance: float = 0.05      # coverage only

    def __post_init__(self):
        if not self.name:
            raise ValueError("slo rule needs a name")
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown slo rule kind {self.kind!r}; valid: {_KINDS}")
        if self.kind != "coverage" and self.objective <= 0:
            raise ValueError(
                f"rule {self.name!r}: objective must be > 0 seconds")
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(f"rule {self.name!r}: quantile outside (0, 1)")
        if not 0.0 < self.tolerance < 1.0:
            raise ValueError(f"rule {self.name!r}: tolerance outside (0, 1)")

    @classmethod
    def from_conf(cls, conf: dict) -> "SLORule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            raise ValueError(
                f"unknown monitoring.slo rule key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        return cls(**{k: conf[k] for k in conf})


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The ``monitoring.slo`` conf block."""

    enabled: bool = False
    evaluation_interval_s: float = 30.0
    error_budget: float = 0.05
    windows: Tuple[Tuple[float, float], ...] = ((300.0, 2.0), (3600.0, 1.0))
    rules: Tuple[SLORule, ...] = ()

    def __post_init__(self):
        if self.evaluation_interval_s <= 0:
            raise ValueError("evaluation_interval_s must be > 0")
        if not 0.0 < self.error_budget <= 1.0:
            raise ValueError("error_budget must be in (0, 1]")
        if not self.windows:
            raise ValueError("slo needs at least one burn-rate window")
        for w, t in self.windows:
            if w <= 0 or t <= 0:
                raise ValueError(
                    f"burn-rate window [{w}, {t}] must be positive")
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo rule names in {names}")

    @property
    def short_window(self) -> Tuple[float, float]:
        return min(self.windows, key=lambda wt: wt[0])

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "SLOConfig":
        conf = dict(conf or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            raise ValueError(
                f"unknown monitoring.slo conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs: Dict = {}
        if "enabled" in conf:
            kwargs["enabled"] = bool(conf["enabled"])
        if "evaluation_interval_s" in conf:
            kwargs["evaluation_interval_s"] = float(
                conf["evaluation_interval_s"])
        if "error_budget" in conf:
            kwargs["error_budget"] = float(conf["error_budget"])
        if "windows" in conf:
            windows = conf["windows"]
            if not isinstance(windows, (list, tuple)):
                raise ValueError("monitoring.slo windows must be a list of "
                                 "[window_s, burn_threshold] pairs")
            kwargs["windows"] = tuple(
                (float(w[0]), float(w[1])) for w in windows)
        if "rules" in conf:
            rules = conf["rules"]
            if not isinstance(rules, (list, tuple)):
                raise ValueError("monitoring.slo rules must be a list")
            kwargs["rules"] = tuple(SLORule.from_conf(dict(r))
                                    for r in rules)
        return cls(**kwargs)


def latest_run_timestamp(tracking_root: str) -> Optional[float]:
    """Newest run timestamp under a FileTracker root — ``end_time`` when the
    run finished, else ``start_time`` (an in-flight retrain still counts as
    freshness).  None when no run has ever been logged."""
    latest: Optional[float] = None
    pattern = os.path.join(tracking_root, "experiments", "*", "runs", "*",
                           "meta.json")
    for path in glob.glob(pattern):
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue
        ts = meta.get("end_time") or meta.get("start_time")
        if ts is not None and (latest is None or float(ts) > latest):
            latest = float(ts)
    return latest


class SLOEvaluator:
    """Periodic rule evaluation: SLI -> good/bad sample -> burn rates ->
    ``dftpu_slo_*`` gauges.

    Sources are injected callables so the evaluator carries no serving
    imports: ``latency_histogram`` (the serving latency Histogram or None),
    ``coverage_fn`` (-> rolling coverage, NaN before data),
    ``nominal_fn`` (-> target width), ``staleness_fn`` (-> newest run
    timestamp or None).  ``_lock`` guards the per-rule firing state; store
    reads/writes happen outside it (snapshot-then-write, the fleet
    supervisor's discipline).
    """

    def __init__(
        self,
        config: SLOConfig,
        store,
        latency_histogram=None,
        coverage_fn=None,
        nominal_fn=None,
        staleness_fn=None,
    ):
        self.config = config
        self.store = store
        self._latency = latency_histogram
        self._coverage_fn = coverage_fn
        self._nominal_fn = nominal_fn
        self._staleness_fn = staleness_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._firing: Dict[str, bool] = {r.name: False for r in config.rules}
        self.logger = get_logger("SLOEvaluator")

        r = MetricsRegistry()
        self.registry = r
        self.evaluations = r.counter(
            "dftpu_slo_evaluations_total", "SLO evaluation ticks completed")
        self.evaluation_errors = r.counter(
            "dftpu_slo_evaluation_errors_total",
            "rule evaluations that raised (isolated per rule)")
        self.sli_gauge = r.labeled_gauge(
            "dftpu_slo_sli", ("rule",),
            "current SLI value per rule (seconds or coverage fraction)")
        self.burn_gauge = r.labeled_gauge(
            "dftpu_slo_burn_rate", ("rule", "window"),
            "error-budget burn rate per rule and window")
        self.firing_gauge = r.labeled_gauge(
            "dftpu_slo_firing", ("rule",),
            "1 while every burn-rate window of the rule exceeds its "
            "threshold (multi-window alerting)")

    def bind_latency(self, histogram) -> None:
        """Late-bind the serving latency histogram — it only exists once
        the server process constructs its ``ServingMetrics``.  Called
        before ``start()``, so the write happens-before the evaluator
        thread ever reads it."""
        self._latency = histogram  # dflint: disable=unlocked-shared-state — bound before start(); happens-before the evaluator thread

    # -- SLI computation -----------------------------------------------------
    def _sli(self, rule: SLORule, now: float) -> Tuple[float, Optional[bool]]:
        """(sli_value, bad) — ``bad`` None when the SLI is unmeasurable
        (no traffic yet / no runs yet): no budget burns on silence."""
        if rule.kind == "latency_quantile":
            if self._latency is None:
                return float("nan"), None
            q = self._latency.snapshot_quantiles((rule.quantile,))[
                rule.quantile]
            if q != q:
                return float("nan"), None
            return q, q > rule.objective
        if rule.kind == "coverage":
            if self._coverage_fn is None:
                return float("nan"), None
            cov = float(self._coverage_fn())
            if cov != cov:
                return float("nan"), None
            target = rule.objective or (
                float(self._nominal_fn()) if self._nominal_fn else 0.95)
            return cov, abs(cov - target) > rule.tolerance
        # staleness
        if self._staleness_fn is None:
            return float("nan"), None
        ts = self._staleness_fn()
        if ts is None:
            return float("nan"), None
        age = max(now - float(ts), 0.0)
        return age, age > rule.objective

    def _burn_rates(self, rule: SLORule, now: float) -> Dict[float, float]:
        """Burn per window from the stored bad/good samples: mean(bad) /
        error_budget; a window with no samples burns 0."""
        out: Dict[float, float] = {}
        for window_s, _ in self.config.windows:
            pts = self.store.query(
                name=_BAD_SERIES, since=now - window_s,
                labels={"rule": rule.name})
            if pts:
                bad_frac = sum(p["value"] for p in pts) / len(pts)
                out[window_s] = bad_frac / self.config.error_budget
            else:
                out[window_s] = 0.0
        return out

    # -- the tick ------------------------------------------------------------
    def evaluate_once(self, now: Optional[float] = None) -> Dict:
        """One evaluation pass over every rule; returns the JSON-friendly
        state ``/debug/quality`` embeds."""
        if now is None:
            now = time.time()  # dflint: disable=nondeterminism — SLO windows are wall-clock by definition
        results = []
        points: List[Dict] = []
        for rule in self.config.rules:
            try:
                sli, bad = self._sli(rule, now)
                if bad is not None:
                    points.append({
                        "ts": now, "name": _BAD_SERIES,
                        "labels": {"rule": rule.name},
                        "value": 1.0 if bad else 0.0})
                    points.append({
                        "ts": now, "name": _SLI_SERIES,
                        "labels": {"rule": rule.name}, "value": sli})
                results.append((rule, sli, bad))
            except Exception:  # noqa: BLE001 — one broken rule must not silence the rest
                self.evaluation_errors.inc()
                self.logger.exception("slo rule %s failed", rule.name)
        if points:
            # outside any lock: the store synchronizes internally (one
            # atomic O_APPEND write per batch)
            self.store.append(points)  # dflint: disable=unlocked-shared-state — TimeSeriesStore is internally synchronized; deliberately outside _lock
        state: Dict = {"rules": []}
        short_w = self.config.short_window[0]
        for rule, sli, bad in results:
            try:
                burns = self._burn_rates(rule, now)
                burning_all = all(
                    burns[w] > threshold
                    for w, threshold in self.config.windows)
                short_thresh = self.config.short_window[1]
                with self._lock:
                    firing = self._firing[rule.name]
                    if burning_all:
                        firing = True
                    elif burns[short_w] <= short_thresh:
                        # hysteresis: clear on short-window recovery only
                        firing = False
                    self._firing[rule.name] = firing
                if sli == sli:
                    self.sli_gauge.set(sli, rule=rule.name)
                for w, burn in burns.items():
                    self.burn_gauge.set(burn, rule=rule.name,
                                        window=f"{w:g}s")
                self.firing_gauge.set(1.0 if firing else 0.0,
                                      rule=rule.name)
                state["rules"].append({
                    "name": rule.name, "kind": rule.kind,
                    "sli": None if sli != sli else round(float(sli), 6),
                    "bad": bad, "firing": firing,
                    "burn_rates": {f"{w:g}s": round(b, 4)
                                   for w, b in burns.items()},
                })
            except Exception:  # noqa: BLE001
                self.evaluation_errors.inc()
                self.logger.exception("slo burn-rate for %s failed",
                                      rule.name)
        self.evaluations.inc()
        return state

    def snapshot(self) -> Dict:
        with self._lock:
            firing = dict(self._firing)
        return {
            "enabled": self.config.enabled,
            "error_budget": self.config.error_budget,
            "windows": [list(w) for w in self.config.windows],
            "firing": firing,
            "evaluations": self.evaluations.value,
            "evaluation_errors": self.evaluation_errors.value,
        }

    # -- lifecycle -----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.config.evaluation_interval_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the loop must outlive one bad tick
                self.evaluation_errors.inc()
                self.logger.exception("slo evaluation tick failed")

    def start(self) -> None:
        # lifecycle runs on the owning (server) thread only; _lock guards
        # the firing map the evaluator thread shares, not these
        if self._thread is not None:
            return
        self._thread = threading.Thread(  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
            target=self._run, name="slo-evaluator", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
