"""Deterministic failpoint registry: injectable faults at named sites.

Every durability and dispatch boundary in the serving stack carries a
named ``failpoint("...")`` call — WAL append/roll/read, AOT store
load/store, state swap and refit install, every front-door → replica
forward leg.  When the registry is inactive (the default, and the only
state production ever runs in) a site is a single module-global boolean
test — no lock, no dict lookup, no allocation — so the hot paths pay
nothing for their testability.  When a spec is armed (env var, conf, or
:func:`configure` from a test/harness), the named sites fire reproducible
faults: the probabilistic modifier draws from a SEEDED PRNG, so a chaos
run that found a bug replays bit-for-bit from its seed.

Activation spec — ``;``/newline-separated terms::

    name=action[:prob][:count]

    wal.append.enospc=raise OSError            # every evaluation raises
    fleet.forward=raise:0.1                    # 10% of legs, seeded PRNG
    aot.load.payload=corrupt                   # flip a byte in the data
    aot.load.payload=corrupt truncate          # drop the tail instead
    state.swap=sleep 250:0.5:3                 # 250ms stall, p=0.5, 3 hits
    wal.append.enospc=kill9                    # SIGKILL self (no cleanup)

``prob`` is a float in (0, 1]; ``count`` caps total firings (``3`` or
``3x``) after which the site disarms itself.  Actions:

* ``raise [ExcName]`` — raise ``ExcName`` (a builtin exception name;
  default :class:`FailpointError`).
* ``sleep <ms>`` — block the calling thread; models brownouts and slow
  disks/replicas rather than hard failures.
* ``corrupt [flip|truncate]`` — only meaningful at data sites
  (:func:`failpoint_data`): deterministically flip one byte, or cut the
  payload short.  At a plain site it is ignored.
* ``kill9`` — ``SIGKILL`` the current process: the crash-consistency
  hammer (no atexit, no flush — exactly what the WAL must survive).

Environment activation (read once at import, the hook replica
subprocesses and CI use)::

    DFTPU_FAILPOINTS="wal.append.enospc=raise OSError:0.01"
    DFTPU_FAILPOINTS_SEED=42

The conf route is the strict ``serving.resilience.failpoints`` key
(``serving/resilience.py``); tests call :func:`configure` /
:func:`deactivate` directly.
"""

from __future__ import annotations

import builtins
import os
import random
import signal
import threading
import time
from typing import Dict, Optional

__all__ = [
    "FailpointError",
    "configure",
    "configure_from_env",
    "deactivate",
    "failpoint",
    "failpoint_data",
    "fired",
    "is_active",
    "snapshot",
]


class FailpointError(RuntimeError):
    """Default exception for ``raise`` actions with no exception name."""


class _Armed:
    """One armed site.  Mutable (count decrements under the module lock);
    deliberately not a dataclass — the hot path never touches it unless
    the registry is enabled."""

    __slots__ = ("action", "arg", "prob", "count")

    def __init__(self, action: str, arg: str = "",
                 prob: float = 1.0, count: int = -1):
        self.action = action
        self.arg = arg
        self.prob = prob
        self.count = count  # firings remaining; -1 = unlimited


_ACTIONS = ("raise", "sleep", "corrupt", "kill9")

# Registry state.  ``_enabled`` is the ONLY thing a disabled site reads:
# a module-global bool test, rebound under ``_lock`` by configure/
# deactivate.  Everything else is touched only while armed.
_lock = threading.Lock()
_enabled = False
_armed: Dict[str, _Armed] = {}
_fired: Dict[str, int] = {}
_rng = random.Random(0)  # dflint: disable=nondeterminism — re-seeded by every configure(); the seed IS the reproducibility contract


def _resolve_exception(name: str):
    if not name:
        return FailpointError
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(f"failpoint spec names unknown exception {name!r}")


def _parse_term(term: str) -> tuple:
    name, sep, rest = term.partition("=")
    name = name.strip()
    if not sep or not name or not rest.strip():
        raise ValueError(
            f"failpoint term {term!r} is not name=action[:prob][:count]")
    parts = [p.strip() for p in rest.split(":")]
    action_word, _, arg = parts[0].partition(" ")
    action_word = action_word.strip()
    arg = arg.strip()
    if action_word not in _ACTIONS:
        raise ValueError(
            f"failpoint {name!r}: unknown action {action_word!r} "
            f"(valid: {', '.join(_ACTIONS)})")
    if action_word == "raise":
        _resolve_exception(arg)  # fail at configure time, not at the site
    elif action_word == "sleep":
        if not arg:
            raise ValueError(f"failpoint {name!r}: sleep needs milliseconds")
        float(arg)
    elif action_word == "corrupt":
        if arg not in ("", "flip", "truncate"):
            raise ValueError(
                f"failpoint {name!r}: corrupt mode must be flip|truncate, "
                f"got {arg!r}")
    prob, count = 1.0, -1
    for mod in parts[1:]:
        if not mod:
            continue
        if mod.endswith("x"):
            count = int(mod[:-1])
        elif "." in mod:
            prob = float(mod)
        else:
            # a bare int is a count, a bare float a probability — ``1``
            # alone is read as a count (fire once); spell ``1.0`` for
            # "always"
            count = int(mod)
    if not 0.0 < prob <= 1.0:
        raise ValueError(f"failpoint {name!r}: prob {prob} outside (0, 1]")
    if count == 0 or count < -1:
        raise ValueError(f"failpoint {name!r}: count must be >= 1")
    return name, _Armed(action_word, arg, prob, count)


def configure(spec: Optional[str], seed: int = 0) -> int:
    """Arm the registry from an activation spec; returns the number of
    armed sites.  An empty/None spec deactivates (the conf-default path:
    ``failpoints: ""`` must leave production untouched)."""
    global _enabled
    terms = []
    for raw in (spec or "").replace("\n", ";").split(";"):
        raw = raw.strip()
        if raw:
            terms.append(_parse_term(raw))
    with _lock:
        _armed.clear()
        _fired.clear()
        _rng.seed(seed)
        for name, armed in terms:
            _armed[name] = armed
        _enabled = bool(_armed)
    return len(terms)


def configure_from_env() -> int:
    """Arm from ``DFTPU_FAILPOINTS`` / ``DFTPU_FAILPOINTS_SEED``; a
    missing/empty var leaves the current state alone (so an in-process
    ``configure`` is not clobbered by a late import)."""
    spec = os.environ.get("DFTPU_FAILPOINTS", "").strip()
    if not spec:
        return 0
    return configure(spec, seed=int(os.environ.get(
        "DFTPU_FAILPOINTS_SEED", "0") or 0))


def deactivate() -> None:
    """Disarm every site; every ``failpoint()`` is a no-op again."""
    configure(None)


def is_active(name: Optional[str] = None) -> bool:
    if not _enabled:
        return False
    with _lock:
        return name in _armed if name is not None else bool(_armed)


def fired(name: str) -> int:
    """How many times the named site has fired since configure()."""
    with _lock:
        return _fired.get(name, 0)


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_fired)


def _draw(name: str) -> Optional[tuple]:
    """Decide whether ``name`` fires this evaluation; returns the armed
    ``(action, arg)`` when it does.  All registry mutation happens here,
    under the lock; the action itself executes outside it."""
    with _lock:
        armed = _armed.get(name)
        if armed is None or armed.count == 0:
            return None
        if armed.prob < 1.0 and _rng.random() >= armed.prob:
            return None
        if armed.count > 0:
            armed.count -= 1
        _fired[name] = _fired.get(name, 0) + 1
        return armed.action, armed.arg


def failpoint(name: str) -> None:
    """A fault-injection site.  Disabled (the production state), this is
    one global-bool test; armed, it may raise, sleep, or kill the
    process according to the active spec."""
    if not _enabled:
        return
    hit = _draw(name)
    if hit is None:
        return
    action, arg = hit
    if action == "raise":
        raise _resolve_exception(arg)(f"failpoint {name}")
    if action == "sleep":
        time.sleep(float(arg) / 1000.0)
        return
    if action == "kill9":
        os.kill(os.getpid(), signal.SIGKILL)
    # "corrupt" at a plain site has nothing to corrupt: ignore, so one
    # spec can arm a data site without tripping same-named plain sites


def failpoint_data(name: str, data: bytes) -> bytes:
    """A data-mangling site: returns ``data`` possibly corrupted.

    ``corrupt`` (or ``corrupt flip``) flips one byte in the middle —
    the checksum-mismatch fault; ``corrupt truncate`` drops the second
    half — the torn/partial-write fault.  Non-corrupt actions behave as
    at a plain site (raise/sleep/kill9 still work here)."""
    if not _enabled:
        return data
    hit = _draw(name)
    if hit is None:
        return data
    action, arg = hit
    if action != "corrupt":
        if action == "raise":
            raise _resolve_exception(arg)(f"failpoint {name}")
        if action == "sleep":
            time.sleep(float(arg) / 1000.0)
            return data
        if action == "kill9":
            os.kill(os.getpid(), signal.SIGKILL)
        return data
    if not data:
        return data
    if arg == "truncate":
        return data[: max(len(data) // 2, 1) - 1]
    mid = len(data) // 2
    return data[:mid] + bytes([data[mid] ^ 0xFF]) + data[mid + 1:]


# Replica subprocesses (serving/replica.py children) inherit the chaos
# harness's environment; arming at import means no per-module plumbing.
configure_from_env()
