"""dftsan runtime: an opt-in concurrency sanitizer for the serving stack.

dflint's lock rules (``analysis/rules_lockorder.py``) model the code:
they build the acquired-while-holding graph from the AST and flag cycles
and blocking calls.  This module observes the same locks at runtime — the
static+dynamic pairing ThreadSanitizer uses — and feeds what it sees back
into the dflint pipeline through ``analysis/dftsan.py``:

* **lock instrumentation** — :func:`attach` replaces the ``threading``
  primitives a class owns with wrappers that record acquisition order
  (the observed edge set, keyed by the SAME ``(relpath, class, attr)``
  lock ids the static analysis uses), hold time, and owner threads;
* **guarded attributes** — a declared ``{lock_attr: (attr, ...)}`` map
  turns those attrs into data descriptors that flag any read/write made
  without the owning lock held, with stack + thread provenance;
* **schedule perturbation** — every instrumented acquire/release runs the
  ``sanitizer.yield`` failpoint, so arming e.g.
  ``sanitizer.yield=sleep 1:0.05`` (seeded, via the PR-14 registry)
  deterministically shakes interleavings under ``make tsan``.

Disabled — the default, and the only state production runs in — the whole
module is one module-global boolean test: :func:`attach` returns before
touching the object, so instances keep their raw ``threading`` primitives
and their original class; the hot paths are structurally identical to a
build without the sanitizer (same contract as ``failpoints.py``, and why
the perf sentinel's ``--strict`` gate holds).

Enable BEFORE constructing the objects under test (instances built while
disabled stay uninstrumented)::

    DFTPU_TSAN=1                          # enable at import
    DFTPU_TSAN_REPORT_DIR=/tmp/dftsan     # atexit: one JSON per process
    DFTPU_FAILPOINTS="sanitizer.yield=sleep 1:0.05"   # optional shaking
    DFTPU_FAILPOINTS_SEED=42

or, from a test: ``sanitizer.configure()`` / ``sanitizer.deactivate()``.
``analysis/dftsan.py`` cross-checks the written report against the static
lock graph and renders findings (text/json/sarif, baseline, suppressions).

Known approximations, by design:

* ``Condition.wait`` bookkeeping marks the condition released for the
  wait window and re-held on wakeup (``wait_for`` is re-implemented on
  top of ``wait`` so the predicate runs with the lock marked held);
* bare ``acquire()/release()`` call pairs are tracked, but a release on
  a thread that never acquired through the wrapper is ignored rather
  than guessed at — same scope limit the static rules document.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Dict, Iterable, Mapping, Optional, Tuple

from distributed_forecasting_tpu.monitoring.failpoints import failpoint

__all__ = [
    "attach",
    "configure",
    "configure_from_env",
    "deactivate",
    "is_enabled",
    "reset",
    "snapshot",
    "write_report",
]

#: same shape as analysis.rules_lockorder.LockId — the join key between
#: the observed and the static lock graphs
LockId = Tuple[str, Optional[str], str]

# ``_enabled`` is the ONLY thing a disabled call path reads: attach() and
# the guarded descriptors test it first, same fast-path contract as
# failpoints._enabled.  Everything below it is touched only while enabled.
_enabled = False

_lock = threading.Lock()          # recorder lock; deliberately raw
_tls = threading.local()
_report_path: Optional[str] = None

_MAX_EDGES = 512                  # distinct (src, dst) pairs kept
_MAX_VIOLATION_SITES = 256        # distinct (lock, attr, op, site) kept
_MAX_THREADS_PER_LOCK = 8

#: LockId -> {"kind", "acquires", "max_hold_ms", "total_hold_ms", threads}
_locks: Dict[LockId, dict] = {}
#: (src LockId, dst LockId) -> {"count", "path", "line", "thread"}
_edges: Dict[Tuple[LockId, LockId], dict] = {}
#: (LockId, attr, op, path, line) -> {"count", "thread", "stack"}
_violations: Dict[tuple, dict] = {}
_dropped = {"edges": 0, "violations": 0}

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SELF_FILE = os.path.abspath(__file__).rstrip("co")  # .pyc -> .py


def _relpath(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(_REPO_ROOT + os.sep):
        return os.path.relpath(ap, _REPO_ROOT).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _call_site(depth_hint: int = 2) -> Tuple[str, int, str]:
    """(relpath, line, short stack) of the nearest caller frame outside
    this module — the provenance attached to edges and violations."""
    try:
        frame = sys._getframe(depth_hint)
    except ValueError:
        frame = sys._getframe(1)
    site: Optional[Tuple[str, int]] = None
    stack = []
    while frame is not None and len(stack) < 3:
        fname = frame.f_code.co_filename
        if os.path.abspath(fname).rstrip("co") != _SELF_FILE:
            rel = _relpath(fname)
            if site is None:
                site = (rel, frame.f_lineno)
            stack.append(f"{rel}:{frame.f_lineno} in "
                         f"{frame.f_code.co_name}")
        frame = frame.f_back
    if site is None:
        return "<unknown>", 0, ""
    return site[0], site[1], " <- ".join(stack)


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def _record_acquire(sync: "_InstrumentedSync") -> None:
    path, line, _ = _call_site(3)
    tname = threading.current_thread().name
    stack = _held_stack()
    with _lock:
        st = _locks.get(sync.lock_id)
        if st is None:
            st = _locks[sync.lock_id] = {
                "kind": sync.kind, "acquires": 0,
                "max_hold_ms": 0.0, "total_hold_ms": 0.0, "threads": set()}
        st["acquires"] += 1
        if len(st["threads"]) < _MAX_THREADS_PER_LOCK:
            st["threads"].add(tname)
        for held in stack:
            key = (held, sync.lock_id)
            edge = _edges.get(key)
            if edge is not None:
                edge["count"] += 1
            elif len(_edges) < _MAX_EDGES:
                _edges[key] = {"count": 1, "path": path, "line": line,
                               "thread": tname}
            else:
                _dropped["edges"] += 1
    stack.append(sync.lock_id)


def _record_release(sync: "_InstrumentedSync", held_s: float) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == sync.lock_id:
            del stack[i]
            break
    ms = held_s * 1000.0
    with _lock:
        st = _locks.get(sync.lock_id)
        if st is not None:
            st["total_hold_ms"] += ms
            if ms > st["max_hold_ms"]:
                st["max_hold_ms"] = ms


def _record_violation(lock_id: LockId, attr: str, op: str) -> None:
    path, line, stack = _call_site(3)
    key = (lock_id, attr, op, path, line)
    with _lock:
        hit = _violations.get(key)
        if hit is not None:
            hit["count"] += 1
        elif len(_violations) < _MAX_VIOLATION_SITES:
            _violations[key] = {
                "count": 1, "thread": threading.current_thread().name,
                "stack": stack}
        else:
            _dropped["violations"] += 1


# ---------------------------------------------------------------------------
# instrumented primitives
# ---------------------------------------------------------------------------

_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())


def _kind_of(obj) -> Optional[str]:
    if isinstance(obj, threading.Condition):
        return "condition"
    if isinstance(obj, _RLOCK_TYPE):
        return "rlock"
    if isinstance(obj, _LOCK_TYPE):
        return "lock"
    return None


class _InstrumentedSync:
    """Wraps one Lock/RLock/Condition; records order/hold/owner and runs
    the ``sanitizer.yield`` perturbation point at both boundaries."""

    __slots__ = ("_inner", "lock_id", "kind", "_owner", "_depth", "_acq_t")

    def __init__(self, inner, lock_id: LockId, kind: str):
        self._inner = inner
        self.lock_id = lock_id
        self.kind = kind
        self._owner: Optional[int] = None
        self._depth = 0
        self._acq_t = 0.0

    # -- core protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        failpoint("sanitizer.yield")
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        self._note_released()
        self._inner.release()
        failpoint("sanitizer.yield")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else \
            self._owner is not None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    # -- bookkeeping --------------------------------------------------------

    def _note_acquired(self) -> None:
        me = threading.get_ident()
        if self.kind == "rlock" and self._owner == me:
            self._depth += 1
            return
        self._owner = me
        self._depth = 1
        self._acq_t = time.monotonic()
        _record_acquire(self)

    def _note_released(self) -> None:
        if self._owner != threading.get_ident():
            return  # release by a non-owner: let the primitive raise
        if self.kind == "rlock" and self._depth > 1:
            self._depth -= 1
            return
        held = time.monotonic() - self._acq_t
        self._owner = None
        self._depth = 0
        _record_release(self, held)

    # -- condition surface --------------------------------------------------

    def wait(self, timeout: Optional[float] = None):
        # wait atomically releases the underlying lock: mirror that in the
        # bookkeeping so a concurrent holder is not a fabricated violation
        self._note_released()
        try:
            return self._inner.wait(timeout)
        finally:
            self._note_acquired()  # wait() reacquired before returning

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # reimplemented over self.wait() so the predicate runs with the
        # lock MARKED held (delegating would evaluate it "unlocked")
        endtime: Optional[float] = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                remaining = endtime - time.monotonic()
                if remaining <= 0:
                    break
                self.wait(remaining)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# ---------------------------------------------------------------------------
# guarded attributes
# ---------------------------------------------------------------------------


class _GuardedAttr:
    """Data descriptor: the value stays in the instance ``__dict__`` under
    its own name; every attribute-protocol access is checked against the
    owning instrumented lock."""

    __slots__ = ("name", "lock_attr")

    def __init__(self, name: str, lock_attr: str):
        self.name = name
        self.lock_attr = lock_attr

    def _check(self, obj, op: str) -> None:
        if not _enabled:
            return
        sync = obj.__dict__.get(self.lock_attr)
        if isinstance(sync, _InstrumentedSync) and not sync.held_by_me():
            _record_violation(sync.lock_id, self.name, op)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            value = obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None
        self._check(obj, "read")
        return value

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        obj.__dict__[self.name] = value

    def __delete__(self, obj) -> None:
        self._check(obj, "write")
        del obj.__dict__[self.name]


_sanitized_classes: Dict[tuple, type] = {}


def _sanitized_class(cls: type, guard_items: tuple) -> type:
    key = (cls, guard_items)
    sub = _sanitized_classes.get(key)
    if sub is None:
        ns = {"_dftsan_attached": True}
        for lock_attr, attrs in guard_items:
            for attr in attrs:
                ns[attr] = _GuardedAttr(attr, lock_attr)
        sub = type(cls.__name__, (cls,), ns)
        sub.__module__ = cls.__module__
        sub.__qualname__ = cls.__qualname__
        _sanitized_classes[key] = sub
    return sub


# ---------------------------------------------------------------------------
# public surface
# ---------------------------------------------------------------------------


def attach(obj, cls: Optional[type] = None,
           guards: Optional[Mapping[str, Iterable[str]]] = None,
           locks: Iterable[str] = ()):
    """Instrument ``obj`` in place; returns it.

    Disabled, this is one boolean test and the object is untouched —
    same class, same raw ``threading`` primitives.  Enabled:

    * every attr in ``locks`` and every ``guards`` key holding a
      Lock/RLock/Condition is wrapped in :class:`_InstrumentedSync`,
      identified as ``(relpath-of-cls-module, cls.__name__, attr)`` —
      pass ``cls`` explicitly from ``__init__`` so a subclass instance
      still records the ids the static analysis catalogued;
    * ``guards`` maps each lock attr to the attrs it protects; those
      become checked descriptors (the instance's class is swapped to a
      cached subclass — call attach LAST in ``__init__``).
    """
    if not _enabled:
        return obj
    owner = cls if cls is not None else type(obj)
    relpath = owner.__module__.replace(".", "/") + ".py"
    guard_map = {k: tuple(v) for k, v in (guards or {}).items()}
    for attr in sorted(set(locks) | set(guard_map)):
        inner = obj.__dict__.get(attr)
        if inner is None or isinstance(inner, _InstrumentedSync):
            continue
        kind = _kind_of(inner)
        if kind is None:
            continue
        obj.__dict__[attr] = _InstrumentedSync(
            inner, (relpath, owner.__name__, attr), kind)
    if guard_map and not getattr(type(obj), "_dftsan_attached", False):
        obj.__class__ = _sanitized_class(
            type(obj), tuple(sorted(guard_map.items())))
    return obj


def configure(report_path: Optional[str] = None) -> None:
    """Enable the sanitizer (and optionally set the atexit report target).
    Objects must be constructed AFTER this to be instrumented."""
    global _enabled, _report_path
    with _lock:
        if report_path is not None:
            _report_path = report_path
        _enabled = True


def deactivate() -> None:
    """Disable.  Already-instrumented objects keep their wrappers but the
    descriptors stop checking; new constructions are left raw."""
    global _enabled
    with _lock:
        _enabled = False


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop all recorded data (test isolation)."""
    with _lock:
        _locks.clear()
        _edges.clear()
        _violations.clear()
        _dropped["edges"] = 0
        _dropped["violations"] = 0
    _tls.stack = []


def snapshot() -> dict:
    """The event report ``analysis/dftsan.py`` consumes."""
    with _lock:
        return {
            "version": 1,
            "pid": os.getpid(),
            "locks": [
                {"id": list(lid), "kind": st["kind"],
                 "acquires": st["acquires"],
                 "max_hold_ms": round(st["max_hold_ms"], 3),
                 "total_hold_ms": round(st["total_hold_ms"], 3),
                 "threads": sorted(st["threads"])}
                for lid, st in sorted(_locks.items())],
            "edges": [
                {"src": list(src), "dst": list(dst), "count": e["count"],
                 "path": e["path"], "line": e["line"],
                 "thread": e["thread"]}
                for (src, dst), e in sorted(_edges.items())],
            "violations": [
                {"lock": list(lid), "attr": attr, "op": op, "path": path,
                 "line": line, "count": v["count"], "thread": v["thread"],
                 "stack": v["stack"]}
                for (lid, attr, op, path, line), v
                in sorted(_violations.items())],
            "dropped": dict(_dropped),
        }


def write_report(path: str) -> str:
    """Write the snapshot as JSON; creates parent dirs.  Returns the
    resolved file path (a directory target gets a pid-named file)."""
    if os.path.isdir(path) or path.endswith(os.sep):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, f"dftsan-{os.getpid()}.json")
    else:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def _atexit_report() -> None:
    if _report_path and (_locks or _violations):
        try:
            write_report(_report_path)
        except OSError:
            pass  # a dying process must not fail in atexit


def configure_from_env() -> bool:
    """``DFTPU_TSAN=1`` enables at import; ``DFTPU_TSAN_REPORT`` (file)
    or ``DFTPU_TSAN_REPORT_DIR`` (directory, one pid-named file per
    process — what replica subprocesses under ``make tsan`` use) arms the
    atexit report dump."""
    if os.environ.get("DFTPU_TSAN", "").strip().lower() not in (
            "1", "true", "yes"):
        return False
    target = os.environ.get("DFTPU_TSAN_REPORT", "").strip()
    if not target:
        d = os.environ.get("DFTPU_TSAN_REPORT_DIR", "").strip()
        if d:
            target = os.path.join(d, f"dftsan-{os.getpid()}.json")
    configure(report_path=target or None)
    return True


atexit.register(_atexit_report)
configure_from_env()
