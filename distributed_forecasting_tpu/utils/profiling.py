"""Tracing/profiling hooks.

The reference has no tracer (SURVEY.md §5): observability is the MLflow run
tree plus the Spark UI.  Here:

  * :class:`PhaseTimer` — wall-clock per named phase (tensorize / cv / fit /
    write...), loggable straight into a tracking run as metrics — run-level
    tracing that survives into the experiment store;
  * :func:`device_trace` — context manager around ``jax.profiler`` emitting a
    TensorBoard-loadable device trace when requested (gated: profiling absent
    or broken never breaks a run).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional


class PhaseTimer:
    def __init__(self) -> None:
        self._durations: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.time()
        try:
            yield
        finally:
            self._durations[name] = self._durations.get(name, 0.0) + time.time() - t0

    def metrics(self, prefix: str = "phase_") -> Dict[str, float]:
        return {f"{prefix}{k}_seconds": round(v, 4) for k, v in self._durations.items()}

    def total(self) -> float:
        return sum(self._durations.values())


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace into ``log_dir`` (None = disabled no-op)."""
    if not log_dir:
        yield
        return
    try:
        import jax.profiler as _prof

        _prof.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - profiler unavailable
        started = False
    try:
        yield
    finally:
        if started:
            try:
                _prof.stop_trace()
            except Exception:  # pragma: no cover
                pass
