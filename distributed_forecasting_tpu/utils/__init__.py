from distributed_forecasting_tpu.utils.logging import get_logger
from distributed_forecasting_tpu.utils.config import load_conf, parse_conf_args
from distributed_forecasting_tpu.utils.platform import apply_platform_override

__all__ = ["apply_platform_override", "get_logger", "load_conf", "parse_conf_args"]
