from distributed_forecasting_tpu.utils.logging import get_logger
from distributed_forecasting_tpu.utils.config import load_conf, parse_conf_args

__all__ = ["get_logger", "load_conf", "parse_conf_args"]
