"""Platform override for task/CLI entry points.

``DFTPU_PLATFORM=cpu`` forces the JAX backend through
``jax.config.update("jax_platforms", ...)`` — the route that actually
works in this environment.  The plain ``JAX_PLATFORMS`` env var is NOT
sufficient when an ambient sitecustomize registers a remote-accelerator
PJRT plugin with a patched ``get_backend``: that patch initializes its
client regardless of the env filter, and a degraded remote tunnel then
hangs every device access (observed 2026-07-30: ``JAX_PLATFORMS=cpu``
blocked >60 s inside ``make_c_api_client`` while the config route ran
instantly).  Call this BEFORE any ``jax.devices()``/array creation.
"""

from __future__ import annotations

import os


def apply_platform_override() -> str | None:
    """Apply ``DFTPU_PLATFORM`` if set; returns the platform or None.

    Safe to call repeatedly.  Raises if a DIFFERENT backend was already
    initialized: the config update is silently ignored post-init (it is a
    plain config value with no re-init hook), and logging a fake success
    while the process stays on a hung accelerator would defeat the escape
    hatch's purpose — callers must invoke this at process entry, before
    any device access.
    """
    plat = os.environ.get("DFTPU_PLATFORM")
    if not plat:
        return None
    import jax

    jax.config.update("jax_platforms", plat)
    actual = jax.default_backend()  # initializes the backend NOW if not yet
    if actual != plat:
        raise RuntimeError(
            f"DFTPU_PLATFORM={plat!r} requested but the JAX backend was "
            f"already initialized to {actual!r} — set the override before "
            f"any jax.devices()/array use in this process"
        )
    return plat
