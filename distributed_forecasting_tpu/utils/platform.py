"""Platform override for task/CLI entry points.

``DFTPU_PLATFORM=cpu`` forces the JAX backend through
``jax.config.update("jax_platforms", ...)`` — the route that actually
works in this environment.  The plain ``JAX_PLATFORMS`` env var is NOT
sufficient when an ambient sitecustomize registers a remote-accelerator
PJRT plugin with a patched ``get_backend``: that patch initializes its
client regardless of the env filter, and a degraded remote tunnel then
hangs every device access (observed 2026-07-30: ``JAX_PLATFORMS=cpu``
blocked >60 s inside ``make_c_api_client`` while the config route ran
instantly).  Call this BEFORE any ``jax.devices()``/array creation.
"""

from __future__ import annotations

import os


def _initialized_backends() -> dict | None:
    """The xla_bridge backend cache WITHOUT populating it, or None when no
    probe resolves under this jax version.

    Probe chain: the canonical private module first, then the long-standing
    ``jax.lib.xla_bridge`` alias (the closest thing to a public route to the
    same cache).  Detection must stay lazy — every genuinely public API that
    names the current backend (``jax.devices``, ``jax.default_backend``,
    ``jax.extend.backend.get_backend``) *initializes* one, which is exactly
    what the too-late-override guard exists to avoid.  A unit test
    (tests/unit/test_tasks.py) pins this to not return None so a jax bump
    that moves the cache fails loudly instead of silently degrading."""
    import jax  # noqa: F401  (both probe routes hang off the jax package)

    try:
        from jax._src import xla_bridge

        return xla_bridge._backends
    except (ImportError, AttributeError):
        pass
    try:
        backends = jax.lib.xla_bridge._backends
        if isinstance(backends, dict):
            return backends
    except AttributeError:
        pass
    return None


def apply_platform_override() -> str | None:
    """Apply ``DFTPU_PLATFORM`` if set; returns the platform or None.

    Safe to call repeatedly, and LAZY: it only records the platform in jax
    config — it never initializes the XLA backend itself.  That matters for
    multi-host bring-up: ``jax.distributed.initialize()`` must run before
    any backend init, and the Task harness applies this override first
    (``tasks/common.py``), so an eager ``jax.default_backend()`` here would
    kill every distributed launch whose environment carries the override
    (the documented configuration during accelerator outages).  The config
    route is sufficient — ``jax_platforms`` governs backend selection at
    whatever point the first genuine device access happens.

    The one case verified eagerly is the one that NEEDS eager detection: a
    backend already initialized to a different platform.  The config update
    is silently ignored post-init (plain config value, no re-init hook),
    and logging a fake success while the process stays on a hung
    accelerator would defeat the escape hatch's purpose — so that raises.
    Detection reads the xla_bridge backend cache without populating it.
    """
    plat = os.environ.get("DFTPU_PLATFORM")
    if not plat:
        return None
    import jax

    jax.config.update("jax_platforms", plat)
    backends = _initialized_backends()
    if backends is None:
        # every probe route moved under a jax upgrade: stay lazy (the config
        # update above still governs selection) but say loudly that the
        # too-late-override guard is gone rather than silently skipping it
        import warnings

        warnings.warn(
            "jax xla_bridge backend cache is unavailable under this jax "
            "version — DFTPU_PLATFORM too-late-override detection disabled",
            RuntimeWarning,
        )
        already_initialized = False
    else:
        already_initialized = bool(backends)
    if already_initialized:
        # backend(s) exist already — default_backend() is a cached lookup
        # here, not an init
        actual = jax.default_backend()
        if actual != plat:
            raise RuntimeError(
                f"DFTPU_PLATFORM={plat!r} requested but the JAX backend was "
                f"already initialized to {actual!r} — set the override before "
                f"any jax.devices()/array use in this process"
            )
    return plat
