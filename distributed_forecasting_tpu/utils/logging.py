"""Logging — plain stdlib logging where the reference bridges to Spark's
log4j over py4j (reference ``forecasting/common.py:88-96``).  No JVM here, so
the logger is a normal Python logger with one consistent format."""

from __future__ import annotations

import logging
import sys

_FORMAT = "[dftpu][%(asctime)s][%(name)s][%(levelname)s] %(message)s"


def get_logger(name: str = "dftpu", level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger
