"""Layered YAML config — the reference's conf system, minus dbx.

Reference behavior reproduced (``forecasting/common.py:63-86``):
  * ``--conf-file <path>`` parsed with ``parse_known_args`` so unrecognized
    job-runner arguments pass through untouched;
  * missing conf file -> empty dict with a warning, not a crash;
  * tests/jobs can inject a dict directly and skip argv entirely
    (``Task(init_conf=...)``, used by the reference's integration test).

Engine-level flags (mesh shape, precision, padding buckets) ride in the same
YAML under an ``engine:`` key — the third tier the reference implements as
``spark.conf.set`` calls (``notebooks/prophet/02_training.py:127-128``).
"""

from __future__ import annotations

import argparse
from collections.abc import Mapping
from typing import Any, Dict, List, Optional

import yaml


def load_conf(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return yaml.safe_load(f) or {}


class FrozenMap(Mapping):
    """Immutable, hashable mapping for dict-valued config fields.

    Reads like a dict (so ``**cfg`` / ``cfg[key]`` consumers keep working)
    but hashes, so a config dataclass holding one stays a valid static jit
    argument.  Values must already be frozen (``freeze`` guarantees this).
    """

    __slots__ = ("_d",)

    def __init__(self, d):
        object.__setattr__(self, "_d", dict(d))

    def __getitem__(self, k):
        return self._d[k]

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def __hash__(self):
        return hash(tuple(sorted(self._d.items())))

    def __eq__(self, other):
        if isinstance(other, Mapping):
            return dict(self._d) == dict(other)
        return NotImplemented

    def __repr__(self):
        return f"FrozenMap({self._d!r})"


def to_jsonable(x, strict: bool = False):
    """Coerce frozen-config / numpy values to plain JSON types.

    The single coercion rule shared by the tracker param store
    (``tracking/filestore.py``) and the forecaster artifact meta
    (``serving/predictor.py``) — one place to extend when a new config value
    type appears, so the two serializations cannot diverge.  ``strict=True``
    raises on unknown types (artifact meta must round-trip); the default
    degrades to ``str(x)`` (tracker params are display-oriented).
    """
    import numpy as np

    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if isinstance(x, (np.floating, np.integer)):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, Mapping):  # e.g. FrozenMap
        return {k: to_jsonable(v, strict) for k, v in x.items()}
    if isinstance(x, (tuple, list)):
        return [to_jsonable(v, strict) for v in x]
    if strict:
        raise TypeError(f"not JSON serializable: {type(x).__name__}")
    return str(x)


def freeze(value):
    """Recursively turn lists into tuples and dicts into hashable maps.

    YAML and JSON both deliver sequences as lists and mappings as dicts, but
    model config dataclasses are static jit arguments and must stay hashable
    — every config constructed from conf files or persisted metadata goes
    through this (training pipeline, serving artifact load).
    """
    if isinstance(value, list):
        return tuple(freeze(v) for v in value)
    if isinstance(value, dict):
        return FrozenMap({k: freeze(v) for k, v in value.items()})
    return value


def parse_conf_args(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--conf-file", dest="conf_file", default=None)
    ns, _unknown = p.parse_known_args(argv)
    if ns.conf_file is None:
        return {}
    try:
        return load_conf(ns.conf_file)
    except FileNotFoundError:
        return {}
