"""Layered YAML config — the reference's conf system, minus dbx.

Reference behavior reproduced (``forecasting/common.py:63-86``):
  * ``--conf-file <path>`` parsed with ``parse_known_args`` so unrecognized
    job-runner arguments pass through untouched;
  * missing conf file -> empty dict with a warning, not a crash;
  * tests/jobs can inject a dict directly and skip argv entirely
    (``Task(init_conf=...)``, used by the reference's integration test).

Engine-level flags (mesh shape, precision, padding buckets) ride in the same
YAML under an ``engine:`` key — the third tier the reference implements as
``spark.conf.set`` calls (``notebooks/prophet/02_training.py:127-128``).
"""

from __future__ import annotations

import argparse
from typing import Any, Dict, List, Optional

import yaml


def load_conf(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return yaml.safe_load(f) or {}


def freeze(value):
    """Recursively turn lists into tuples.

    YAML and JSON both deliver sequences as lists, but model config
    dataclasses are static jit arguments and must stay hashable — every
    config constructed from conf files or persisted metadata goes through
    this (training pipeline, serving artifact load).
    """
    if isinstance(value, list):
        return tuple(freeze(v) for v in value)
    return value


def parse_conf_args(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--conf-file", dest="conf_file", default=None)
    ns, _unknown = p.parse_known_args(argv)
    if ns.conf_file is None:
        return {}
    try:
        return load_conf(ns.conf_file)
    except FileNotFoundError:
        return {}
