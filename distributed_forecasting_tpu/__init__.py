"""distributed_forecasting_tpu — a TPU-native fine-grained demand-forecasting framework.

Capability-parity rebuild of the reference Spark/Prophet solution accelerator
(rafaelvp-db/distributed-forecasting): fit one seasonal-trend model per
(store, item) series at 500+-series scale, cross-validate, track every fit,
register a batched-inference model, and run distributed fine-grained
prediction.

Where the reference fans independent Prophet/Stan fits out over Spark
executors (`notebooks/prophet/02_training.py:304-307` in the reference), this
framework tensorizes all series into one padded ``(n_series, T)`` batch and
fits them in a single XLA-compiled program — ``jit(vmap(fit))`` on one chip,
``shard_map`` over a ``jax.sharding.Mesh`` across a pod slice.

Layer map (mirrors SURVEY.md §1):
  - L1 data plane ......... :mod:`distributed_forecasting_tpu.data`
  - L2 model kernels ...... :mod:`distributed_forecasting_tpu.models`
  - L2 tracking/registry .. :mod:`distributed_forecasting_tpu.tracking`
  - L3 fit/CV engine ...... :mod:`distributed_forecasting_tpu.engine`
  - L3 batched serving .... :mod:`distributed_forecasting_tpu.serving`
  - L4/L5 tasks ........... :mod:`distributed_forecasting_tpu.tasks`
  - L6 workflows/CLI ...... :mod:`distributed_forecasting_tpu.workflows`
  - scale-out ............. :mod:`distributed_forecasting_tpu.parallel`
"""

from distributed_forecasting_tpu.version import __version__  # noqa: F401
