"""distributed_forecasting_tpu — a TPU-native fine-grained demand-forecasting framework.

Capability-parity rebuild of the reference Spark/Prophet solution accelerator
(rafaelvp-db/distributed-forecasting): fit one seasonal-trend model per
(store, item) series at 500+-series scale, cross-validate, track every fit,
register a batched-inference model, and run distributed fine-grained
prediction.

Where the reference fans independent Prophet/Stan fits out over Spark
executors (`notebooks/prophet/02_training.py:304-307` in the reference), this
framework tensorizes all series into one padded ``(n_series, T)`` batch and
fits them in a single XLA-compiled program — ``jit(vmap(fit))`` on one chip,
``shard_map`` over a ``jax.sharding.Mesh`` across a pod slice.

Layer map (mirrors SURVEY.md §1):
  - L1 data plane ......... :mod:`distributed_forecasting_tpu.data`
  - L2 model kernels ...... :mod:`distributed_forecasting_tpu.models`
  - L2 tracking/registry .. :mod:`distributed_forecasting_tpu.tracking`
  - L3 fit/CV engine ...... :mod:`distributed_forecasting_tpu.engine`
  - L3 batched serving .... :mod:`distributed_forecasting_tpu.serving`
  - L4/L5 tasks ........... :mod:`distributed_forecasting_tpu.tasks`
  - L6 workflows/CLI ...... :mod:`distributed_forecasting_tpu.workflows`
  - scale-out ............. :mod:`distributed_forecasting_tpu.parallel`
"""

from distributed_forecasting_tpu.version import __version__  # noqa: F401

# DFTPU_PLATFORM=cpu escape hatch at PACKAGE import, so every entry point —
# examples, bench scripts, ad-hoc shells, not just Task CLIs — gets the
# working platform-override route before any device access (a degraded
# remote accelerator otherwise hangs the first jax.devices() touch; see
# utils/platform.py).  Guarded on the env var so the common no-override
# import stays as light as before (no utils/yaml import), and a too-late
# override WARNS here rather than failing the package import — Task init
# re-applies it and raises with entry-point context.
import os as _os

if _os.environ.get("DFTPU_PLATFORM"):
    from distributed_forecasting_tpu.utils.platform import (
        apply_platform_override as _apply_platform_override,
    )

    try:
        _apply_platform_override()
    except RuntimeError as _e:
        import warnings as _warnings

        _warnings.warn(str(_e), RuntimeWarning)
