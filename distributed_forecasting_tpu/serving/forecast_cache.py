"""Materialized forecast cache: sub-millisecond reads, write-path epochs.

The read-heavy serving regime (ROADMAP item 2) is a huge fan of identical
``/invocations`` reads between rare writes, yet every read pays a full
batched device dispatch (~7 ms warm p50 on CPU).  The reference repo's
whole serving model is precomputed batch forecasts persisted to tables;
this module adopts that natively on top of the atomic install hooks the
streaming stack already has:

* after a state install (``BatchForecaster.swap_state`` — the ONE commit
  point every writer funnels through: streaming apply, full-refit install,
  windowed tail-refit, day1-only grid advances), the owning process
  recomputes each resident signature's forecast frame in ONE batched
  full-S dispatch through the unchanged ``predictor.py`` machinery;
* reads become row gathers out of that frame.  Because BatchForecaster's
  predict returns request-order per-series blocks that are BIT-IDENTICAL
  across request-size buckets (``coalesce_safe`` — the same property the
  coalescer scatters on), a gather of series rows out of the full-S frame
  is byte-for-byte what a dedicated dispatch for that request would have
  served;
* only misses (cold signature, rebuild in flight, raced epoch) and exotic
  requests (xreg, include_history, unlisted quantile sets, horizons past
  the admission cap) fall through to the RequestBatcher / direct dispatch.

Torn/stale reads are impossible by construction: entries are tagged with
the state generation captured in the same locked snapshot the rebuild
predict reads from, a read only serves an entry whose epoch equals the
CURRENT generation, and a rebuild that a writer overtakes is discarded at
publish.  The staleness contract is therefore "a read observes either the
pre-install frame before the install commits or the post-install state
after, never a mix and never an old frame after commit".

Entries optionally persist to an mmap-backed directory (``mmap_dir``):
one ``.npy`` payload + one ``.meta.json`` commit record per signature,
written temp-then-rename with a ``cache.persist`` failpoint at the
boundary, validated on load (``cache.load``) against a sha256 payload
digest AND a fingerprint of the live model state — a restart with changed
state quietly discards and falls through to dispatch, never serves stale.

Config is the strict ``serving.cache`` block (unknown keys hard-error)::

    serving:
      cache:
        enabled: true
        max_horizons: 4          # distinct horizons admitted per process
        quantile_sets: [[0.1, 0.5, 0.9]]   # quantile reads served cached
        mmap_dir: null           # persistence off by default
        max_bytes: 268435456     # in-memory budget; oldest entries evicted

Telemetry: ``dftpu_cache_*`` counters (hits/misses-by-reason/
invalidations/rebuilds/evictions/persist+load outcomes), entry-count and
resident-bytes gauges, and an entry-age gauge the fleet aggregator
max-merges (the oldest cached frame anywhere is the staleness headline).
Lookups, rebuilds, persists and loads land on the trace path as
``cache.*`` spans.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.utils import get_logger

_META_SUFFIX = ".meta.json"
_PAYLOAD_SUFFIX = ".npy"
_PERSIST_FORMAT = "dftpu-forecast-cache-v1"


def canonical_quantiles(quantiles) -> Tuple[float, ...]:
    """The server's quantile canonicalization (sort, dedupe, round to 3
    decimals) — one function so the cache signature can never drift from
    what ``server._invoke`` actually dispatches."""
    return tuple(sorted({round(float(q), 3) for q in quantiles}))


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """The ``serving.cache`` conf block (tasks/serve.py)."""

    enabled: bool = False
    max_horizons: int = 4          # distinct horizons admitted per process
    quantile_sets: tuple = ()      # canonical quantile tuples served cached
    mmap_dir: Optional[str] = None  # persistence directory (None = memory)
    max_bytes: int = 256 * 1024 * 1024

    def __post_init__(self):
        if self.max_horizons < 1:
            raise ValueError(
                f"max_horizons must be >= 1, got {self.max_horizons}")
        if self.max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {self.max_bytes}")
        for qs in self.quantile_sets:
            if not qs or not all(0.0 < q < 1.0 for q in qs):
                raise ValueError(
                    f"quantile_sets entries must be non-empty levels in "
                    f"(0, 1), got {qs!r}")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "CacheConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like max_horizon must not silently serve uncached
            raise ValueError(
                f"unknown serving.cache conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        qsets = conf.get("quantile_sets") or ()
        defaults = {f.name: f.default for f in dataclasses.fields(cls)}

        def pick(key):
            # explicit 0 must reach validation, not fall back to a default
            value = conf.get(key)
            return defaults[key] if value is None else value

        return cls(
            enabled=bool(conf.get("enabled", False)),
            max_horizons=int(pick("max_horizons")),
            quantile_sets=tuple(canonical_quantiles(qs) for qs in qsets),
            mmap_dir=conf.get("mmap_dir"),
            max_bytes=int(pick("max_bytes")),
        )


class CacheMetrics:
    """``dftpu_cache_*`` telemetry, one registry per cache instance,
    appended to the serving ``GET /metrics`` exposition.

    Fleet note: the counters SUM across replicas as usual; the
    ``entry_age_seconds`` gauge is max-merged by the fleet aggregator
    (serving/fleet.aggregate_prometheus) — the fleet's staleness headline
    is its OLDEST cached frame, and summing ages is meaningless.
    """

    def __init__(self) -> None:
        r = MetricsRegistry()
        self.registry = r
        self.hits = r.counter(
            "dftpu_cache_hits_total",
            "reads served as row gathers from a current-epoch cached frame")
        self.misses = r.labeled_counter(
            "dftpu_cache_misses_total", ("reason",),
            "reads that fell through to dispatch, by reason (cold: no "
            "entry yet; stale: entry epoch behind a write; rebuilding: "
            "another thread held the rebuild gate; bypass: xreg/"
            "include_history/unlisted quantile set; horizon_cap: distinct-"
            "horizon admission bound)")
        self.invalidations = r.counter(
            "dftpu_cache_invalidations_total",
            "resident entries invalidated by state installs (epoch bumps)")
        self.rebuilds = r.counter(
            "dftpu_cache_rebuilds_total",
            "full-S batched dispatches that materialized a cache frame")
        self.evictions = r.counter(
            "dftpu_cache_evictions_total",
            "entries evicted to hold the max_bytes budget")
        self.persists = r.counter(
            "dftpu_cache_persists_total",
            "entries durably persisted to the mmap directory")
        self.persist_errors = r.counter(
            "dftpu_cache_persist_errors_total",
            "persist attempts that failed (cache kept serving from memory)")
        self.loads = r.counter(
            "dftpu_cache_loads_total",
            "persisted entries adopted at boot after fingerprint + digest "
            "validation")
        self.load_errors = r.counter(
            "dftpu_cache_load_errors_total",
            "persisted entries discarded at boot (torn payload, digest or "
            "state-fingerprint mismatch) — served via dispatch instead")
        self.entries = r.gauge(
            "dftpu_cache_entries", "resident materialized frames")
        self.bytes = r.gauge(
            "dftpu_cache_bytes", "resident bytes across cached frames")
        self.entry_age = r.gauge(
            "dftpu_cache_entry_age_seconds",
            "age of the oldest resident frame since its rebuild (fleet "
            "mode: max-merged by the aggregator)")


#: distinct request shapes (series subsets) whose ASSEMBLED frames are
#: memoized per entry — the read-heavy regime repeats a small set of
#: requests, so repeat reads skip the ~150us DataFrame construction and
#: pay only a dict hit + shallow copy (~10us)
_FRAME_MEMO_MAX = 512


class _Entry:
    """One materialized frame: the full-S forecast for a signature.

    The payload (``ds``/``columns``/``values``) is immutable after
    construction — readers hold a reference snapshot and gather outside
    any lock, so invalidation can never tear a frame a read is mid-way
    through.  ``memo`` caches assembled request frames by series-index
    key; it is epoch-private (dies with the entry at invalidation) and
    its dict get/set are GIL-atomic, so no lock guards it.  ``body_memo``
    is the serialized-response byte cache — final encoded HTTP bodies by
    the same series-index key — with the identical epoch-private
    lifecycle: an epoch bump drops the entry and every memoized body with
    it, so stale bytes are impossible by construction."""

    __slots__ = ("sig", "epoch", "day1", "ds", "columns", "values",
                 "built_at", "nbytes", "memo", "body_memo")

    def __init__(self, sig, epoch, day1, ds, columns, values, built_at):
        self.sig = sig            # (horizon, quantile tuple | None)
        self.epoch = epoch        # state generation the frame was built from
        self.day1 = day1
        self.ds = ds              # (T,) date tile, one series' ds column
        self.columns = columns    # value column names in predict's order
        self.values = values      # (ncols, S, T) float32
        self.built_at = built_at  # monotonic clock
        self.nbytes = int(values.nbytes) + int(ds.nbytes)
        self.memo: Dict[bytes, pd.DataFrame] = {}
        self.body_memo: Dict[bytes, bytes] = {}


class ForecastCache:
    """Shard-local materialized forecast frames over a BatchForecaster.

    Concurrency contract (the dflint ``unlocked-shared-state`` shape):
    ``_lock`` guards the entry map and admission bookkeeping; reads take a
    reference snapshot of the (immutable) entry under the lock and gather
    rows outside it.  Rebuild dispatches and persist I/O are serialized by
    ``_rebuild_gate`` (a capacity semaphore, same discipline as the state
    store's apply gate) and never run under ``_lock``.
    """

    def __init__(self, forecaster, config: CacheConfig,
                 metrics: Optional[CacheMetrics] = None):
        self._fc = forecaster
        self.config = config
        self.metrics = metrics if metrics is not None else CacheMetrics()
        self.logger = get_logger("ForecastCache")
        self._lock = threading.Lock()
        self._entries: Dict[tuple, _Entry] = {}
        self._horizons: set = set()   # distinct horizons ever admitted
        self._bytes = 0
        # one rebuild/persist at a time: a capacity limiter, not a mutex
        # around shared attrs — dispatches run outside _lock
        self._rebuild_gate = threading.BoundedSemaphore(1)
        self._fp_cache: Tuple[int, str] = (-1, "")
        if config.mmap_dir:
            self._load_persisted()
        # subscribe AFTER the persisted adoption so a boot-time WAL replay
        # (replica.py replays before ready) invalidates adopted entries too
        forecaster.register_state_listener(self._on_state_install)
        # dftsan (no-op unless DFTPU_TSAN armed): the entry table + byte
        # accounting that every lookup/install/invalidate touches
        sanitizer.attach(self, cls=ForecastCache, guards={
            "_lock": ("_entries", "_horizons", "_bytes")})

    # -- read path -----------------------------------------------------------

    def lookup(self, frame: pd.DataFrame, horizon: int,
               include_history: bool, quantiles, on_missing: str,
               xreg) -> Optional[pd.DataFrame]:
        """Serve one parsed /invocations request from the cache, or return
        None to fall through to the dispatch path.  Raises exactly what the
        dispatch path would for unknown series, so the HTTP status story is
        identical on both paths."""
        entry, sidx = self._lookup_entry(frame, horizon, include_history,
                                         quantiles, on_missing, xreg)
        if entry is None:
            return None
        return self._gather(entry, sidx)

    def lookup_response(self, frame: pd.DataFrame, horizon: int,
                        include_history: bool, quantiles, on_missing: str,
                        xreg, encode) -> Optional[bytes]:
        """Serve the final ENCODED response body from the cache, or return
        None to fall through to dispatch — the transport-level sibling of
        :meth:`lookup` for handlers that would immediately serialize the
        frame anyway.  ``encode(frame) -> bytes`` is the caller's own
        serializer (the server passes its ``_encode_predictions``), run at
        most once per (entry, series subset): repeat hits return memoized
        bytes and skip frame assembly AND json encoding.  Same admission,
        metrics, epoch and UnknownSeriesError story as :meth:`lookup`;
        the memo dies with its entry on every epoch bump, so a stale body
        can never outlive the state it was encoded from."""
        entry, sidx = self._lookup_entry(frame, horizon, include_history,
                                         quantiles, on_missing, xreg)
        if entry is None:
            return None
        memo_key = sidx.tobytes()
        body = entry.body_memo.get(memo_key)
        if body is None:
            body = encode(self._gather(entry, sidx))
            if len(entry.body_memo) < _FRAME_MEMO_MAX:
                entry.body_memo[memo_key] = body
        return body

    def _lookup_entry(self, frame, horizon, include_history, quantiles,
                      on_missing, xreg):
        """The shared read path behind :meth:`lookup` and
        :meth:`lookup_response`: admission checks, series resolution, the
        epoch-checked entry fetch (with inline cold rebuild) and all
        hit/miss metrics.  Returns ``(entry, sidx)`` on a current-epoch
        hit, ``(None, None)`` on any miss or bypass."""
        if not self.config.enabled:
            return None, None
        if xreg is not None or include_history:
            self.metrics.misses.inc(reason="bypass")
            return None, None
        if quantiles is not None:
            quantiles = canonical_quantiles(quantiles)
            if quantiles not in self.config.quantile_sets:
                self.metrics.misses.inc(reason="bypass")
                return None, None
        sig = (int(horizon), quantiles)
        with get_tracer().span("cache.lookup", horizon=int(horizon),
                               quantiles=len(quantiles or ())) as span:
            # same resolution (and same UnknownSeriesError) as dispatch
            sidx = self._fc.series_indices(frame, on_missing=on_missing)
            if sidx.size == 0:
                # the dispatch path's empty-frame shape is family-specific;
                # rare enough to just dispatch
                span.set_attribute("outcome", "bypass")
                self.metrics.misses.inc(reason="bypass")
                return None, None
            entry, reason = self._current_entry(sig)
            if entry is None and reason == "cold":
                entry = self._rebuild_for_miss(sig)
                if entry is None:
                    reason = "rebuilding"
            if entry is None:
                span.set_attribute("outcome", reason)
                self.metrics.misses.inc(reason=reason)
                return None, None
            span.set_attribute("outcome", "hit")
            self.metrics.hits.inc()
            return entry, sidx

    def _current_entry(self, sig):
        """(entry, miss_reason): the resident entry iff its epoch is the
        CURRENT state generation — the no-stale-read invariant."""
        gen = self._fc.state_generation()
        with self._lock:
            entry = self._entries.get(sig)
            if entry is not None and entry.epoch == gen:
                return entry, ""
            if sig[0] not in self._horizons and (
                    len(self._horizons) >= self.config.max_horizons):
                return None, "horizon_cap"
            return None, ("stale" if entry is not None else "cold")

    def _gather(self, entry: _Entry, sidx: np.ndarray) -> pd.DataFrame:
        """Row-gather the requested series out of the materialized frame —
        byte-identical to a dedicated dispatch because predict's per-series
        blocks are bit-identical across request-size buckets.

        Always returns a SHALLOW COPY of the memoized frame: the handler
        replaces the ds column on the response (astype(str)), and a column
        replacement on a fresh shallow copy never reaches the cached
        original (values are never mutated in place anywhere on the read
        path)."""
        memo_key = sidx.tobytes()
        frame = entry.memo.get(memo_key)
        if frame is None:
            T = entry.ds.shape[0]
            out = {"ds": np.tile(entry.ds, len(sidx))}
            keys = self._fc.keys
            for j, name in enumerate(self._fc.key_names):
                out[name] = np.repeat(keys[sidx, j], T)
            for ci, col in enumerate(entry.columns):
                out[col] = np.asarray(entry.values[ci][sidx]).reshape(-1)
            frame = pd.DataFrame(out)
            if len(entry.memo) < _FRAME_MEMO_MAX:
                entry.memo[memo_key] = frame
        return frame.copy(deep=False)

    # -- write path ----------------------------------------------------------

    def _on_state_install(self) -> None:
        """swap_state listener (writer's thread, outside the state lock):
        count the now-stale residents, then re-materialize each resident
        signature in one batched dispatch apiece.  A reader meanwhile
        either still sees the pre-install frame REJECTED by the epoch check
        (dispatch fall-through) or the fresh frame — never the old values."""
        with self._lock:
            sigs = [e.sig for e in self._entries.values()]
        if not sigs:
            return
        self.metrics.invalidations.inc(len(sigs))
        for sig in sigs:
            self.rebuild(sig)

    def rebuild(self, sig) -> bool:
        """Materialize ``sig``'s full-S frame (blocking on the gate);
        returns True iff the frame was published (False: a newer install
        overtook the dispatch, or the forecaster raised)."""
        with self._rebuild_gate:
            return self._rebuild_locked(sig)

    def _rebuild_for_miss(self, sig) -> Optional[_Entry]:
        """Cold-miss inline rebuild: non-blocking gate — if another thread
        is already materializing, this read just falls through to dispatch
        instead of queueing behind a device call."""
        if not self._rebuild_gate.acquire(blocking=False):
            return None
        try:
            self._rebuild_locked(sig)
        finally:
            self._rebuild_gate.release()
        entry, _ = self._current_entry(sig)
        return entry

    def _rebuild_locked(self, sig) -> bool:
        horizon, quantiles = sig
        fc = self._fc
        epoch = fc.state_generation()
        req = pd.DataFrame(fc.keys, columns=fc.key_names)
        with get_tracer().span("cache.rebuild", horizon=int(horizon),
                               series=int(fc.keys.shape[0])) as span:
            try:
                if quantiles is None:
                    frame = fc.predict(req, horizon=horizon)
                else:
                    frame = fc.predict_quantiles(
                        req, quantiles=quantiles, horizon=horizon)
            except Exception:  # noqa: BLE001 — reads keep dispatching
                self.logger.exception("cache rebuild dispatch failed")
                span.set_attribute("outcome", "error")
                return False
            self.metrics.rebuilds.inc()
            _, day1, gen_after = fc._state_snapshot_versioned()
            if gen_after != epoch:
                # a writer overtook the dispatch: this frame mixes epochs
                # from the reader's perspective — drop it; the writer's own
                # listener pass re-materializes from the newer state
                span.set_attribute("outcome", "superseded")
                return False
            S = int(fc.keys.shape[0])
            key_cols = set(fc.key_names) | {"ds"}
            columns = [c for c in frame.columns if c not in key_cols]
            T = len(frame) // S
            values = np.stack(
                [frame[c].to_numpy().reshape(S, T) for c in columns])
            entry = _Entry(sig, epoch, int(day1),
                           frame["ds"].to_numpy()[:T].copy(), columns,
                           values, time.monotonic())
            span.set_attribute("outcome", "published")
        if not self._publish(entry):
            return False
        if self.config.mmap_dir:
            self._persist(entry)
        return True

    def _publish(self, entry: _Entry) -> bool:
        evicted = []
        with self._lock:
            if entry.epoch != self._fc.state_generation():
                return False  # raced a writer between dispatch and publish
            if entry.nbytes > self.config.max_bytes:
                # a frame that alone busts the budget is never admitted
                self.metrics.evictions.inc()
                return False
            old = self._entries.get(entry.sig)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.sig] = entry
            self._horizons.add(entry.sig[0])
            self._bytes += entry.nbytes
            while self._bytes > self.config.max_bytes:
                victim = min(
                    (e for e in self._entries.values() if e.sig != entry.sig),
                    key=lambda e: e.built_at, default=None)
                if victim is None:
                    break
                del self._entries[victim.sig]
                self._bytes -= victim.nbytes
                evicted.append(victim.sig)
            self._refresh_gauges_locked()
        for sig in evicted:
            self.metrics.evictions.inc()
            self._remove_persisted(sig)
        return True

    # -- persistence ---------------------------------------------------------

    def _sig_stem(self, sig) -> str:
        horizon, quantiles = sig
        stem = f"h{int(horizon)}"
        if quantiles:
            stem += "-q" + "_".join(f"{q:.3f}".rstrip("0").rstrip(".")
                                    for q in quantiles)
        return stem.replace(".", "p")

    def _state_fingerprint(self) -> str:
        """sha256 over the live (params, day1, model, keys) — what a
        persisted frame must have been computed from to be adoptable.
        Computed lazily once per generation (a host pull per leaf)."""
        while True:
            params, day1, gen = self._fc._state_snapshot_versioned()
            with self._lock:
                if self._fp_cache[0] == gen:
                    return self._fp_cache[1]
            import jax

            h = hashlib.sha256()
            h.update(f"{self._fc.model}|{day1}|".encode())
            h.update(np.ascontiguousarray(self._fc.keys).tobytes())
            for leaf in jax.tree_util.tree_leaves(params):
                h.update(np.ascontiguousarray(leaf).tobytes())
            digest = h.hexdigest()
            if self._fc.state_generation() == gen:
                with self._lock:
                    self._fp_cache = (gen, digest)
                return digest
            # a writer landed mid-hash; recompute from the new snapshot

    def _persist(self, entry: _Entry) -> None:
        """Durably record ``entry`` under mmap_dir: payload tmp-written,
        fsync-free renamed, then the meta JSON as the commit record — a
        kill -9 anywhere in between leaves either nothing visible or a
        payload with no meta, both of which the loader ignores."""
        cfg_dir = self.config.mmap_dir
        stem = self._sig_stem(entry.sig)
        try:
            with get_tracer().span("cache.persist", sig=stem):
                failpoint("cache.persist")
                os.makedirs(cfg_dir, exist_ok=True)
                payload = np.ascontiguousarray(entry.values)
                ppath = os.path.join(cfg_dir, stem + _PAYLOAD_SUFFIX)
                tmp = ppath + ".tmp"
                with open(tmp, "wb") as f:
                    np.save(f, payload)
                os.replace(tmp, ppath)
                with open(ppath, "rb") as f:
                    digest = hashlib.sha256(f.read()).hexdigest()
                ds = entry.ds
                meta = {
                    "format": _PERSIST_FORMAT,
                    "horizon": int(entry.sig[0]),
                    "quantiles": (None if entry.sig[1] is None
                                  else list(entry.sig[1])),
                    "columns": list(entry.columns),
                    "day1": int(entry.day1),
                    "ds_i8": np.asarray(ds).view("i8").tolist(),
                    "ds_dtype": str(np.asarray(ds).dtype),
                    "payload_sha256": digest,
                    "state_fingerprint": self._state_fingerprint(),
                }
                mpath = os.path.join(cfg_dir, stem + _META_SUFFIX)
                tmp = mpath + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(meta, f)
                os.replace(tmp, mpath)
            self.metrics.persists.inc()
        except Exception:  # noqa: BLE001 — memory serving survives disk loss
            self.metrics.persist_errors.inc()
            self.logger.exception("cache persist failed (sig %s)", stem)

    def _remove_persisted(self, sig) -> None:
        if not self.config.mmap_dir:
            return
        stem = self._sig_stem(sig)
        for suffix in (_META_SUFFIX, _PAYLOAD_SUFFIX):
            try:
                os.remove(os.path.join(self.config.mmap_dir, stem + suffix))
            except OSError:
                pass

    def _load_persisted(self) -> None:
        """Adopt persisted frames whose state fingerprint matches the LIVE
        model state; anything torn, corrupt, or computed from other state
        is discarded — the fall-through path serves those reads instead."""
        cfg_dir = self.config.mmap_dir
        try:
            names = sorted(n for n in os.listdir(cfg_dir)
                           if n.endswith(_META_SUFFIX))
        except OSError:
            return
        fingerprint = self._state_fingerprint() if names else ""
        epoch = self._fc.state_generation()
        for name in names:
            stem = name[: -len(_META_SUFFIX)]
            try:
                with get_tracer().span("cache.load", sig=stem):
                    failpoint("cache.load")
                    with open(os.path.join(cfg_dir, name)) as f:
                        meta = json.load(f)
                    if (meta.get("format") != _PERSIST_FORMAT
                            or meta.get("state_fingerprint") != fingerprint):
                        raise ValueError("state fingerprint mismatch")
                    ppath = os.path.join(cfg_dir, stem + _PAYLOAD_SUFFIX)
                    with open(ppath, "rb") as f:
                        digest = hashlib.sha256(f.read()).hexdigest()
                    if digest != meta.get("payload_sha256"):
                        raise ValueError("payload digest mismatch")
                    values = np.load(ppath, mmap_mode="r")
                    quantiles = meta.get("quantiles")
                    sig = (int(meta["horizon"]),
                           None if quantiles is None
                           else tuple(float(q) for q in quantiles))
                    ds = np.asarray(meta["ds_i8"], dtype="i8").view(
                        np.dtype(meta["ds_dtype"]))
                    S = int(self._fc.keys.shape[0])
                    if values.shape[1:] != (S, ds.shape[0]):
                        raise ValueError(
                            f"payload shape {values.shape} does not cover "
                            f"{S} series x {ds.shape[0]} steps")
                    entry = _Entry(sig, epoch, int(meta["day1"]), ds,
                                   list(meta["columns"]), values,
                                   time.monotonic())
                self.metrics.loads.inc()
                self._publish(entry)
            except Exception:  # noqa: BLE001 — discard, never serve torn
                self.metrics.load_errors.inc()
                self.logger.warning(
                    "discarding persisted cache entry %s (torn or stale)",
                    stem)
                for suffix in (_META_SUFFIX, _PAYLOAD_SUFFIX):
                    try:
                        os.remove(os.path.join(cfg_dir, stem + suffix))
                    except OSError:
                        pass

    # -- introspection -------------------------------------------------------

    def _refresh_gauges_locked(self) -> None:
        self.metrics.entries.set(float(len(self._entries)))  # dflint: disable=unlocked-shared-state — _locked suffix contract: every caller holds self._lock
        self.metrics.bytes.set(float(self._bytes))  # dflint: disable=unlocked-shared-state — _locked suffix contract: every caller holds self._lock

    def render_metrics(self) -> str:
        now = time.monotonic()
        with self._lock:
            oldest = min((e.built_at for e in self._entries.values()),
                         default=None)
            self._refresh_gauges_locked()
        self.metrics.entry_age.set(0.0 if oldest is None else now - oldest)
        return self.metrics.registry.render_prometheus()

    def describe(self) -> dict:
        gen = self._fc.state_generation()
        with self._lock:
            entries = [{
                "horizon": e.sig[0],
                "quantiles": list(e.sig[1]) if e.sig[1] else None,
                "epoch": e.epoch,
                "current": e.epoch == gen,
                "bytes": e.nbytes,
            } for e in self._entries.values()]
            total = self._bytes
        return {"enabled": self.config.enabled, "generation": gen,
                "entries": entries, "bytes": total}


def build_forecast_cache(conf, forecaster,
                         default_mmap_dir: Optional[str] = None):
    """``serving.cache`` conf -> ForecastCache (or None when disabled).

    Composite forecasters (ensemble/bucketed) don't declare
    ``coalesce_safe``, so their row order is not gather-stable — they serve
    uncached rather than refuse to boot."""
    config = CacheConfig.from_conf(conf)
    if not config.enabled:
        return None
    if not getattr(forecaster, "coalesce_safe", False):
        get_logger("ForecastCache").warning(
            "%s is not coalesce-safe; forecast cache disabled",
            type(forecaster).__name__)
        return None
    if config.mmap_dir is None and default_mmap_dir is not None:
        config = dataclasses.replace(config, mmap_dir=default_mmap_dir)
    return ForecastCache(forecaster, config)
