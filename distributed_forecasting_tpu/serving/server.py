"""Online inference endpoint: the registered model behind an HTTP surface.

The reference's serving story is its PyFunc model deployed behind Databricks
model serving / dispatched by a Spark UDF (reference
``notebooks/prophet/03_deploy.py:20-36``, ``04_inference.py:4-16``) — every
request pays registry resolution, artifact download, and a per-series model
load.  Here the registered artifact is loaded ONCE into device memory and
every request runs the request-proportional batched predict
(``serving/predictor.py``): a k-series request is one compiled forecast of
leading axis ~k.

Endpoints (JSON over HTTP, stdlib http.server — no web framework in the
image, and none needed for a single-model scorer):

  GET  /health            -> {"status": "ok", "model": ..., "n_series": N}
  GET  /schema            -> serving schema + key names (the tag the
                             reference stores on the model version,
                             03_deploy.py:44-58)
  POST /invocations       -> {"inputs": [{"store": 1, "item": 2}, ...],
                              "horizon": 90, "include_history": false}
                          -> {"predictions": [...]} (records of the output
                             frame; unknown series -> 404 unless
                             "on_missing": "skip")

``serve`` blocks; ``start_server`` returns the live server for tests/
embedding.  Model resolution goes through the registry exactly like the
reference's ``predict_udf`` (latest version, optionally stage-filtered).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.serving.ensemble import (
    BlendedForecaster,
    MultiModelForecaster,
)
from distributed_forecasting_tpu.serving.predictor import (
    BatchForecaster,
    UnknownSeriesError,
)
from distributed_forecasting_tpu.utils import get_logger

_ENSEMBLE_META = "ensemble.json"
_BLEND_META = "blend.json"
_BUCKETS_META = "buckets.json"
_MAX_HORIZON = 3650  # 10 years daily — beyond any sane scoring request
_MAX_QUANTILES = 32  # more levels than any scorer needs; bounds compile count


def load_forecaster(artifact_dir: str):
    """Load whichever serving artifact lives in ``artifact_dir`` — a single
    BatchForecaster, a mixed-family MultiModelForecaster, a weighted
    BlendedForecaster, or a span-bucketed BucketedForecaster."""
    if os.path.exists(os.path.join(artifact_dir, _ENSEMBLE_META)):
        return MultiModelForecaster.load(artifact_dir)
    if os.path.exists(os.path.join(artifact_dir, _BLEND_META)):
        return BlendedForecaster.load(artifact_dir)
    if os.path.exists(os.path.join(artifact_dir, _BUCKETS_META)):
        from distributed_forecasting_tpu.serving.bucketed import (
            BucketedForecaster,
        )

        return BucketedForecaster.load(artifact_dir)
    return BatchForecaster.load(artifact_dir)


def resolve_from_registry(registry, model_name: str, stage: Optional[str] = None):
    """Registry -> loaded forecaster, the reference's latest-version rule
    (``04_inference.py:10-13``) done once at startup instead of per group."""
    version = registry.latest_version(model_name, stage=stage)
    sub = os.path.join(version.artifact_dir, "forecaster")
    return load_forecaster(sub if os.path.isdir(sub) else version.artifact_dir), version


class _Handler(BaseHTTPRequestHandler):
    server_version = "dftpu-serve/1.0"

    # the forecaster and metadata ride on the server object
    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route through framework logging
        self.server.logger.info("%s " + fmt, self.address_string(), *args)

    def do_GET(self):
        fc = self.server.forecaster
        if self.path == "/health":
            self._send(
                200,
                {
                    "status": "ok",
                    # every serving class exposes .family ("blend:..."/
                    # "auto:..." for composites, the family name otherwise)
                    "model": fc.family,
                    # n_series, not .keys: the span-bucketed composite has
                    # no top-level key table, only per-bucket routing
                    "n_series": int(fc.n_series),
                    "version": self.server.model_version,
                },
            )
        elif self.path == "/schema":
            self._send(
                200,
                {
                    "key_names": list(fc.key_names),
                    # the forecaster's own schema (ensembles add a model
                    # column) — not re-derived here, so it can't drift
                    "serving_schema": fc.serving_schema,
                },
            )
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path not in ("/invocations", "/predict"):
            self._send(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(req, dict):
                self._send(400, {"error": "body must be a JSON object with 'inputs'"})
                return
            inputs = req.get("inputs")
            if not inputs:
                self._send(400, {"error": "body needs a non-empty 'inputs' list"})
                return
            horizon = int(req.get("horizon", 90))
            if not 1 <= horizon <= _MAX_HORIZON:
                # unbounded request-controlled horizons would let one call
                # allocate GB-scale outputs in a long-lived scorer
                self._send(
                    400,
                    {"error": f"horizon must be in [1, {_MAX_HORIZON}], got {horizon}"},
                )
                return
            frame = pd.DataFrame(inputs)
            missing_cols = set(self.server.forecaster.key_names) - set(frame.columns)
            if missing_cols:
                self._send(
                    400, {"error": f"inputs missing key columns {sorted(missing_cols)}"}
                )
                return
            xreg = req.get("xreg")
            if xreg is not None:
                # exogenous regressor values for models fit with
                # n_regressors > 0: nested lists, (T_all, R) shared or
                # (S_trained, T_all, R) per-series — shape/length checks
                # live in BatchForecaster.predict
                xreg = np.asarray(xreg, dtype=np.float32)
            quantiles = req.get("quantiles")
            if quantiles is not None:
                # probabilistic scoring: {"quantiles": [0.1, 0.5, 0.9]}
                # returns q<level> columns instead of yhat/bounds
                if (
                    not isinstance(quantiles, list)
                    or not quantiles
                    or len(quantiles) > _MAX_QUANTILES
                    or not all(
                        isinstance(q, (int, float)) and 0.0 < q < 1.0
                        for q in quantiles
                    )
                ):
                    self._send(
                        400,
                        {"error": "quantiles must be a non-empty list of "
                                  f"at most {_MAX_QUANTILES} levels in (0, 1)"},
                    )
                    return
                # canonicalize to 3 decimals: levels are a STATIC jit arg,
                # so every distinct tuple compiles — rounding bounds the
                # compile-cache growth a hostile/naive client could force
                # (same DoS class _MAX_HORIZON guards)
                quantiles = tuple(
                    sorted({round(float(q), 3) for q in quantiles})
                )
                if not all(0.0 < q < 1.0 for q in quantiles):
                    self._send(
                        400,
                        {"error": "quantile levels round to the open "
                                  "interval (0.001, 0.999)"},
                    )
                    return
                out = self.server.forecaster.predict_quantiles(
                    frame,
                    quantiles=quantiles,
                    horizon=horizon,
                    include_history=bool(req.get("include_history", False)),
                    on_missing=req.get("on_missing", "raise"),
                    xreg=xreg,
                )
            else:
                out = self.server.forecaster.predict(
                    frame,
                    horizon=horizon,
                    include_history=bool(req.get("include_history", False)),
                    on_missing=req.get("on_missing", "raise"),
                    xreg=xreg,
                )
            out["ds"] = out["ds"].astype(str)
            keys = list(self.server.forecaster.key_names)
            n_series = int(out[keys].drop_duplicates().shape[0]) if len(out) else 0
            self._send(
                200,
                {
                    "predictions": out.to_dict(orient="records"),
                    "n_series": n_series,
                },
            )
        except UnknownSeriesError as e:
            self._send(404, {"error": str(e)})
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            # TypeError covers JSON-legal but wrong-typed fields, e.g.
            # "horizon": null / [90]
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — scorer must not die mid-request
            self.server.logger.exception("invocation failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})


class ForecastServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, forecaster, model_version: Optional[str] = None):
        super().__init__(addr, _Handler)
        self.forecaster = forecaster
        self.model_version = model_version
        self.logger = get_logger("ForecastServer")


def start_server(
    forecaster,
    host: str = "127.0.0.1",
    port: int = 0,
    model_version: Optional[str] = None,
) -> ForecastServer:
    """Start serving on a background thread; returns the server (its
    ``server_address[1]`` is the bound port — port=0 picks a free one)."""
    srv = ForecastServer((host, port), forecaster, model_version)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def serve(
    forecaster,
    host: str = "0.0.0.0",
    port: int = 8080,
    model_version: Optional[str] = None,
) -> None:
    srv = ForecastServer((host, port), forecaster, model_version)
    srv.logger.info("serving on %s:%d", host, port)
    srv.serve_forever()
