"""Online inference endpoint: the registered model behind an HTTP surface.

The reference's serving story is its PyFunc model deployed behind Databricks
model serving / dispatched by a Spark UDF (reference
``notebooks/prophet/03_deploy.py:20-36``, ``04_inference.py:4-16``) — every
request pays registry resolution, artifact download, and a per-series model
load.  Here the registered artifact is loaded ONCE into device memory and
every request runs the request-proportional batched predict
(``serving/predictor.py``): a k-series request is one compiled forecast of
leading axis ~k.

Endpoints (JSON over HTTP, stdlib http.server — no web framework in the
image, and none needed for a single-model scorer):

  GET  /health            -> {"status": "ok", "model": ..., "n_series": N}
  GET  /healthz           -> {"status": "ok"} (pure liveness: the process
                             answers; no model state consulted)
  GET  /readyz            -> 200 once warmup is complete AND the batcher is
                             accepting, 503 otherwise (fleet supervisors
                             route traffic on this, not /health)
  GET  /schema            -> serving schema + key names (the tag the
                             reference stores on the model version,
                             03_deploy.py:44-58)
  GET  /metrics           -> Prometheus text exposition: request/dispatch/
                             rejection/timeout counters, queue-depth gauge,
                             latency + coalesced-batch-size histograms; with
                             a quality runtime attached, also the
                             ``dftpu_quality_*`` / ``dftpu_slo_*`` families
  POST /invocations       -> {"inputs": [{"store": 1, "item": 2}, ...],
                              "horizon": 90, "include_history": false}
                          -> {"predictions": [...]} (records of the output
                             frame; unknown series -> 404 unless
                             "on_missing": "skip"; with micro-batching
                             enabled, a full queue -> 429 and a request
                             outliving request_timeout_s -> 503)
  POST /observe           -> {"observations": [{<keys>, "ds": ..., "y": ...},
                              ...]} — ground-truth actuals scored against
                             what this model serves for those dates
                             (``monitoring/quality.py``); 503 when no
                             quality runtime is configured; with
                             ``serving.ingest.observe_feeds_ingest`` set,
                             the same actuals also flow into the WAL so
                             scoring traffic keeps the model fresh
  POST /ingest            -> {"points": [{<keys>, "ds"|"d": ..., "y": ...},
                              ...]} — new observations into the streaming
                             WAL (``serving/ingest.py``); in sync mode the
                             response reports the batched state update that
                             already made /invocations reflect them; 503
                             when no ingest runtime is configured
  GET  /debug/quality     -> rolling quality + SLO + store snapshot (behind
                             tracing.debug_endpoints, like /debug/trace)
  GET  /debug/ingest      -> WAL/state-store/refit snapshot (same gate)

``serve`` blocks; ``start_server`` returns the live server for tests/
embedding.  Model resolution goes through the registry exactly like the
reference's ``predict_udf`` (latest version, optionally stage-filtered).
Concurrent-request coalescing (``serving/batcher.py``) is OFF by default;
pass a ``BatchingConfig(enabled=True, ...)`` (conf: ``serving.batching``)
to merge concurrent ``/invocations`` into shared device dispatches.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from concurrent.futures import TimeoutError as _FutureTimeoutError
from http.server import BaseHTTPRequestHandler
from typing import Optional

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.monitoring.trace import (
    ProfilerBusyError,
    dump_flight_recorder,
    get_tracer,
    to_chrome_trace,
)
from distributed_forecasting_tpu.serving.batcher import (
    BatchingConfig,
    QueueFullError,
    RequestBatcher,
    ServingMetrics,
    ShuttingDownError,
)
from distributed_forecasting_tpu.serving.dataplane import (
    HttpConfig,
    KeepAliveHandlerMixin,
    PooledHTTPServer,
)
from distributed_forecasting_tpu.serving.ensemble import (
    BlendedForecaster,
    MultiModelForecaster,
)
from distributed_forecasting_tpu.serving.predictor import (
    BatchForecaster,
    UnknownSeriesError,
)
from distributed_forecasting_tpu.utils import get_logger

_ENSEMBLE_META = "ensemble.json"
_BLEND_META = "blend.json"
_BUCKETS_META = "buckets.json"
_MAX_HORIZON = 3650  # 10 years daily — beyond any sane scoring request
_MAX_QUANTILES = 32  # more levels than any scorer needs; bounds compile count


def load_forecaster(artifact_dir: str):
    """Load whichever serving artifact lives in ``artifact_dir`` — a single
    BatchForecaster, a mixed-family MultiModelForecaster, a weighted
    BlendedForecaster, or a span-bucketed BucketedForecaster."""
    if os.path.exists(os.path.join(artifact_dir, _ENSEMBLE_META)):
        return MultiModelForecaster.load(artifact_dir)
    if os.path.exists(os.path.join(artifact_dir, _BLEND_META)):
        return BlendedForecaster.load(artifact_dir)
    if os.path.exists(os.path.join(artifact_dir, _BUCKETS_META)):
        from distributed_forecasting_tpu.serving.bucketed import (
            BucketedForecaster,
        )

        return BucketedForecaster.load(artifact_dir)
    return BatchForecaster.load(artifact_dir)


def resolve_from_registry(registry, model_name: str, stage: Optional[str] = None):
    """Registry -> loaded forecaster, the reference's latest-version rule
    (``04_inference.py:10-13``) done once at startup instead of per group."""
    version = registry.latest_version(model_name, stage=stage)
    sub = os.path.join(version.artifact_dir, "forecaster")
    return load_forecaster(sub if os.path.isdir(sub) else version.artifact_dir), version


def _encode_predictions(out: pd.DataFrame, key_names) -> bytes:
    """A forecast frame -> the exact ``/invocations`` 200 response body.

    One function on purpose: the dispatch path encodes through it AND the
    byte cache (``ForecastCache.lookup_response``) memoizes its output, so
    cached bytes are byte-identical to encode-on-read by construction —
    there is no second serializer to drift.  The shallow copy keeps the
    ``ds`` stringification off the caller's (possibly cached) frame."""
    out = out.copy(deep=False)
    out["ds"] = out["ds"].astype(str)
    keys = list(key_names)
    n_series = int(out[keys].drop_duplicates().shape[0]) if len(out) else 0
    return json.dumps({
        "predictions": out.to_dict(orient="records"),
        "n_series": n_series,
    }).encode()


def _safe_trace_id(raw: Optional[str]) -> Optional[str]:
    """Accept a client-supplied X-Trace-Id only when it is a sane token —
    a hostile header must not ride into log files or dump names."""
    if not raw:
        return None
    raw = raw.strip()
    if 1 <= len(raw) <= 64 and all(c.isalnum() or c in "-_" for c in raw):
        return raw
    return None


class _Handler(KeepAliveHandlerMixin, BaseHTTPRequestHandler):
    server_version = "dftpu-serve/1.0"

    # per-connection trace state, reset per request in do_POST/do_GET
    # (with keep-alive one handler instance now serves many requests)
    _trace_id: Optional[str] = None
    _status: int = 0

    # the forecaster and metadata ride on the server object
    def _send(self, code: int, payload: dict, extra_headers=()) -> None:
        self._send_bytes(code, json.dumps(payload).encode(),
                         extra_headers=extra_headers)

    def _send_bytes(self, code: int, body: bytes, extra_headers=()) -> None:
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            # echo the correlation id so clients can quote it in bug reports
            # and operators can grep it out of trace exports
            self.send_header("X-Trace-Id", self._trace_id)
        for name, value in extra_headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route through framework logging
        self.server.logger.info("%s " + fmt, self.address_string(), *args)

    def do_GET(self):
        # a keep-alive connection reuses this handler instance: a trace id
        # from an earlier POST must not echo onto an unrelated GET
        self._trace_id = None
        fc = self.server.forecaster
        parsed = urllib.parse.urlsplit(self.path)
        if parsed.path == "/healthz":
            # liveness only: answering at all is the signal
            self._send(200, {"status": "ok"})
            return
        if parsed.path == "/readyz":
            ready, reason = self.server.readiness()
            self._send(200 if ready else 503,
                       {"ready": ready, "reason": reason},
                       extra_headers=(() if ready
                                      else (("Retry-After", "1"),)))
            return
        if parsed.path.startswith("/debug/"):
            self._debug(parsed)
            return
        if self.path == "/health":
            self._send(
                200,
                {
                    "status": "ok",
                    # every serving class exposes .family ("blend:..."/
                    # "auto:..." for composites, the family name otherwise)
                    "model": fc.family,
                    # n_series, not .keys: the span-bucketed composite has
                    # no top-level key table, only per-bucket routing
                    "n_series": int(fc.n_series),
                    "version": self.server.model_version,
                },
            )
        elif self.path == "/schema":
            self._send(
                200,
                {
                    "key_names": list(fc.key_names),
                    # the forecaster's own schema (ensembles add a model
                    # column) — not re-derived here, so it can't drift
                    "serving_schema": fc.serving_schema,
                },
            )
        elif self.path == "/metrics":
            text = self.server.metrics.render()
            if self.server.quality is not None:
                text += self.server.quality.render_metrics()
            if self.server.ingest is not None:
                text += self.server.ingest.render_metrics()
            if self.server.anomaly is not None:
                text += self.server.anomaly.render_metrics()
            if self.server.cache is not None:
                text += self.server.cache.render_metrics()
            if self.server.extra_metrics is not None:
                text += self.server.extra_metrics.render()
            from distributed_forecasting_tpu.data.quality import (
                render_data_quality_metrics,
            )

            text += render_data_quality_metrics()
            body = text.encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def _debug(self, parsed) -> None:
        """Introspection surface, OFF unless tracing.debug_endpoints is set
        (these expose internals and can hold a handler thread for seconds)."""
        tracer = get_tracer()
        if not tracer.config.debug_endpoints:
            self._send(404, {"error": f"no route {parsed.path}"})
            return
        if parsed.path == "/debug/trace":
            # the flight recorder's recent spans as a Perfetto-loadable
            # Chrome trace — save the body, open it in ui.perfetto.dev
            spans = tracer.recorder.snapshot()
            self._send(200, to_chrome_trace(
                spans, metadata={"n_spans": len(spans)}))
        elif parsed.path == "/debug/profile":
            query = urllib.parse.parse_qs(parsed.query)
            try:
                seconds = float(query.get("seconds", ["3"])[0])
            except ValueError:
                self._send(400, {"error": "seconds must be a number"})
                return
            if not tracer.profiler.available:
                self._send(503, {"error": "profiler capture not configured "
                                          "(tracing.profile_dir is unset)"},
                           extra_headers=(("Retry-After", "60"),))
                return
            try:
                # blocks THIS handler thread for the capture window; other
                # handler threads keep serving (ThreadingHTTPServer), which
                # is the point — the capture sees live traffic
                out = tracer.profiler.capture(seconds)
            except ProfilerBusyError as e:
                self._send(409, {"error": str(e)})
                return
            self._send(200, {"capture_dir": out, "seconds": seconds})
        elif parsed.path == "/debug/quality":
            quality = self.server.quality
            if quality is None:
                self._send(503, {"error": "quality monitoring not enabled "
                                          "(monitoring.quality conf block)"},
                           extra_headers=(("Retry-After", "60"),))
                return
            self._send(200, quality.snapshot())
        elif parsed.path == "/debug/ingest":
            ingest = self.server.ingest
            if ingest is None:
                self._send(503, {"error": "streaming ingest not enabled "
                                          "(serving.ingest conf block)"},
                           extra_headers=(("Retry-After", "60"),))
                return
            self._send(200, ingest.snapshot())
        elif parsed.path == "/debug/cost":
            from distributed_forecasting_tpu.monitoring.cost import (
                cost_metrics,
                get_cost_config,
            )

            cconf = get_cost_config()
            if not cconf.enabled:
                self._send(503, {"error": "cost observability disabled "
                                          "(monitoring.cost conf block)"},
                           extra_headers=(("Retry-After", "60"),))
                return
            # per-entry cost table + roofline placement when the conf
            # carries backend peaks; watermarks are freshly sampled
            self._send(200, cost_metrics().snapshot(cconf))
        else:
            self._send(404, {"error": f"no route {parsed.path}"})

    def do_POST(self):
        # deadline shed (serving/resilience): work whose X-Deadline-Ms
        # budget is already spent gets its terminal 503 BEFORE parsing or
        # dispatch — the client stopped waiting, so device time spent on
        # it would be pure waste.  The front door forwards the remaining
        # budget; direct clients can send the header themselves.
        raw_budget = (self.headers.get("X-Deadline-Ms") or "").strip()
        if raw_budget:
            try:
                budget_ms = float(raw_budget)
            except ValueError:
                budget_ms = None  # hostile/garbage header: ignore
            if budget_ms is not None and budget_ms <= 0:
                self.server.metrics.deadline_shed.inc()
                self._send(
                    503,
                    {"error": "deadline budget exhausted before dispatch"},
                    extra_headers=(("Retry-After", "1"),))
                return
        if self.path == "/observe":
            self._observe()
            return
        if self.path == "/ingest":
            self._ingest()
            return
        if self.path == "/detect_anomalies":
            self._detect_anomalies()
            return
        if self.path not in ("/invocations", "/predict"):
            self._send(404, {"error": f"no route {self.path}"})
            return
        metrics = self.server.metrics
        metrics.requests.inc()
        tracer = get_tracer()
        self._trace_id = _safe_trace_id(self.headers.get("X-Trace-Id"))
        t0 = time.monotonic()
        try:
            with tracer.root_span(
                "http.request", trace_id=self._trace_id,
                method="POST", path=self.path,
            ) as root:
                self._trace_id = root.trace_id or self._trace_id
                self._invoke()
                root.set_attribute("status", self._status)
        finally:
            metrics.latency.observe(time.monotonic() - t0)
            if self._status >= 500:
                # slow (503 deadline) and failed (5xx) requests leave the
                # last seconds of span history on disk for post-mortems
                path = dump_flight_recorder(f"http-{self._status}")
                if path:
                    self.server.logger.warning(
                        "status %d: flight recorder dumped to %s",
                        self._status, path)

    def _invoke(self):
        metrics = self.server.metrics
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(req, dict):
                self._send(400, {"error": "body must be a JSON object with 'inputs'"})
                return
            inputs = req.get("inputs")
            if not inputs:
                self._send(400, {"error": "body needs a non-empty 'inputs' list"})
                return
            horizon = int(req.get("horizon", 90))
            if not 1 <= horizon <= _MAX_HORIZON:
                # unbounded request-controlled horizons would let one call
                # allocate GB-scale outputs in a long-lived scorer
                self._send(
                    400,
                    {"error": f"horizon must be in [1, {_MAX_HORIZON}], got {horizon}"},
                )
                return
            frame = pd.DataFrame(inputs)
            missing_cols = set(self.server.forecaster.key_names) - set(frame.columns)
            if missing_cols:
                self._send(
                    400, {"error": f"inputs missing key columns {sorted(missing_cols)}"}
                )
                return
            xreg = req.get("xreg")
            if xreg is not None:
                # exogenous regressor values for models fit with
                # n_regressors > 0: nested lists, (T_all, R) shared or
                # (S_trained, T_all, R) per-series — shape/length checks
                # live in BatchForecaster.predict
                xreg = np.asarray(xreg, dtype=np.float32)
            quantiles = req.get("quantiles")
            if quantiles is not None:
                # probabilistic scoring: {"quantiles": [0.1, 0.5, 0.9]}
                # returns q<level> columns instead of yhat/bounds
                if (
                    not isinstance(quantiles, list)
                    or not quantiles
                    or len(quantiles) > _MAX_QUANTILES
                    or not all(
                        isinstance(q, (int, float)) and 0.0 < q < 1.0
                        for q in quantiles
                    )
                ):
                    self._send(
                        400,
                        {"error": "quantiles must be a non-empty list of "
                                  f"at most {_MAX_QUANTILES} levels in (0, 1)"},
                    )
                    return
                # canonicalize to 3 decimals: levels are a STATIC jit arg,
                # so every distinct tuple compiles — rounding bounds the
                # compile-cache growth a hostile/naive client could force
                # (same DoS class _MAX_HORIZON guards)
                quantiles = tuple(
                    sorted({round(float(q), 3) for q in quantiles})
                )
                if not all(0.0 < q < 1.0 for q in quantiles):
                    self._send(
                        400,
                        {"error": "quantile levels round to the open "
                                  "interval (0.001, 0.999)"},
                    )
                    return
            include_history = bool(req.get("include_history", False))
            on_missing = req.get("on_missing", "raise")
            key_names = self.server.forecaster.key_names
            if self.server.cache is not None:
                # serialized-response fast path: a current-epoch hit skips
                # frame assembly AND json.dumps — the memoized bytes were
                # produced by the same _encode_predictions as the dispatch
                # path below, so the response is byte-identical either way
                body = self.server.cache.lookup_response(
                    frame,
                    horizon=horizon,
                    include_history=include_history,
                    quantiles=quantiles,
                    on_missing=on_missing,
                    xreg=xreg,
                    encode=lambda f: _encode_predictions(f, key_names),
                )
                if body is not None:
                    self._send_bytes(200, body)
                    return
            out = self.server.execute(
                frame,
                horizon=horizon,
                include_history=include_history,
                quantiles=quantiles,
                on_missing=on_missing,
                xreg=xreg,
                # the byte lookup above already consulted (and counted) the
                # cache; a second frame-level lookup would double the miss
                # metrics and re-race the same epoch check
                use_cache=False,
            )
            self._send_bytes(200, _encode_predictions(out, key_names))
        except UnknownSeriesError as e:
            self._send(404, {"error": str(e)})
        except QueueFullError as e:
            # admission control: shed load NOW so clients can back off,
            # instead of stacking handler threads behind a saturated chip
            metrics.rejections.inc()
            self._send(429, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
        except (TimeoutError, _FutureTimeoutError) as e:
            # the request outlived request_timeout_s (queued or in flight)
            metrics.timeouts.inc()
            self._send(503, {"error": f"request timed out: {e}" if str(e)
                             else "request timed out"},
                       extra_headers=(("Retry-After", "1"),))
        except ShuttingDownError as e:
            self._send(503, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            # TypeError covers JSON-legal but wrong-typed fields, e.g.
            # "horizon": null / [90]
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — scorer must not die mid-request
            metrics.errors.inc()
            self.server.logger.exception("invocation failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _observe(self):
        """POST /observe: ground-truth actuals into the quality monitor.

        Body: ``{"observations": [{<key cols>, "ds": "...", "y": ...}, ...],
        "on_missing": "skip"|"raise"}``.  Scoring runs the forecaster's own
        batched predict plus one term-kernel dispatch (the quality module's
        batching contract), so a large actuals batch is still two device
        calls, not a per-series loop.
        """
        quality = self.server.quality
        if quality is None or quality.monitor is None:
            self._send(503, {"error": "quality monitoring not enabled "
                                      "(monitoring.quality conf block)"},
                       extra_headers=(("Retry-After", "60"),))
            return
        tracer = get_tracer()
        self._trace_id = _safe_trace_id(self.headers.get("X-Trace-Id"))
        try:
            with tracer.root_span(
                "http.request", trace_id=self._trace_id,
                method="POST", path="/observe",
            ) as root:
                self._trace_id = root.trace_id or self._trace_id
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    self._send(400, {"error": "body must be a JSON object "
                                              "with 'observations'"})
                    return
                observations = req.get("observations")
                if not observations:
                    self._send(400, {"error": "body needs a non-empty "
                                              "'observations' list"})
                    return
                summary = quality.observe(
                    pd.DataFrame(observations),
                    on_missing=req.get("on_missing", "skip"))
                ingest = self.server.ingest
                if ingest is not None and ingest.config.observe_feeds_ingest:
                    # the scoring feedback loop doubles as an ingest source:
                    # actuals flow into the WAL so the model stays fresh
                    # without a second client integration.  A feed failure
                    # must not fail the observe — scoring already happened.
                    try:
                        summary["ingest"] = ingest.submit(observations)
                    except Exception:  # noqa: BLE001
                        self.server.logger.exception(
                            "observe -> ingest feed failed")
                self._send(200, summary)
                root.set_attribute("status", self._status)
        except UnknownSeriesError as e:
            self._send(404, {"error": str(e)})
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — scorer must not die mid-request
            self.server.logger.exception("observe failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _detect_anomalies(self):
        """POST /detect_anomalies: score actuals against the served bands.

        Body: ``{"points": [{<key cols>, "ds": "...", "y": ...}, ...],
        "threshold": 4.0, "on_missing": "skip"|"raise"}``.  One batched
        predict per request (through the coalescer when batching is on),
        per-point ``anomaly_score`` + ``is_anomaly`` back in request
        order.  503 when no anomaly runtime is configured
        (``serving.anomaly`` conf block).
        """
        anomaly = self.server.anomaly
        if anomaly is None:
            self._send(503, {"error": "anomaly detection not enabled "
                                      "(serving.anomaly conf block)"},
                       extra_headers=(("Retry-After", "60"),))
            return
        tracer = get_tracer()
        self._trace_id = _safe_trace_id(self.headers.get("X-Trace-Id"))
        try:
            with tracer.root_span(
                "http.request", trace_id=self._trace_id,
                method="POST", path="/detect_anomalies",
            ) as root:
                self._trace_id = root.trace_id or self._trace_id
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    self._send(400, {"error": "body must be a JSON object "
                                              "with 'points'"})
                    return
                points = req.get("points")
                if not points or not isinstance(points, list):
                    self._send(400, {"error": "body needs a non-empty "
                                              "'points' list"})
                    return
                if len(points) > anomaly.config.max_points_per_request:
                    self._send(400, {
                        "error": f"request has {len(points)} points; "
                                 f"max_points_per_request="
                                 f"{anomaly.config.max_points_per_request}"})
                    return
                threshold = req.get("threshold")
                if threshold is not None:
                    threshold = float(threshold)
                    if not threshold > 0:
                        self._send(400, {"error": "threshold must be > 0"})
                        return
                out = anomaly.score(
                    pd.DataFrame(points),
                    on_missing=req.get("on_missing", "skip"),
                    threshold=threshold)
                root.set_attribute("points", len(points))
                root.set_attribute("flagged", out["n_flagged"])
                self._send(200, out)
                root.set_attribute("status", self._status)
        except UnknownSeriesError as e:
            self._send(404, {"error": str(e)})
        except QueueFullError as e:
            self._send(429, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
        except (TimeoutError, _FutureTimeoutError) as e:
            self._send(503, {"error": f"request timed out: {e}" if str(e)
                             else "request timed out"},
                       extra_headers=(("Retry-After", "1"),))
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — scorer must not die mid-request
            self.server.logger.exception("detect_anomalies failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _ingest(self):
        """POST /ingest: new observations into the streaming WAL.

        Body: ``{"points": [{<key cols> | "keys": {...}, "ds": "..." or
        "d": <ordinal>, "y": ...}, ...]}``.  The append is durable before
        the response; in sync apply mode the response's ``applied`` block
        means a subsequent /invocations already reflects these points —
        the always-fresh contract, one batched update dispatch, no refit.
        """
        ingest = self.server.ingest
        if ingest is None:
            self._send(503, {"error": "streaming ingest not enabled "
                                      "(serving.ingest conf block)"},
                       extra_headers=(("Retry-After", "60"),))
            return
        tracer = get_tracer()
        self._trace_id = _safe_trace_id(self.headers.get("X-Trace-Id"))
        try:
            with tracer.root_span(
                "http.request", trace_id=self._trace_id,
                method="POST", path="/ingest",
            ) as root:
                self._trace_id = root.trace_id or self._trace_id
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    self._send(400, {"error": "body must be a JSON object "
                                              "with 'points'"})
                    return
                points = req.get("points")
                if not points or not isinstance(points, list):
                    self._send(400, {"error": "body needs a non-empty "
                                              "'points' list"})
                    return
                out = ingest.submit(points)
                root.set_attribute("points", len(points))
                self._send(200, out)
                root.set_attribute("status", self._status)
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._send(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — scorer must not die mid-request
            self.server.logger.exception("ingest failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})


class ForecastServer(PooledHTTPServer):
    # listen backlog, worker pool, keep-alive and TCP_NODELAY all come
    # from PooledHTTPServer + the serving.http conf block — shedding load
    # stays the batcher's 429, not the kernel's RST

    def __init__(
        self,
        addr,
        forecaster,
        model_version: Optional[str] = None,
        batching: Optional[BatchingConfig] = None,
        quality=None,
        ingest=None,
        extra_metrics=None,
        anomaly=None,
        cache=None,
        http: Optional[HttpConfig] = None,
    ):
        super().__init__(addr, _Handler, http=http)
        self.forecaster = forecaster
        self.model_version = model_version
        self.logger = get_logger("ForecastServer")
        self.metrics = ServingMetrics()
        self.busy_gauge = self.metrics.http_workers_busy
        self.batching = batching
        # extra exposition appended to GET /metrics — any object with a
        # ``render() -> str`` (sharded replicas attach their per-shard
        # registry here; see serving/sharding.ShardMetrics)
        self.extra_metrics = extra_metrics
        # the wired quality stack (monitoring/quality.QualityRuntime) —
        # owns the scrape + SLO loops, started here so every construction
        # path (serve, start_server, tests) gets the same lifecycle; the
        # latency SLO and the scrape loop bind to THIS server's metrics
        self.quality = quality
        if quality is not None:
            quality.attach_server_metrics(self.metrics)
            quality.start()
        # the streaming ingest runtime (serving/ingest.IngestRuntime) —
        # owns the WAL follower + refit scheduler threads; same lifecycle
        # story as quality: started here, stopped in shutdown()
        self.ingest = ingest
        if ingest is not None:
            ingest.start()
            self.logger.info(
                "streaming ingest on: wal_dir=%s apply_mode=%s refit=%s",
                ingest.wal.directory, ingest.config.apply_mode,
                "on" if ingest.refit is not None else "off")
        # the anomaly scorer (serving/anomaly.AnomalyScorer): detection
        # batches ride the SAME coalescing dispatch as forecast traffic,
        # so /detect_anomalies under load shares device batches with
        # /invocations instead of competing with them
        self.anomaly = anomaly
        if anomaly is not None:
            anomaly.bind_execute(self.execute)
            if ingest is not None and anomaly.config.stream_scoring:
                # streaming leg: every validated /ingest batch is scored
                # against the current bands (serving/ingest.py hooks this
                # BEFORE the sync apply — a point must not vouch for
                # itself)
                ingest.anomaly = anomaly
            self.logger.info(
                "anomaly detection on: threshold=%.3f stream_scoring=%s",
                anomaly.threshold,
                anomaly.config.stream_scoring and ingest is not None)
        # the materialized forecast cache (serving/forecast_cache) — reads
        # become row gathers from a current-epoch frame, with misses and
        # exotic requests falling through to the batcher/direct dispatch;
        # the cache subscribed itself to swap_state at construction, so no
        # lifecycle work is needed here beyond exposition
        self.cache = cache
        if cache is not None:
            self.logger.info(
                "forecast cache on: max_horizons=%d quantile_sets=%d "
                "mmap_dir=%s max_bytes=%d",
                cache.config.max_horizons, len(cache.config.quantile_sets),
                cache.config.mmap_dir, cache.config.max_bytes)
        # readiness is an Event, not a guarded flag: it is set exactly once
        # after warmup and cleared at shutdown, and /readyz polls it
        self._ready = threading.Event()
        self.batcher: Optional[RequestBatcher] = None
        if batching is not None and batching.enabled:
            self.batcher = RequestBatcher(forecaster, batching, self.metrics)
            self.logger.info(
                "micro-batching on: max_batch_size=%d max_wait_ms=%g "
                "max_queue_depth=%d request_timeout_s=%g",
                batching.max_batch_size, batching.max_wait_ms,
                batching.max_queue_depth, batching.request_timeout_s,
            )

    def execute(
        self,
        frame,
        horizon: int,
        include_history: bool,
        quantiles,
        on_missing: str,
        xreg,
        use_cache: bool = True,
    ):
        """Run one parsed /invocations request — through the coalescer when
        batching is on, as a direct forecaster call otherwise (both paths
        feed the same dispatch/batch-size metrics, so /metrics tells the
        coalescing story in either mode).  The materialized cache gets
        first refusal: a current-epoch hit is a row gather (no dispatch,
        no batch metrics — it genuinely wasn't one); a None is a miss or
        an inadmissible request and takes the dispatch path below.
        ``use_cache=False`` skips that refusal — the HTTP handler passes it
        after its own byte-level lookup already consulted (and counted)
        the cache for this request."""
        if use_cache and self.cache is not None:
            cached = self.cache.lookup(
                frame,
                horizon=horizon,
                include_history=include_history,
                quantiles=quantiles,
                on_missing=on_missing,
                xreg=xreg,
            )
            if cached is not None:
                return cached
        if self.batcher is not None:
            fut = self.batcher.submit(
                frame,
                horizon=horizon,
                include_history=include_history,
                quantiles=quantiles,
                on_missing=on_missing,
                xreg=xreg,
            )
            # the batcher already fails queued requests at their deadline;
            # this wait is the backstop for a request stuck IN a dispatch
            return fut.result(timeout=self.batching.request_timeout_s)
        self.metrics.dispatches.inc()
        self.metrics.batch_size.observe(1)
        if quantiles is not None:
            return self.forecaster.predict_quantiles(
                frame,
                quantiles=quantiles,
                horizon=horizon,
                include_history=include_history,
                on_missing=on_missing,
                xreg=xreg,
            )
        return self.forecaster.predict(
            frame,
            horizon=horizon,
            include_history=include_history,
            on_missing=on_missing,
            xreg=xreg,
        )

    def mark_ready(self) -> None:
        """Flip /readyz to 200 — called by the launcher AFTER warmup, so a
        supervisor never routes traffic at a replica still compiling."""
        self._ready.set()

    def readiness(self):
        """(ready, reason) for /readyz: warmup done and batcher accepting."""
        if not self._ready.is_set():
            return False, "warming up"
        if self.batcher is not None and not self.batcher.accepting:
            return False, "draining"
        return True, "ok"

    def shutdown(self):
        """Graceful: flip /readyz to 503 and drain the batching queue (every
        queued request gets its response) BEFORE stopping the accept loop
        and closing the socket."""
        self._ready.clear()
        if self.batcher is not None:
            self.batcher.close()
        if self.ingest is not None:
            # stop the follower + refit threads; the WAL itself stays on
            # disk — it is the durable half of the streaming contract
            self.ingest.stop()
        if self.quality is not None:
            # stop the SLO/scrape threads and flush one final scrape so the
            # on-disk history covers the full process lifetime
            self.quality.stop()
        super().shutdown()


def start_server(
    forecaster,
    host: str = "127.0.0.1",
    port: int = 0,
    model_version: Optional[str] = None,
    batching: Optional[BatchingConfig] = None,
    ready: bool = True,
    quality=None,
    ingest=None,
    extra_metrics=None,
    anomaly=None,
    cache=None,
    http: Optional[HttpConfig] = None,
) -> ForecastServer:
    """Start serving on a background thread; returns the server (its
    ``server_address[1]`` is the bound port — port=0 picks a free one).
    ``ready=False`` starts with /readyz at 503 until ``mark_ready()`` —
    for launchers that warm the compile ladder against the live server."""
    srv = ForecastServer((host, port), forecaster, model_version, batching,
                         quality=quality, ingest=ingest,
                         extra_metrics=extra_metrics, anomaly=anomaly,
                         cache=cache, http=http)
    if ready:
        srv.mark_ready()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def serve(
    forecaster,
    host: str = "0.0.0.0",
    port: int = 8080,
    model_version: Optional[str] = None,
    batching: Optional[BatchingConfig] = None,
    quality=None,
    ingest=None,
    anomaly=None,
    cache=None,
    http: Optional[HttpConfig] = None,
) -> None:
    srv = ForecastServer((host, port), forecaster, model_version, batching,
                         quality=quality, ingest=ingest, anomaly=anomaly,
                         cache=cache, http=http)
    srv.mark_ready()
    srv.logger.info("serving on %s:%d", host, port)
    srv.serve_forever()
