"""Streaming ingest: WAL-backed always-fresh forecasts.

ARIMA_PLUS's core serving claim (arXiv:2510.24452 §3) is that forecasts
never go stale because new rows flow INTO the model between full
re-trains.  This module is that path for the served JAX artifact:

    POST /ingest ──► WriteAheadLog (append-only JSONL segments)
                          │ follower read (torn-line tolerant)
                          ▼
                 SeriesStateStore.ingest ──► apply_pending
                          │                    (ONE batched update
                          ▼                     dispatch, AOT-cached)
                 BatchForecaster.swap_state ──► /invocations is fresh

The WAL is the source of truth and the ONLY route into model state:
``submit`` appends and then (sync mode) polls the log like any other
follower, so a single replica and a fleet sharing ``wal_dir`` run the
exact same code path — fleet convergence is just every replica's
follower cursor catching up to the same byte offset.  Segment naming,
``O_APPEND`` whole-line appends and the torn-line-tolerant follower read
are the ``monitoring/store`` machinery, reused
(:func:`monitoring.store.read_segments_from`).

Lock discipline mirrors :class:`monitoring.store.TimeSeriesStore`: the
append lock covers segment-cursor bookkeeping ONLY — the ``os.write``
itself happens outside, so an ingest burst never serializes behind disk
(and the dflint blocking-under-lock rule keeps it that way).  The poll
path serializes followers with a capacity-1 semaphore, the lint-exempt
capacity-limiter idiom, because a poll legitimately spans file reads and
a device dispatch.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import period_ordinals
from distributed_forecasting_tpu.engine.state_store import SeriesStateStore
from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.monitoring.monitor import IngestMetrics
from distributed_forecasting_tpu.monitoring.store import (
    read_segments_from,
    segment_indices,
    segment_path,
)
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.utils import get_logger

# How long stop() waits for the WAL follower before declaring the drain
# stuck (module-level so tests can shrink it without a 10s wall stall).
_JOIN_TIMEOUT_S = 10.0


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """The ``serving.ingest`` conf block (see conf/tasks/serve_config.yml)."""

    enabled: bool = False
    wal_dir: str = ""                 # "" -> caller supplies a default root
    max_segment_bytes: int = 4194304
    apply_mode: str = "sync"          # "sync": apply inline with POST /ingest
                                      # "interval": background follower poll
    apply_interval_ms: float = 200.0
    time_bucket: int = 32             # fitted/predict-grid growth increment
    observe_feeds_ingest: bool = False  # POST /observe actuals also ingest
    max_points_per_request: int = 10000
    max_pending_days: int = 366       # reject days past frontier + this:
                                      # the apply densifies that many
                                      # columns, so one typo'd far-future
                                      # ordinal must not OOM the fleet
    refit: dict = dataclasses.field(default_factory=dict)  # serving/refit.py

    def __post_init__(self):
        if self.apply_mode not in ("sync", "interval"):
            raise ValueError(
                f"apply_mode must be 'sync' or 'interval', "
                f"got {self.apply_mode!r}")
        if self.apply_interval_ms <= 0:
            raise ValueError("apply_interval_ms must be > 0")
        if self.time_bucket < 1:
            raise ValueError("time_bucket must be >= 1")
        if self.max_segment_bytes < 1024:
            raise ValueError("max_segment_bytes must be >= 1024")
        if self.max_points_per_request < 1:
            raise ValueError("max_points_per_request must be >= 1")
        if self.max_pending_days < 1:
            raise ValueError("max_pending_days must be >= 1")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "IngestConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like aply_mode must not silently fall back to sync
            raise ValueError(
                f"unknown serving.ingest conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in conf or conf[f.name] is None:
                continue
            if f.name == "refit":
                kwargs[f.name] = dict(conf[f.name])
            else:
                kwargs[f.name] = type(f.default)(conf[f.name])
        return cls(**kwargs)


class WriteAheadLog:
    """Append-only JSONL record log over numbered segments.

    Same on-disk format and discipline as the quality store's segments —
    one atomic ``O_APPEND`` write per batch, whole lines only, roll to a
    new segment past ``max_segment_bytes`` — but holding ingest RECORDS,
    and read through the follower API (:meth:`read_new`) instead of
    time-range queries.  Multiple processes may append to the same
    directory: ``O_APPEND`` keeps single-write lines atomic on POSIX, and
    the follower's rfind-newline read tolerates whatever interleaving
    lands.
    """

    def __init__(self, directory: str, max_segment_bytes: int = 4194304):
        self.directory = str(directory)
        self.max_segment_bytes = int(max_segment_bytes)
        os.makedirs(self.directory, exist_ok=True)
        idxs = segment_indices(self.directory)
        seg = idxs[-1] if idxs else 0
        seg_bytes = self._seal_torn_tail(segment_path(self.directory, seg))
        self._lock = threading.Lock()  # segment-cursor bookkeeping ONLY
        self._seg = seg
        self._seg_bytes = seg_bytes
        # dftsan (no-op unless DFTPU_TSAN armed): the append cursor pair
        sanitizer.attach(self, cls=WriteAheadLog, guards={
            "_lock": ("_seg", "_seg_bytes")})

    @staticmethod
    def _seal_torn_tail(path: str) -> int:
        """Recovery hygiene: if the live segment ends mid-line (the writer
        was SIGKILLed inside its ``os.write``), append a newline BEFORE
        this process's first append.  Without the seal, the new writer's
        first line would glue onto the torn fragment into one undecodable
        line and an acked batch would silently vanish on replay; with it,
        the fragment becomes its own skippable junk line.  Returns the
        segment's size (post-seal), the append cursor's starting point."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        if size == 0:
            return 0
        try:
            with open(path, "rb") as f:
                f.seek(size - 1)
                last = f.read(1)
            if last != b"\n":
                fd = os.open(path, os.O_WRONLY | os.O_APPEND)
                try:
                    os.write(fd, b"\n")
                finally:
                    os.close(fd)
                size += 1
        except OSError:
            pass  # read-only media etc.: appends will fail loudly anyway
        return size

    def append(self, records: List[Dict]) -> int:
        """Append record dicts as JSONL; one ``os.write``, outside the
        lock (snapshot-then-write, the TimeSeriesStore.append idiom)."""
        if not records:
            return 0
        payload = "".join(
            json.dumps(r, separators=(",", ":")) + "\n" for r in records
        ).encode()
        rolled = False
        with self._lock:
            if self._seg_bytes >= self.max_segment_bytes:
                self._seg += 1
                self._seg_bytes = 0
                rolled = True
            seg = self._seg
            path = segment_path(self.directory, seg)
            self._seg_bytes += len(payload)
        written = 0
        try:
            # fault sites live inside the try: an injected OSError takes
            # the same cursor-compensation path a real ENOSPC/EIO does
            if rolled:
                failpoint("wal.roll")
            failpoint("wal.append.enospc")
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                while written < len(payload):
                    written += os.write(fd, payload[written:])
            finally:
                os.close(fd)
        except OSError:
            # ENOSPC/EIO: compensate the cursor for bytes that never hit
            # disk, so roll decisions and stats() keep tracking durable
            # bytes instead of drifting ahead of the file forever
            with self._lock:
                if self._seg == seg:
                    self._seg_bytes = max(
                        self._seg_bytes - (len(payload) - written), 0)
            raise
        return len(records)

    def read_new(self, cursor: Optional[Dict[int, int]] = None,
                 ) -> Tuple[List[Dict], Dict[int, int]]:
        """(decoded records past ``cursor``, advanced cursor).  Lines that
        fail to decode (foreign writers, disk corruption) are skipped —
        the log must stay replayable end to end."""
        # the "wal.read" fault site lives in read_segments_from (shared
        # with the quality store's follower) — no second site here
        lines, cursor = read_segments_from(self.directory, cursor)
        records = []
        for line in lines:
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
        return records, cursor

    def stats(self) -> Dict[str, int]:
        idxs = segment_indices(self.directory)
        total = 0
        for i in idxs:
            try:
                total += os.path.getsize(segment_path(self.directory, i))
            except OSError:
                continue
        return {"segments": len(idxs), "bytes": total}


class IngestRuntime:
    """Glue between HTTP, the WAL, and the state store.

    ``submit`` validates + appends; applying ALWAYS goes through the
    follower read (:meth:`poll_apply`) so replicas sharing the WAL and
    the appending replica itself converge through one code path.
    """

    def __init__(self, config: IngestConfig, forecaster,
                 store: SeriesStateStore, wal: WriteAheadLog,
                 metrics: Optional[IngestMetrics] = None,
                 refit_scheduler=None):
        self.config = config
        self.forecaster = forecaster
        self.store = store
        self.wal = wal
        self.metrics = metrics if metrics is not None else IngestMetrics()
        self.refit = refit_scheduler
        # optional streaming anomaly leg (serving/anomaly.AnomalyScorer),
        # late-bound by ForecastServer when serving.anomaly.stream_scoring
        # is on: validated batches score against the CURRENT bands before
        # the sync apply moves the frontier
        self.anomaly = None
        self.logger = get_logger("IngestRuntime")
        self.key_names = tuple(forecaster.key_names)
        self._key_index = {
            tuple(k): i for i, k in enumerate(forecaster.keys.tolist())
        }
        self._cursor: Dict[int, int] = {}
        # capacity-1 semaphore, not a Lock: a poll spans file reads and a
        # device dispatch, the capacity-limiter case the lock lint exempts
        self._poll_gate = threading.BoundedSemaphore(1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- record parsing ------------------------------------------------------
    def _parse_record(self, rec: Dict) -> Tuple[Optional[Tuple], str]:
        """One request item -> ((sidx, day, y), "") or (None, reason).

        Accepts ``{"keys": {...}|[...], "ds": <date>|"d": <ordinal>,
        "y": <float>}``, or the flat ``/observe`` record shape with the
        key columns inline (``{"store": 1, "item": 2, "ds": ..., "y":
        ...}``); WAL rows use the compact ``{"k": [...], "d": n, "y": v}``
        form, which parses through the same path on replay.
        """
        try:
            raw = rec.get("k", rec.get("keys"))
            if raw is None:
                raw = {n: rec[n] for n in self.key_names}
            if isinstance(raw, dict):
                key = tuple(int(raw[n]) for n in self.key_names)
            else:
                key = tuple(int(v) for v in raw)
            if len(key) != len(self.key_names):
                return None, "key_arity"
            if "d" in rec:
                day = int(rec["d"])
            else:
                day = int(period_ordinals(
                    pd.DatetimeIndex([pd.Timestamp(rec["ds"])]),
                    self.forecaster.freq)[0])
            y = float(rec["y"])
        except (KeyError, TypeError, ValueError):
            return None, "malformed"
        if not np.isfinite(y):
            return None, "malformed"
        sidx = self._key_index.get(key)
        if sidx is None:
            return None, "unknown_series"
        return (sidx, day, y), ""

    # -- write path ----------------------------------------------------------
    def submit(self, records: List[Dict]) -> Dict:
        """Validate, WAL-append, and (sync mode) apply a request batch.

        Only points whose key matches a fitted series AND whose day falls
        inside ``[day0, frontier + max_pending_days]`` reach the WAL — the
        keyset and grid are frozen at fit time and shared by every
        replica, so filtering before the append keeps the log replayable
        anywhere: a typo'd far-future ordinal (or a wrong-century ``ds``)
        must never become a durable line that every restart and every
        fleet follower re-reads into a multi-GB apply allocation.
        """
        if len(records) > self.config.max_points_per_request:
            raise ValueError(
                f"request has {len(records)} points; "
                f"max_points_per_request={self.config.max_points_per_request}")
        horizon = self.store.day_cur + self.config.max_pending_days
        day0 = self.store.day0
        rows, unknown, malformed, out_of_range = [], 0, 0, 0
        for rec in records:
            parsed, reason = self._parse_record(rec)
            if parsed is None:
                if reason == "unknown_series":
                    unknown += 1
                else:
                    malformed += 1
                continue
            sidx, day, y = parsed
            if day < day0 or day > horizon:
                out_of_range += 1
                continue
            rows.append({"k": list(self._row_key(sidx)), "d": day, "y": y})
        out = {"written": len(rows), "unknown_series": unknown,
               "malformed": malformed, "out_of_range": out_of_range}
        if rows:
            with get_tracer().span("ingest.append", points=len(rows),
                                   wal_dir=self.wal.directory):
                self.wal.append(rows)  # dflint: disable=unlocked-shared-state — WriteAheadLog is internally synchronized; deliberately outside _poll_gate so appends never queue behind an apply
            self.metrics.points_total.inc(len(rows))
            self.metrics.wal_appends_total.inc()
        if unknown:
            self.metrics.unknown_series_total.inc(unknown)
        if out_of_range:
            self.metrics.out_of_range_total.inc(out_of_range)
        if rows and self.anomaly is not None:
            # streaming anomaly leg: score the batch against the bands as
            # they stand BEFORE this batch applies (a point must not
            # vouch for itself).  The WAL append above is already
            # durable, so a scoring failure must never fail the ingest.
            try:
                out["anomalies"] = self.anomaly.score_ingest(rows)
            except Exception:  # noqa: BLE001
                self.logger.exception("ingest anomaly scoring failed")
        if rows and self.config.apply_mode == "sync":
            out["applied"] = self.poll_apply()
        return out

    def _row_key(self, sidx: int) -> Tuple:
        return tuple(int(v) for v in self.forecaster.keys[sidx])

    # -- read/apply path (the follower) --------------------------------------
    def poll_apply(self) -> Dict:
        """Consume new WAL lines into the state store, then apply pending
        points in one batched dispatch.  Safe to call from any thread; the
        gate serializes concurrent followers, and a blocked caller re-reads
        after acquiring, so its own freshly appended lines are never missed.
        """
        with self._poll_gate:
            records, self._cursor = self.wal.read_new(self._cursor)
            counts = {"accepted": 0, "late": 0, "rejected": 0}
            if records:
                points = []
                for rec in records:
                    parsed, _ = self._parse_record(rec)
                    if parsed is not None:
                        points.append(parsed)
                routed = self.store.ingest(points)
                for k in counts:
                    counts[k] += routed[k]
                if counts["late"]:
                    self.metrics.late_points_total.inc(counts["late"])
            applied = self.store.apply_pending()
        self._publish_gauges()
        return {**counts, **applied}

    def _publish_gauges(self) -> None:
        st = self.store.stats()
        wal = self.wal.stats()
        m = self.metrics
        m.dirty_series.set(st["dirty_series"])
        m.pending_days.set(st["pending_days"])
        m.applied_day.set(st["day_cur"])
        m.refit_backlog.set(st["applied_since_refit"])
        m.wal_bytes.set(wal["bytes"])
        m.wal_segments.set(wal["segments"])

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self.config.apply_mode == "interval" and self._thread is None:
            self._stop.clear()  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
            self._thread = threading.Thread(  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
                target=self._run, name="ingest-follower", daemon=True)
            self._thread.start()
        if self.refit is not None:
            self.refit.start()

    def _run(self) -> None:
        interval = self.config.apply_interval_ms / 1000.0
        while not self._stop.wait(interval):
            try:
                self.poll_apply()
            except Exception:
                self.logger.exception("WAL follower poll failed")

    def stop(self) -> None:
        if self.refit is not None:
            self.refit.stop()
        self._stop.set()
        thread = self._thread
        if thread is not None:
            # NOT under _poll_gate: the follower takes the gate inside
            # poll_apply, so joining while holding it would deadlock
            thread.join(timeout=_JOIN_TIMEOUT_S)
            if thread.is_alive():
                # the poll is wedged (hung disk, stuck device dispatch):
                # the daemon thread leaks past this shutdown and may still
                # mutate state while teardown proceeds — say so loudly
                # instead of pretending the drain succeeded
                self.metrics.ingest_shutdown_stuck_total.inc()
                self.logger.error(
                    "WAL follower thread still alive after %.0fs join; "
                    "leaking it (daemon) — shutdown is NOT clean",
                    _JOIN_TIMEOUT_S)
            else:
                self._thread = None  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread

    # -- exposition ----------------------------------------------------------
    def render_metrics(self) -> str:
        self._publish_gauges()
        return self.metrics.registry.render_prometheus()

    def snapshot(self) -> Dict:
        out = {"store": self.store.stats(), "wal": self.wal.stats(),
               "apply_mode": self.config.apply_mode}
        if self.refit is not None:
            out["refit"] = self.refit.snapshot()
        return out


def build_ingest_runtime(conf: Optional[dict], forecaster,
                         history_y=None, history_mask=None,
                         quality=None,
                         default_wal_dir: Optional[str] = None,
                         wal_factory=None,
                         ) -> Optional[IngestRuntime]:
    """``serving.ingest`` conf block -> a started-able runtime (or None
    when the block is absent/disabled).  ``history_y``/``history_mask``
    enable full refits; without them the scheduler is skipped and only
    the incremental path runs (a bare-artifact deployment).
    ``wal_factory(wal_dir, max_segment_bytes)`` overrides the log
    construction — sharded replicas substitute a per-shard-namespace
    facade (``serving/sharding.py``) that duck-types the single log."""
    config = IngestConfig.from_conf(conf)
    if not config.enabled:
        return None
    wal_dir = config.wal_dir or default_wal_dir
    if not wal_dir:
        raise ValueError(
            "serving.ingest.wal_dir is empty and no default was supplied")
    metrics = IngestMetrics()
    store = SeriesStateStore(
        forecaster, time_bucket=config.time_bucket,
        history_y=history_y, history_mask=history_mask, metrics=metrics,
        max_pending_days=config.max_pending_days)
    if wal_factory is not None:
        wal = wal_factory(wal_dir, config.max_segment_bytes)
    else:
        wal = WriteAheadLog(
            wal_dir, max_segment_bytes=config.max_segment_bytes)
    refit_scheduler = None
    if config.refit:
        from distributed_forecasting_tpu.serving.refit import (
            RefitConfig,
            RefitScheduler,
        )
        refit_config = RefitConfig.from_conf(config.refit)
        if refit_config.enabled:
            if not store.can_refit:
                raise ValueError(
                    "serving.ingest.refit is enabled but no training "
                    "history was supplied to build_ingest_runtime")
            refit_scheduler = RefitScheduler(
                store, refit_config, quality=quality, metrics=metrics)
    return IngestRuntime(config, forecaster, store, wal, metrics=metrics,
                         refit_scheduler=refit_scheduler)
