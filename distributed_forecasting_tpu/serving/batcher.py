"""Micro-batching request coalescer: continuous batching for the scorer.

The serving story so far loads the artifact once and makes every request a
single request-proportional compiled call (``serving/predictor.py``) — but
the HTTP surface is a ``ThreadingHTTPServer``, so N concurrent clients mean
N independent small device dispatches contending on one chip, each paying
its own dispatch round trip (docs/benchmarks.md measures that round trip at
~66 ms on the remote-TPU tunnel — as large as an entire 500-series fit).
The reference's batch path amortizes exactly this by scoring whole key sets
in one PyFunc dispatch (``04_inference.py``); this module is the online
analogue, the continuous-batching idiom of modern inference stacks:

  * handler threads ``submit()`` parsed requests into a bounded queue and
    block on a ``Future`` (admission control: over-depth requests are
    rejected immediately — the server maps that to 429 — and requests that
    outlive ``request_timeout_s`` fail with ``TimeoutError`` — mapped to
    503);
  * ONE scheduler thread drains the queue each tick (waiting at most
    ``max_wait_ms`` after the first arrival, less whatever the request
    already waited, or until ``max_batch_size`` are pending), groups the
    drained requests by compile signature ``(horizon, include_history,
    quantiles, on_missing)``, concatenates each group's series keys into a
    single merged ``predict``/``predict_quantiles`` call, and scatters
    per-request result slices back through the futures
    (``predictor.result_block_index``);
  * because scattering relies on request-order per-series blocks being
    bit-identical across request-size buckets, merging only happens when the
    forecaster declares ``coalesce_safe`` (BatchForecaster does; composites
    reorder rows by member family and go through the same scheduler one
    request per dispatch — they still get admission control, timeouts and
    metrics).  Requests carrying ``xreg`` are never merged: two requests'
    regressor tensors have no well-defined concatenation.

Failure isolation: if a merged call raises (e.g. one request's unknown key
under ``on_missing='raise'``), the batch falls back to per-request dispatch
so a poisoned request cannot fail its neighbors.

Telemetry rides on ``monitoring/monitor.py`` primitives and is exposed by
the server's ``GET /metrics`` (Prometheus text format): request / coalesced
dispatch / rejection / timeout counters, a queue-depth gauge, and latency +
batch-size histograms.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional

import pandas as pd

from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import clock as trace_clock
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.serving.predictor import result_block_index
from distributed_forecasting_tpu.utils import get_logger


class QueueFullError(RuntimeError):
    """Admission control: the pending queue is at max_queue_depth (-> 429)."""


class ShuttingDownError(RuntimeError):
    """The batcher stopped accepting work (server shutdown in progress)."""


# latency: sub-ms CPU cache hits through multi-second cold compiles
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# coalesced requests per device dispatch
_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


class ServingMetrics:
    """The scorer's live telemetry, one registry per server process.

    Names follow the Prometheus convention; the server increments the
    request-outcome counters (it owns the HTTP status mapping), the batcher
    owns dispatch/batch-size/queue-depth.
    """

    def __init__(self) -> None:
        r = MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "serving_requests_total", "POST /invocations requests received")
        self.rejections = r.counter(
            "serving_rejections_total",
            "requests rejected by admission control (HTTP 429)")
        self.timeouts = r.counter(
            "serving_timeouts_total",
            "requests that exceeded request_timeout_s (HTTP 503)")
        self.errors = r.counter(
            "serving_errors_total", "requests that failed with HTTP 500")
        self.deadline_shed = r.counter(
            "serving_deadline_shed_total",
            "requests shed before dispatch because their X-Deadline-Ms "
            "budget was already exhausted (HTTP 503)")
        self.dispatches = r.counter(
            "serving_dispatches_total",
            "forecaster predict calls (coalesced device dispatches)")
        self.queue_depth = r.gauge(
            "serving_queue_depth", "requests waiting in the batching queue")
        self.http_workers_busy = r.gauge(
            "dftpu_http_workers_busy",
            "HTTP pool workers currently handling a request (fleet mode: "
            "summed across replicas — per-replica busy counts are additive)")
        self.latency = r.histogram(
            "serving_request_latency_seconds", _LATENCY_BUCKETS,
            "request latency, parse to response")
        self.batch_size = r.histogram(
            "serving_batch_size", _BATCH_BUCKETS,
            "requests coalesced into each dispatch")

    def render(self) -> str:
        # The compile-cache, training-pipeline, and cost registries ride
        # along on /metrics so operators can watch warmup hit/miss
        # behaviour, executor occupancy, and device-time/FLOPs/watermark
        # telemetry without a second endpoint.
        from distributed_forecasting_tpu.engine.compile_cache import (
            metrics_registry,
        )
        from distributed_forecasting_tpu.monitoring.cost import cost_metrics
        from distributed_forecasting_tpu.monitoring.monitor import (
            pipeline_metrics,
        )

        return (self.registry.render_prometheus()
                + metrics_registry().render_prometheus()
                + pipeline_metrics().registry.render_prometheus()
                + cost_metrics().registry.render_prometheus())

    def snapshot(self) -> dict:
        return self.registry.snapshot()


@dataclasses.dataclass(frozen=True)
class BatchingConfig:
    """The ``serving.batching`` conf block (tasks/serve.py)."""

    enabled: bool = False
    max_batch_size: int = 64      # requests merged into one dispatch
    max_wait_ms: float = 5.0      # coalescing window after first arrival
    max_queue_depth: int = 256    # admission-control bound (429 past it)
    request_timeout_s: float = 30.0

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {self.max_batch_size}")
        if self.max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {self.max_wait_ms}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.request_timeout_s <= 0:
            raise ValueError(
                f"request_timeout_s must be > 0, got {self.request_timeout_s}")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "BatchingConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like max_batchsize must not silently serve unbatched
            raise ValueError(
                f"unknown batching conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        return cls(
            enabled=bool(conf.get("enabled", False)),
            max_batch_size=int(conf.get("max_batch_size", 64)),
            max_wait_ms=float(conf.get("max_wait_ms", 5.0)),
            max_queue_depth=int(conf.get("max_queue_depth", 256)),
            request_timeout_s=float(conf.get("request_timeout_s", 30.0)),
        )


@dataclasses.dataclass
class _Pending:
    frame: pd.DataFrame
    horizon: int
    include_history: bool
    quantiles: Optional[tuple]
    on_missing: str
    xreg: object
    future: Future
    enqueued_at: float
    deadline: float
    # the submitting request's TraceContext (None when tracing is off or the
    # caller had no open span): the scheduler thread adopts it so the
    # merged dispatch lands in the submitter's trace
    trace_ctx: object = None

    def signature(self, coalesce_safe: bool):
        """Requests merge iff their compiled program and merge semantics
        match; xreg / non-coalescable forecasters force singleton groups."""
        if not coalesce_safe or self.xreg is not None:
            return ("solo", id(self))
        return (self.horizon, self.include_history, self.quantiles,
                self.on_missing)


class RequestBatcher:
    """Background scheduler draining a bounded queue into merged dispatches."""

    def __init__(self, forecaster, config: BatchingConfig,
                 metrics: Optional[ServingMetrics] = None):
        self.forecaster = forecaster
        self.config = config
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.logger = get_logger("RequestBatcher")
        self._coalesce_safe = bool(getattr(forecaster, "coalesce_safe", False))
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        # dftsan (no-op unless DFTPU_TSAN armed): MUST run before the
        # scheduler thread starts, so producer and scheduler see the same
        # (wrapped) condition object
        sanitizer.attach(self, cls=RequestBatcher, guards={
            "_cond": ("_queue", "_closed")})
        self._thread = threading.Thread(
            target=self._run, name="dftpu-batcher", daemon=True)
        self._thread.start()

    # -- producer side (handler threads) ------------------------------------
    def submit(
        self,
        frame: pd.DataFrame,
        horizon: int = 90,
        include_history: bool = False,
        quantiles: Optional[tuple] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> Future:
        """Enqueue a parsed request; the returned future resolves to the
        result frame (or the exception a solo call would have raised)."""
        # time.monotonic IS the trace clock (monitoring.trace.clock), so
        # enqueued_at doubles as the queue-wait span's start timestamp
        now = time.monotonic()
        item = _Pending(
            frame=frame,
            horizon=int(horizon),
            include_history=bool(include_history),
            quantiles=None if quantiles is None else tuple(quantiles),
            on_missing=on_missing,
            xreg=xreg,
            future=Future(),
            enqueued_at=now,
            deadline=now + self.config.request_timeout_s,
            trace_ctx=get_tracer().current(),
        )
        with self._cond:
            if self._closed:
                raise ShuttingDownError("server is shutting down")
            if len(self._queue) >= self.config.max_queue_depth:
                raise QueueFullError(
                    f"request queue is full "
                    f"({self.config.max_queue_depth} pending)")
            self._queue.append(item)
            self.metrics.queue_depth.set(len(self._queue))
            self._cond.notify()
        return item.future

    def close(self, timeout: float = 60.0) -> None:
        """Stop accepting work and DRAIN: everything already queued is
        dispatched and its future resolved before this returns."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - stuck device call
            self.logger.warning("batcher thread did not drain within %.1fs",
                                timeout)

    @property
    def accepting(self) -> bool:
        """False once close() has started — the server's /readyz input."""
        with self._cond:
            return not self._closed

    # -- scheduler side ------------------------------------------------------
    def _run(self) -> None:
        cfg = self.config
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._queue or self._closed)
                if not self._queue:
                    return  # closed and drained
                # coalescing window: measured from the FIRST waiter's arrival
                # (it may already have sat out a full dispatch), cut short
                # when a full batch is pending or shutdown starts
                first = self._queue[0]
                budget = (first.enqueued_at + cfg.max_wait_ms / 1000.0
                          - time.monotonic())
                if budget > 0 and not self._closed:
                    self._cond.wait_for(
                        lambda: len(self._queue) >= cfg.max_batch_size
                        or self._closed,
                        timeout=budget,
                    )
                batch = list(self._queue)
                self._queue.clear()
                self.metrics.queue_depth.set(0)
            self._process(batch)

    def _process(self, batch: list) -> None:
        now = time.monotonic()
        tracer = get_tracer()
        live: dict = {}
        for item in batch:
            if now > item.deadline:
                # expired while queued: fail fast instead of spending a
                # dispatch on a response nobody is waiting for
                tracer.record_span(
                    "batcher.queue_wait", item.enqueued_at, now,
                    ctx=item.trace_ctx, expired=True)
                item.future.set_exception(TimeoutError(
                    f"request timed out after "
                    f"{self.config.request_timeout_s:g}s in queue"))
                continue
            live.setdefault(item.signature(self._coalesce_safe), []).append(item)
        for group in live.values():
            for i in range(0, len(group), self.config.max_batch_size):
                self._dispatch(group[i : i + self.config.max_batch_size])

    def _call(self, item: _Pending, frame: pd.DataFrame) -> pd.DataFrame:
        self.metrics.dispatches.inc()
        if item.quantiles is not None:
            return self.forecaster.predict_quantiles(
                frame,
                quantiles=item.quantiles,
                horizon=item.horizon,
                include_history=item.include_history,
                on_missing=item.on_missing,
                xreg=item.xreg,
            )
        return self.forecaster.predict(
            frame,
            horizon=item.horizon,
            include_history=item.include_history,
            on_missing=item.on_missing,
            xreg=item.xreg,
        )

    def _dispatch(self, chunk: list) -> None:
        self.metrics.batch_size.observe(len(chunk))
        tracer = get_tracer()
        now = trace_clock()
        for item in chunk:
            # queue wait is explicit in every trace: enqueued_at was read
            # from the same monotonic clock, so this is exact, not inferred
            tracer.record_span("batcher.queue_wait", item.enqueued_at, now,
                               ctx=item.trace_ctx)
        # the scheduler thread adopts the FIRST request's trace; coalesced
        # neighbors are correlated through the trace_ids attribute (one
        # dispatch span cannot parent into N traces)
        with tracer.context(chunk[0].trace_ctx):
            with tracer.span(
                "batcher.dispatch",
                batch_size=len(chunk),
                merged=len(chunk) > 1,
                trace_ids=[item.trace_ctx.trace_id for item in chunk
                           if item.trace_ctx is not None],
            ) as span:
                # the predictor records per-dispatch device time into the
                # cost registry; the attribution scope sums THIS thread's
                # recordings so the span carries the chunk's total even
                # when a solo-retry fans one chunk into many dispatches
                from distributed_forecasting_tpu.monitoring.cost import (
                    cost_metrics,
                )

                with cost_metrics().attribution() as acc:
                    self._dispatch_inner(chunk, span)
                span.set_attribute("device_seconds", acc["device_seconds"])

    def _dispatch_inner(self, chunk: list, span) -> None:
        if len(chunk) == 1:
            item = chunk[0]
            try:
                item.future.set_result(self._call(item, item.frame))
            except Exception as e:  # noqa: BLE001 - scatter to the waiter
                span.set_attribute("outcome", f"error:{type(e).__name__}")
                item.future.set_exception(e)
            return
        try:
            self._dispatch_merged(chunk)
        except Exception:  # noqa: BLE001
            # isolation: one poisoned request (unknown key under
            # on_missing='raise', bad payload the parser let through) must
            # not fail its coalesced neighbors — retry each solo
            self.logger.exception(
                "merged dispatch of %d requests failed; retrying solo",
                len(chunk))
            span.set_attribute("outcome", "solo-retry")
            for item in chunk:
                try:
                    item.future.set_result(self._call(item, item.frame))
                except Exception as e:  # noqa: BLE001
                    item.future.set_exception(e)

    def _dispatch_merged(self, chunk: list) -> None:
        names = list(self.forecaster.key_names)
        per_request = [
            list(dict.fromkeys(
                tuple(r) for r in item.frame[names].itertuples(index=False)))
            for item in chunk
        ]
        merged_keys = list(dict.fromkeys(
            k for keys in per_request for k in keys))
        merged = pd.DataFrame(merged_keys, columns=names)
        out = self._call(chunk[0], merged)
        T, block_of = result_block_index(out, names)
        for item, keys in zip(chunk, per_request):
            blocks = [
                out.iloc[block_of[k] * T : (block_of[k] + 1) * T]
                for k in keys
                if k in block_of  # on_missing='skip' drops unknown keys
            ]
            if len(blocks) == 1:
                # the common single-series request: slice, don't concat
                # (this scatter runs on the one scheduler thread, so its
                # per-request cost bounds coalesced throughput)
                part = blocks[0].reset_index(drop=True)
            elif blocks:
                part = pd.concat(blocks, ignore_index=True)
            else:
                part = out.iloc[0:0].reset_index(drop=True)
            item.future.set_result(part)
