"""Serving data plane: keep-alive pooling and worker-pool HTTP servers.

PR 16's materialized forecast cache made a replica-level cache hit a
~0.07 ms row gather, but BENCH_r09 still measured ``qps_speedup_http:
1.0`` — every HTTP read paid a fresh TCP handshake (client AND front-door
leg), a Nagle-delayed small write, and an unbounded ``ThreadingHTTPServer``
thread spawn.  This module is the transport half of the fix; the encoding
half (the serialized-response byte cache) lives in
``serving/forecast_cache.lookup_response``.

Three pieces, shared by the replica server and the fleet front door:

* :class:`HttpConfig` — the strict ``serving.http`` conf block (unknown
  keys hard-error, same contract as every other serving block);
* :class:`ConnectionPool` — bounded per-replica pools of persistent
  keep-alive ``HTTPConnection``s for the front door's forward/scatter/
  health legs.  Lock discipline matches the supervisor's (dflint's
  blocking-under-lock rule gates this file): ``_lock`` only snapshots or
  updates the idle lists — connect/close/settimeout all run OUTSIDE the
  critical section.  Telemetry: ``dftpu_http_pool_{open,reused,evicted}_
  total`` counters and an ``http.conn_acquire`` span per checkout.
* :class:`PooledHTTPServer` + :class:`KeepAliveHandlerMixin` — HTTP/1.1
  keep-alive with an idle timeout (a silent client cannot pin a worker
  forever), ``TCP_NODELAY`` on accepted sockets, a listen backlog sized
  for read bursts, and a BOUNDED pre-spawned worker pool replacing
  thread-per-request (the ``dftpu_http_workers_busy`` gauge reports
  saturation).  Graceful drain is preserved: shutdown stops admission,
  lets queued requests finish, and closes keep-alive connections after
  their in-flight request.

A half-closed pooled connection (the replica restarted, or its idle
timer fired a beat before ours) surfaces as ``RemoteDisconnected``/
``ECONNRESET`` on the NEXT request.  The pool cannot prevent that race,
so callers that acquired a REUSED connection retry once on a
guaranteed-fresh one before reporting failure — predict is idempotent,
and the retry keeps the race invisible to clients (zero 5xx).
"""

from __future__ import annotations

import dataclasses
import http.client
import queue
import socket
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.utils import get_logger


@dataclasses.dataclass(frozen=True)
class HttpConfig:
    """The ``serving.http`` conf block (see conf/tasks/serve_config.yml).

    Parsed by BOTH the fleet task (front door + forward pool) and each
    replica (its own server), so one block tunes the whole data plane.
    """

    keepalive: bool = True        # HTTP/1.1 persistent connections
    pool_size: int = 8            # idle outbound connections kept per replica
    workers: int = 16             # bounded handler pool (was: unbounded)
    idle_timeout_s: float = 30.0  # reap keep-alive sockets idle this long

    def __post_init__(self):
        if self.pool_size < 1:
            raise ValueError(
                f"pool_size must be >= 1, got {self.pool_size}")
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if self.idle_timeout_s <= 0:
            raise ValueError(
                f"idle_timeout_s must be > 0, got {self.idle_timeout_s}")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "HttpConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like pool_sizes must not silently fall back to defaults
            raise ValueError(
                f"unknown serving.http conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf
        }
        return cls(**kwargs)


def _set_nodelay(sock) -> None:
    """TCP_NODELAY on an outbound socket: a forwarded request is one small
    write followed by a read — Nagle would hold the tail segment for the
    peer's delayed ACK (up to ~40 ms) for no batching benefit."""
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (tests may inject fakes)


class ConnectionPool:
    """Bounded per-(host, port) pools of idle keep-alive connections.

    Thread-safety (the dflint ``unlocked-shared-state`` shape): ``_lock``
    guards the idle lists and the closed flag; every blocking socket call
    — connect, close, settimeout — happens OUTSIDE the critical section on
    connections no other thread can reach (checked out, or popped for
    eviction).  LIFO checkout keeps the warmest socket in play and lets
    the cold end of the list age out via ``idle_timeout_s``.
    """

    def __init__(self, config: Optional[HttpConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.config = config or HttpConfig()
        self.logger = get_logger("ConnectionPool")
        self._lock = threading.Lock()
        # (host, port) -> [(conn, released_at monotonic), ...] newest last
        self._idle: Dict[Tuple[str, int], List[tuple]] = {}
        self._closed = False
        r = registry if registry is not None else MetricsRegistry()
        self.registry = r
        self.opened = r.counter(
            "dftpu_http_pool_open_total",
            "outbound connections the pool dialed fresh")
        self.reused = r.counter(
            "dftpu_http_pool_reused_total",
            "checkouts served by an idle keep-alive connection")
        self.evicted = r.counter(
            "dftpu_http_pool_evicted_total",
            "pooled connections closed instead of reused (unhealthy "
            "release, idle expiry, overflow, breaker/drain purge)")
        # dftsan (no-op unless DFTPU_TSAN armed): the idle lists every
        # forward/probe/scatter leg checks out of concurrently
        sanitizer.attach(self, cls=ConnectionPool, guards={
            "_lock": ("_idle", "_closed")})

    def acquire(self, host: str, port: int, timeout: float):
        """Check out a connection to ``host:port`` -> ``(conn, reused)``.

        ``reused`` tells the caller whether a request failure may be the
        half-closed-keep-alive race (retry once fresh) or a real peer
        failure (report it).  The checkout is traced as
        ``http.conn_acquire`` with the reuse outcome."""
        with get_tracer().span("http.conn_acquire", port=int(port)) as span:
            conn = None
            expired: List = []
            if self.config.keepalive:
                now = time.monotonic()
                with self._lock:
                    bucket = self._idle.get((host, int(port)))
                    while bucket:
                        cand, released_at = bucket.pop()
                        if now - released_at <= self.config.idle_timeout_s:
                            conn = cand
                            break
                        expired.append(cand)
            for cand in expired:  # close outside the lock
                self.evicted.inc()
                cand.close()
            if conn is not None:
                self.reused.inc()
                span.set_attribute("outcome", "reused")
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                return conn, True
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            conn.connect()
            _set_nodelay(conn.sock)
            self.opened.inc()
            span.set_attribute("outcome", "open")
            return conn, False

    def release(self, conn, healthy: bool = True) -> None:
        """Return a checked-out connection.  Only a healthy one (response
        fully read, server not closing — ``not resp.will_close``) is
        pooled; everything else closes.  Overflow beyond ``pool_size``
        closes the returned connection (the newest-released socket is the
        one most likely to be reaped by the peer's idle timer anyway)."""
        if not self.config.keepalive or not healthy:
            if self.config.keepalive:
                self.evicted.inc()
            conn.close()
            return
        pooled = False
        with self._lock:
            if not self._closed:
                bucket = self._idle.setdefault(
                    (conn.host, int(conn.port)), [])
                if len(bucket) < self.config.pool_size:
                    bucket.append((conn, time.monotonic()))
                    pooled = True
        if not pooled:
            self.evicted.inc()
            conn.close()

    def discard(self, conn) -> None:
        """Drop a checked-out connection that failed mid-request."""
        self.evicted.inc()
        conn.close()

    def drain(self, host: str, port: int) -> int:
        """Close every idle connection to one replica — called when its
        breaker opens, its process is killed, or a forward fails at the
        connection level: the pooled sockets point at a peer that just
        proved unreliable, and the next checkout should dial fresh."""
        with self._lock:
            bucket = self._idle.pop((host, int(port)), [])
        for conn, _ in bucket:
            self.evicted.inc()
            conn.close()
        return len(bucket)

    def close(self) -> None:
        """Close every idle connection and refuse future pooling (in-flight
        checkouts finish and close on release)."""
        with self._lock:
            self._closed = True
            buckets = list(self._idle.values())
            self._idle = {}
        for bucket in buckets:
            for conn, _ in bucket:
                self.evicted.inc()
                conn.close()

    def idle_count(self, host: str, port: int) -> int:
        with self._lock:
            return len(self._idle.get((host, int(port)), ()))


def pooled_get(pool: ConnectionPool, host: str, port: int, path: str,
               timeout: float):
    """One GET over the pool -> ``(status, body)``.

    Retries once on a fresh connection when a REUSED socket fails (the
    half-closed keep-alive race); a fresh-connection failure propagates —
    that is a real peer failure the caller must account."""
    for attempt in (0, 1):
        conn, reused = pool.acquire(host, port, timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
        except (OSError, http.client.HTTPException):
            pool.discard(conn)
            if reused and attempt == 0:
                continue
            raise
        pool.release(conn, healthy=not resp.will_close)
        return resp.status, body


class KeepAliveHandlerMixin:
    """Mix into a ``BaseHTTPRequestHandler`` serving from a
    :class:`PooledHTTPServer`: HTTP/1.1 persistent connections with an
    idle timeout, and ``TCP_NODELAY`` on the accepted socket."""

    #: socketserver.StreamRequestHandler: setsockopt(TCP_NODELAY) in setup()
    disable_nagle_algorithm = True

    def setup(self):
        http_cfg = getattr(self.server, "http", None)
        if http_cfg is not None and http_cfg.keepalive:
            # per-instance (class default stays HTTP/1.0 so keepalive=false
            # keeps the old close-per-request behavior).  self.timeout must
            # be set BEFORE super().setup(): StreamRequestHandler applies it
            # as the socket timeout, and handle_one_request turns the
            # resulting socket.timeout into close_connection — an idle
            # keep-alive client frees its worker after idle_timeout_s.
            self.protocol_version = "HTTP/1.1"
            self.timeout = http_cfg.idle_timeout_s
        super().setup()

    def handle_one_request(self):
        super().handle_one_request()
        if getattr(self.server, "_pool_draining", False):
            # graceful drain: finish the in-flight request, then close the
            # persistent connection instead of waiting out the idle timer
            self.close_connection = True


class PooledHTTPServer(ThreadingHTTPServer):
    """``ThreadingHTTPServer`` with a BOUNDED pre-spawned worker pool.

    Thread-per-request hands a load spike an unbounded thread count before
    admission control ever runs; here ``http.workers`` daemon workers pull
    accepted connections off a bounded queue (admission backpressure falls
    back to the kernel's listen backlog, sized below).  Workers are plain
    daemon threads, NOT a ``ThreadPoolExecutor`` — executor workers are
    joined at interpreter exit, and one blocked in an idle keep-alive read
    would hang process shutdown.
    """

    daemon_threads = True
    # socketserver's default listen backlog is 5 — a read burst (exactly
    # the traffic the byte cache exists for) would get kernel RSTs before
    # a worker ever ran.  512 absorbs the burst; shedding stays the
    # application's job (the batcher's 429), not the kernel's.
    request_queue_size = 512

    def __init__(self, addr, handler_cls,
                 http: Optional[HttpConfig] = None):
        super().__init__(addr, handler_cls)
        self.http = http or HttpConfig()
        # set by the owner once its metrics exist (ServingMetrics is built
        # after super().__init__ in ForecastServer); None = no telemetry
        self.busy_gauge = None
        self._pool_draining = False
        self._work: queue.Queue = queue.Queue(maxsize=self.http.workers * 4)
        self._workers = [
            threading.Thread(target=self._worker_loop,
                             name=f"http-worker-{i}", daemon=True)
            for i in range(self.http.workers)
        ]
        for t in self._workers:
            t.start()

    def process_request(self, request, client_address):
        """Accept-loop side: enqueue instead of spawning a thread.  A full
        queue blocks the accept loop in short waits — backpressure lands in
        the listen backlog, and a drain wakes us out of the wait."""
        while True:
            if self._pool_draining:
                self.shutdown_request(request)
                return
            try:
                self._work.put((request, client_address), timeout=0.1)
                return
            except queue.Full:
                continue

    def _worker_loop(self):
        while True:
            item = self._work.get()
            if item is None:
                return
            request, client_address = item
            gauge = self.busy_gauge
            if gauge is not None:
                gauge.inc()
            try:
                # mirror ThreadingMixIn.process_request_thread
                try:
                    self.finish_request(request, client_address)
                except Exception:  # noqa: BLE001 — a worker must outlive one bad request
                    self.handle_error(request, client_address)
                finally:
                    self.shutdown_request(request)
            finally:
                if gauge is not None:
                    gauge.dec()

    def shutdown(self):
        """Stop admission, let queued requests finish, and release the
        workers.  In-flight keep-alive connections close after their
        current request (``KeepAliveHandlerMixin.handle_one_request``)."""
        self._pool_draining = True
        super().shutdown()
        for _ in self._workers:
            try:
                # FIFO: sentinels land BEHIND already-queued requests, so
                # the drain serves them first.  A full queue is fine — the
                # workers are daemon threads and die with the process.
                self._work.put_nowait(None)
            except queue.Full:
                break
