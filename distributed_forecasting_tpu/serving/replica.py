"""Fleet replica entrypoint: one serving process under the supervisor.

Launched by ``serving/fleet.py``'s default spawner as

    python -m distributed_forecasting_tpu.serving.replica --conf '<json>'

The conf object carries: ``artifact_dir`` (the saved forecaster to load),
``host``/``port`` (the supervisor-assigned, restart-stable address),
``warmup_sizes``/``warmup_horizon``, optional ``batching``/``tracing``
blocks (same shapes as the ``serving:`` conf), ``model_version``,
``mesh_devices`` (>1 shards every predict's series axis over a device mesh
— ``BatchForecaster.enable_mesh``), an optional ``monitoring`` block
(quality/store/SLO — ``monitoring/quality.py``; the replica suffixes the
store directory with its port so replicas never share an append cursor),
an optional ``cache`` block (``serving/forecast_cache.py`` — the replica
suffixes the persistence directory with its port: a sharded replica's
materialized frames cover only its owned series and must never be adopted
by a sibling), and an optional ``ingest`` block (``serving/ingest.py``).
Unlike the
quality store, the ingest WAL directory is deliberately SHARED across the
fleet: each replica appends O_APPEND whole lines and follows the log with
its own cursor in ``interval`` apply mode, so a point posted through any
replica converges into every replica's model state — the front door can
round-robin /ingest like any other POST.

With a ``sharding`` block plus a ``shards`` assignment list (sharded
fleets — ``serving/sharding.py``), the replica subsets its forecaster,
history sidecar, and WAL follow-set to the owned shards before warmup:
resident series drop to ~S*owned/num_shards, only the owned
``wal_dir/shard-<k>/`` namespaces are replayed, and the backlog replay
happens BEFORE ``/readyz`` flips (the supervisor's hand-off gate).

Boot order is the contract the supervisor routes on: bind the port with
``/readyz`` at 503 first, warm the bucket ladder, THEN flip ready — a
replica never receives traffic while it is still compiling.  The shared
AOT store (``DFTPU_COMPILE_CACHE`` in the spawn env) makes every warmup
after the fleet's first a deserialize, not a compile.  SIGTERM drains
gracefully: /readyz flips to 503, queued requests finish, then the socket
closes.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--conf", required=True,
                    help="JSON replica config (see module docstring)")
    args = ap.parse_args(argv)
    conf = json.loads(args.conf)

    mesh_devices = int(conf.get("mesh_devices") or 0)
    if mesh_devices > 1:
        # must land before the first jax device use; the flag only affects
        # the host (CPU) platform, so it is harmless on real accelerators
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={mesh_devices}"
            ).strip()

    # jax-touching imports stay below the XLA_FLAGS staging above
    from distributed_forecasting_tpu.engine.compile_cache import (
        cache_stats,
        enable_from_env,
    )
    from distributed_forecasting_tpu.monitoring.quality import (
        build_quality_runtime,
    )
    from distributed_forecasting_tpu.monitoring.trace import (
        TraceConfig,
        configure_tracing,
    )
    from distributed_forecasting_tpu.serving.batcher import BatchingConfig
    from distributed_forecasting_tpu.serving.server import (
        load_forecaster,
        start_server,
    )
    from distributed_forecasting_tpu.utils import get_logger

    logger = get_logger("fleet-replica")
    enable_from_env()  # DFTPU_COMPILE_CACHE: the store all replicas share

    tracing_conf = conf.get("tracing")
    trace_dir = os.environ.get("DFTPU_TRACE_DIR")
    if tracing_conf is None and trace_dir:
        # conf-less trace activation, same hook the bench uses: per-replica
        # JSONL streams + flight-recorder dumps land in one artifact dir
        tracing_conf = {
            "enabled": True,
            "jsonl_path": os.path.join(
                trace_dir, f"replica-{int(conf['port'])}.trace.jsonl"),
            "dump_dir": trace_dir,
        }
    configure_tracing(TraceConfig.from_conf(tracing_conf))

    forecaster = load_forecaster(conf["artifact_dir"])

    # -- series partition (serving/sharding.py) -----------------------------
    # The supervisor hands each replica its shard assignment at spawn; the
    # replica subsets params/keys/scales to those shards BEFORE warmup, so
    # resident memory and every forecast/update is ~S*owned/num_shards.
    shards = conf.get("shards")
    sharding_cfg = None
    shard_metrics = None
    owned_idx = None
    if conf.get("sharding") and shards is not None:
        from distributed_forecasting_tpu.serving.predictor import (
            BatchForecaster,
        )
        from distributed_forecasting_tpu.serving.sharding import (
            ShardingConfig,
            ShardMetrics,
            subset_for_shards,
        )

        cfg = ShardingConfig.from_conf(conf["sharding"])
        if isinstance(forecaster, BatchForecaster):
            forecaster, owned_idx = subset_for_shards(
                forecaster, shards, cfg.num_shards)
            sharding_cfg = cfg
            shard_metrics = ShardMetrics()
            shard_metrics.observe_assignment(
                forecaster.keys, shards, cfg.num_shards)
            logger.info(
                "serving shards %s of %d: %d resident series",
                sorted(int(s) for s in shards), cfg.num_shards,
                int(forecaster.keys.shape[0]))
        else:
            # composite artifacts (ensemble/bucketed) don't subset yet;
            # serve the full set rather than refuse to boot — the front
            # door's routing is still correct, just not memory-partitioned
            logger.warning(
                "%s cannot subset to a shard assignment; serving the "
                "full series set", type(forecaster).__name__)

    if mesh_devices > 1:
        enable_mesh = getattr(forecaster, "enable_mesh", None)
        if enable_mesh is None:
            # composite artifacts (ensemble/bucketed) don't shard yet;
            # serve them single-device rather than refuse to boot
            logger.warning(
                "%s has no mesh-parallel predict; serving single-device",
                type(forecaster).__name__)
        else:
            from distributed_forecasting_tpu.parallel import make_mesh

            enable_mesh(make_mesh(mesh_devices))
            logger.info("mesh-parallel predict over %d device(s)",
                        mesh_devices)

    batching = BatchingConfig.from_conf(conf.get("batching"))
    from distributed_forecasting_tpu.serving.dataplane import HttpConfig

    # the serving.http data-plane block (keep-alive, worker pool, idle
    # timeout) — parsed fail-fast here exactly like batching, so a typo'd
    # key kills the replica at boot instead of silently serving defaults
    http = HttpConfig.from_conf(conf.get("http"))
    mon_conf = conf.get("monitoring")
    quality = None
    if mon_conf:
        # every replica gets its OWN store subdirectory (segment cursors
        # are per-process state; two appenders in one directory would race
        # on rotation) — the fleet quality report reads across them
        mon_conf = dict(mon_conf)
        qs = dict(mon_conf.get("quality_store") or {})
        if qs.get("directory"):
            qs["directory"] = os.path.join(
                qs["directory"], f"replica-{int(conf['port'])}")
            mon_conf["quality_store"] = qs
        quality = build_quality_runtime(
            mon_conf,
            forecaster,
            default_store_dir=os.path.join(
                conf["artifact_dir"], "quality_store",
                f"replica-{int(conf['port'])}"),
        )
    ingest = None
    ingest_conf = conf.get("ingest")
    if ingest_conf:
        from distributed_forecasting_tpu.serving.ingest import (
            build_ingest_runtime,
        )

        ingest_conf = dict(ingest_conf)
        if ingest_conf.get("apply_mode") is None:
            # fleet default: every replica FOLLOWS the shared WAL on an
            # interval — sync mode would only freshen the replica that
            # happened to receive the POST
            ingest_conf["apply_mode"] = "interval"
        # training-history sidecar (tasks/serve.py writes it next to the
        # artifact): enables full refits; a sharded replica loads only its
        # shards' rows — the shard "state sidecar" half of hand-off
        history_y = history_mask = None
        for cand in (
            os.path.join(conf["artifact_dir"], "history.npz"),
            os.path.join(conf["artifact_dir"], "forecaster", "history.npz"),
        ):
            if os.path.exists(cand):
                import numpy as np

                blob = np.load(cand)
                history_y, history_mask = blob["y"], blob["mask"]
                if owned_idx is not None:
                    history_y = history_y[owned_idx]
                    history_mask = history_mask[owned_idx]
                break
        wal_factory = None
        if sharding_cfg is not None:
            from distributed_forecasting_tpu.serving.sharding import (
                ShardedWAL,
            )

            def wal_factory(wal_dir, max_segment_bytes):
                # per-shard namespaces under the SHARED wal_dir: this
                # replica appends anywhere (durability) but follows —
                # and therefore applies — only its owned shards
                return ShardedWAL(
                    wal_dir, shards, sharding_cfg.num_shards,
                    max_segment_bytes=max_segment_bytes,
                    on_read=shard_metrics.note_wal_read)

        ingest = build_ingest_runtime(
            ingest_conf,
            forecaster,
            history_y=history_y,
            history_mask=history_mask,
            quality=quality,
            default_wal_dir=os.path.join(conf["artifact_dir"], "ingest_wal"),
            wal_factory=wal_factory,
        )
        if ingest is not None:
            logger.info("streaming ingest: shared WAL at %s (%s mode)",
                        ingest.wal.directory, ingest.config.apply_mode)
    anomaly = None
    if conf.get("anomaly"):
        from distributed_forecasting_tpu.serving.anomaly import (
            build_anomaly_runtime,
        )

        # per-replica stream directory for the same reason as the quality
        # store: segment cursors are per-process state
        anomaly = build_anomaly_runtime(
            conf["anomaly"],
            forecaster,
            default_store_dir=os.path.join(
                conf["artifact_dir"], "anomaly_stream",
                f"replica-{int(conf['port'])}"),
        )
        if anomaly is not None:
            logger.info("anomaly scoring on: threshold=%.3f",
                        anomaly.threshold)
    cache = None
    if conf.get("cache"):
        from distributed_forecasting_tpu.serving.forecast_cache import (
            build_forecast_cache,
        )

        # per-replica mmap directory for the same reason as the quality
        # store: a sharded replica's frames cover only its owned series,
        # and two replicas must never adopt each other's persisted payloads
        cache = build_forecast_cache(
            conf["cache"],
            forecaster,
            default_mmap_dir=os.path.join(
                conf["artifact_dir"], "forecast_cache",
                f"replica-{int(conf['port'])}"),
        )
        if cache is not None:
            logger.info("forecast cache on: %d persisted frame(s) adopted",
                        int(cache.metrics.loads.value))
    srv = start_server(
        forecaster,
        host=conf.get("host", "127.0.0.1"),
        port=int(conf["port"]),
        model_version=conf.get("model_version"),
        batching=batching,
        ready=False,  # warm first; the supervisor routes on /readyz
        quality=quality,
        ingest=ingest,
        anomaly=anomaly,
        extra_metrics=shard_metrics,
        cache=cache,
        http=http,
    )
    sizes = conf.get("warmup_sizes")
    if sizes:
        n = forecaster.warmup(
            horizon=int(conf.get("warmup_horizon", 90)),
            sizes=[int(s) for s in sizes],
        )
        stats = cache_stats()
        logger.info(
            "warmed %d bucket(s) (%d AOT store hit(s), %d miss(es))",
            n, stats["hits"], stats["misses"])
    if ingest is not None:
        # hand-off gate: replay the WAL backlog (a sharded replica: its
        # shards' logs) BEFORE /readyz flips, so a restarted owner never
        # serves forecasts that predate writes the fleet already accepted
        replay = ingest.poll_apply()
        if replay.get("accepted"):
            logger.info("replayed WAL backlog before ready: %s", replay)
    srv.mark_ready()
    logger.info("replica ready on %s:%d", conf.get("host", "127.0.0.1"),
                int(conf["port"]))

    stop = threading.Event()

    def _drain(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    stop.wait()
    logger.info("draining replica on port %d", int(conf["port"]))
    srv.shutdown()  # /readyz -> 503, batcher drains, accept loop stops
    srv.server_close()


if __name__ == "__main__":
    main()
