"""Fleet replica entrypoint: one serving process under the supervisor.

Launched by ``serving/fleet.py``'s default spawner as

    python -m distributed_forecasting_tpu.serving.replica --conf '<json>'

The conf object carries: ``artifact_dir`` (the saved forecaster to load),
``host``/``port`` (the supervisor-assigned, restart-stable address),
``warmup_sizes``/``warmup_horizon``, optional ``batching``/``tracing``
blocks (same shapes as the ``serving:`` conf), ``model_version``,
``mesh_devices`` (>1 shards every predict's series axis over a device mesh
— ``BatchForecaster.enable_mesh``), an optional ``monitoring`` block
(quality/store/SLO — ``monitoring/quality.py``; the replica suffixes the
store directory with its port so replicas never share an append cursor),
and an optional ``ingest`` block (``serving/ingest.py``).  Unlike the
quality store, the ingest WAL directory is deliberately SHARED across the
fleet: each replica appends O_APPEND whole lines and follows the log with
its own cursor in ``interval`` apply mode, so a point posted through any
replica converges into every replica's model state — the front door can
round-robin /ingest like any other POST.

Boot order is the contract the supervisor routes on: bind the port with
``/readyz`` at 503 first, warm the bucket ladder, THEN flip ready — a
replica never receives traffic while it is still compiling.  The shared
AOT store (``DFTPU_COMPILE_CACHE`` in the spawn env) makes every warmup
after the fleet's first a deserialize, not a compile.  SIGTERM drains
gracefully: /readyz flips to 503, queued requests finish, then the socket
closes.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--conf", required=True,
                    help="JSON replica config (see module docstring)")
    args = ap.parse_args(argv)
    conf = json.loads(args.conf)

    mesh_devices = int(conf.get("mesh_devices") or 0)
    if mesh_devices > 1:
        # must land before the first jax device use; the flag only affects
        # the host (CPU) platform, so it is harmless on real accelerators
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={mesh_devices}"
            ).strip()

    # jax-touching imports stay below the XLA_FLAGS staging above
    from distributed_forecasting_tpu.engine.compile_cache import (
        cache_stats,
        enable_from_env,
    )
    from distributed_forecasting_tpu.monitoring.quality import (
        build_quality_runtime,
    )
    from distributed_forecasting_tpu.monitoring.trace import (
        TraceConfig,
        configure_tracing,
    )
    from distributed_forecasting_tpu.serving.batcher import BatchingConfig
    from distributed_forecasting_tpu.serving.server import (
        load_forecaster,
        start_server,
    )
    from distributed_forecasting_tpu.utils import get_logger

    logger = get_logger("fleet-replica")
    enable_from_env()  # DFTPU_COMPILE_CACHE: the store all replicas share

    tracing_conf = conf.get("tracing")
    trace_dir = os.environ.get("DFTPU_TRACE_DIR")
    if tracing_conf is None and trace_dir:
        # conf-less trace activation, same hook the bench uses: per-replica
        # JSONL streams + flight-recorder dumps land in one artifact dir
        tracing_conf = {
            "enabled": True,
            "jsonl_path": os.path.join(
                trace_dir, f"replica-{int(conf['port'])}.trace.jsonl"),
            "dump_dir": trace_dir,
        }
    configure_tracing(TraceConfig.from_conf(tracing_conf))

    forecaster = load_forecaster(conf["artifact_dir"])
    if mesh_devices > 1:
        enable_mesh = getattr(forecaster, "enable_mesh", None)
        if enable_mesh is None:
            # composite artifacts (ensemble/bucketed) don't shard yet;
            # serve them single-device rather than refuse to boot
            logger.warning(
                "%s has no mesh-parallel predict; serving single-device",
                type(forecaster).__name__)
        else:
            from distributed_forecasting_tpu.parallel import make_mesh

            enable_mesh(make_mesh(mesh_devices))
            logger.info("mesh-parallel predict over %d device(s)",
                        mesh_devices)

    batching = BatchingConfig.from_conf(conf.get("batching"))
    mon_conf = conf.get("monitoring")
    quality = None
    if mon_conf:
        # every replica gets its OWN store subdirectory (segment cursors
        # are per-process state; two appenders in one directory would race
        # on rotation) — the fleet quality report reads across them
        mon_conf = dict(mon_conf)
        qs = dict(mon_conf.get("quality_store") or {})
        if qs.get("directory"):
            qs["directory"] = os.path.join(
                qs["directory"], f"replica-{int(conf['port'])}")
            mon_conf["quality_store"] = qs
        quality = build_quality_runtime(
            mon_conf,
            forecaster,
            default_store_dir=os.path.join(
                conf["artifact_dir"], "quality_store",
                f"replica-{int(conf['port'])}"),
        )
    ingest = None
    ingest_conf = conf.get("ingest")
    if ingest_conf:
        from distributed_forecasting_tpu.serving.ingest import (
            build_ingest_runtime,
        )

        ingest_conf = dict(ingest_conf)
        if ingest_conf.get("apply_mode") is None:
            # fleet default: every replica FOLLOWS the shared WAL on an
            # interval — sync mode would only freshen the replica that
            # happened to receive the POST
            ingest_conf["apply_mode"] = "interval"
        ingest = build_ingest_runtime(
            ingest_conf,
            forecaster,
            quality=quality,
            default_wal_dir=os.path.join(conf["artifact_dir"], "ingest_wal"),
        )
        if ingest is not None:
            logger.info("streaming ingest: shared WAL at %s (%s mode)",
                        ingest.wal.directory, ingest.config.apply_mode)
    srv = start_server(
        forecaster,
        host=conf.get("host", "127.0.0.1"),
        port=int(conf["port"]),
        model_version=conf.get("model_version"),
        batching=batching,
        ready=False,  # warm first; the supervisor routes on /readyz
        quality=quality,
        ingest=ingest,
    )
    sizes = conf.get("warmup_sizes")
    if sizes:
        n = forecaster.warmup(
            horizon=int(conf.get("warmup_horizon", 90)),
            sizes=[int(s) for s in sizes],
        )
        stats = cache_stats()
        logger.info(
            "warmed %d bucket(s) (%d AOT store hit(s), %d miss(es))",
            n, stats["hits"], stats["misses"])
    srv.mark_ready()
    logger.info("replica ready on %s:%d", conf.get("host", "127.0.0.1"),
                int(conf["port"]))

    stop = threading.Event()

    def _drain(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    stop.wait()
    logger.info("draining replica on port %d", int(conf["port"]))
    srv.shutdown()  # /readyz -> 503, batcher drains, accept loop stops
    srv.server_close()


if __name__ == "__main__":
    main()
