"""Graceful degradation for the fleet: deadlines, breakers, hedging.

The front door (serving/fleet.py) already survives a DEAD replica —
connection failures retry on the next ready port.  What it could not
survive before this module is a replica that is merely *wrong-speed*:

* a hung socket pinned a front-door worker for the full
  ``proxy_timeout_s`` (2 minutes by default) per attempt;
* a consistently SLOW replica stayed in the rotation — every Nth request
  ate its latency, because only connection failures flip ``ready``;
* a client with its own SLA had no way to say "this answer is worthless
  after 800 ms", so exhausted requests still burned device time.

Three mechanisms, all conf-gated under the strict ``serving.resilience``
block and all off by default:

* **Deadline budgets** — a request carries ``X-Deadline-Ms`` (or the
  conf's ``default_deadline_ms`` applies).  The front door converts it to
  a monotonic deadline once, derives every forwarded leg's socket timeout
  from the REMAINING budget, forwards the remainder downstream, and
  answers 503 the moment the budget is gone instead of queueing doomed
  work.  Replicas shed exhausted requests before dispatch the same way
  (serving/server.py).
* **Per-replica circuit breakers** — consecutive connection failures or
  slow calls open the breaker (``breaker_failures``); an open breaker
  ejects the replica from routing exactly like ``ready=False`` does, and
  after ``breaker_open_s`` a HALF_OPEN probe admits ONE request whose
  outcome closes or re-opens it.  State is exported per port as
  ``dftpu_fleet_breaker_state`` (0 closed / 1 open / 2 half-open).
* **Hedged scatter legs** — on multi-shard scatter, a leg that has not
  answered within the hedge delay (``hedge_delay_ms``, or the observed
  p95 of recent legs when 0) fires a duplicate to the next owner;
  first response wins, the loser is counted, never awaited.

The failpoint activation keys (``failpoints`` / ``failpoint_seed``) ride
in this block too, so one conf stanza describes a chaos run end to end
(``monitoring/failpoints.py`` holds the registry itself).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

# breaker states, also the dftpu_fleet_breaker_state gauge encoding
CLOSED = 0
OPEN = 1
HALF_OPEN = 2

_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """The ``serving.resilience`` conf block (conf/tasks/serve_config.yml).

    Every mechanism is opt-in: the all-defaults instance is behaviorally
    identical to the pre-resilience fleet (no deadlines, no breakers, no
    hedging, no failpoints) except for the per-leg forward timeout, which
    is always derived from ``request_timeout_s`` once the caller passes
    one.
    """

    failpoints: str = ""          # monitoring/failpoints activation spec
    failpoint_seed: int = 0
    default_deadline_ms: float = 0.0   # budget when no X-Deadline-Ms
    #                                    header arrives; 0 = unbounded
    min_leg_timeout_ms: float = 50.0   # floor under budget-derived leg
    #                                    timeouts (a 3ms socket timeout
    #                                    only manufactures failures)
    breaker_failures: int = 0     # consecutive failures/slow calls that
    #                               open a replica's breaker; 0 disables
    breaker_slow_s: float = 0.0   # a successful call slower than this
    #                               counts as a failure; 0 disables
    breaker_open_s: float = 5.0   # open -> half-open probe delay
    hedge_enabled: bool = False   # duplicate slow scatter legs
    hedge_delay_ms: float = 0.0   # fixed hedge delay; 0 = observed p95
    hedge_min_delay_ms: float = 10.0   # floor under the p95-derived delay

    def __post_init__(self):
        if self.default_deadline_ms < 0:
            raise ValueError("default_deadline_ms must be >= 0")
        if self.min_leg_timeout_ms <= 0:
            raise ValueError("min_leg_timeout_ms must be > 0")
        if self.breaker_failures < 0:
            raise ValueError("breaker_failures must be >= 0")
        if self.breaker_slow_s < 0:
            raise ValueError("breaker_slow_s must be >= 0")
        if self.breaker_open_s <= 0:
            raise ValueError("breaker_open_s must be > 0")
        if self.hedge_delay_ms < 0:
            raise ValueError("hedge_delay_ms must be >= 0")
        if self.hedge_min_delay_ms <= 0:
            raise ValueError("hedge_min_delay_ms must be > 0")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "ResilienceConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like breaker_failues must not silently disable the
            # breaker a chaos drill is about to depend on
            raise ValueError(
                f"unknown serving.resilience conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


def state_name(state: int) -> str:
    return _STATE_NAMES.get(int(state), "unknown")


class CircuitBreaker:
    """One replica's breaker: CLOSED -> OPEN -> HALF_OPEN -> CLOSED.

    ``allow()`` is the routing gate: True admits the call.  In OPEN it
    flips to HALF_OPEN once ``open_s`` has elapsed and admits exactly ONE
    probe (concurrent callers are refused until the probe reports).  The
    caller MUST report every admitted call via ``record_success`` /
    ``record_failure`` or a half-open breaker wedges refusing traffic.

    ``time_fn`` is injectable so the state machine unit-tests in
    simulated time instead of sleeping through ``open_s``.
    """

    def __init__(self, failures: int, open_s: float,
                 slow_s: float = 0.0,
                 time_fn: Callable[[], float] = time.monotonic):
        if failures < 1:
            raise ValueError("failures must be >= 1")
        self.failures = int(failures)
        self.open_s = float(open_s)
        self.slow_s = float(slow_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._time() - self._opened_at < self.open_s:
                    return False
                self._state = HALF_OPEN
                self._probing = True
                return True
            # HALF_OPEN: one probe in flight at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self, elapsed_s: float = 0.0) -> None:
        if self.slow_s > 0 and elapsed_s >= self.slow_s:
            # answered, but too slowly to count as healthy: a brownout
            # replica must trip the breaker as surely as a dead one
            self.record_failure()
            return
        with self._lock:
            self._consecutive = 0
            self._probing = False
            self._state = CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN, timer restarted
                self._state = OPEN
                self._opened_at = self._time()
                return
            self._consecutive += 1
            if self._consecutive >= self.failures:
                self._state = OPEN
                self._opened_at = self._time()


class LatencyReservoir:
    """Last-N leg latencies -> the p95 the hedge delay derives from.

    A fixed ring, not a histogram: the hedge wants the RECENT p95 (the
    fleet's speed now), and 256 samples of float append are cheap enough
    to sit on the forward path.
    """

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._buf: List[float] = []
        self._cap = int(capacity)
        self._next = 0

    def observe(self, seconds: float) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(float(seconds))
            else:
                self._buf[self._next] = float(seconds)
                self._next = (self._next + 1) % self._cap

    def p95(self) -> Optional[float]:
        with self._lock:
            if not self._buf:
                return None
            ordered = sorted(self._buf)
        return ordered[min(int(len(ordered) * 0.95), len(ordered) - 1)]


# -- deadline budgets ---------------------------------------------------------

def parse_deadline_header(raw: Optional[str]) -> Optional[float]:
    """``X-Deadline-Ms`` value -> remaining milliseconds, or None when the
    header is absent/garbage (garbage is treated as absent, not as an
    error: a hostile header must not 500 the front door)."""
    if raw is None:
        return None
    try:
        return float(raw.strip())
    except ValueError:
        return None


def deadline_from_headers(headers, default_ms: float = 0.0,
                          ) -> Optional[float]:
    """Monotonic deadline for a request, or None when unbounded.

    The header wins over the conf default — a client saying 500 ms means
    it.  A header that is already <= 0 yields a deadline in the past, so
    the shed check downstream fires without a special case.
    """
    budget_ms = parse_deadline_header(headers.get("X-Deadline-Ms"))
    if budget_ms is None:
        if default_ms <= 0:
            return None
        budget_ms = default_ms
    return time.monotonic() + budget_ms / 1000.0


def remaining_ms(deadline: Optional[float]) -> Optional[float]:
    if deadline is None:
        return None
    return (deadline - time.monotonic()) * 1000.0
