"""On-device anomaly detection: ``POST /detect_anomalies`` + the
streaming ``/ingest`` scoring leg.

ARIMA_PLUS ships anomaly detection as a first-class verb next to
forecasting (``ML.DETECT_ANOMALIES`` over a trained model): actuals are
scored against the model's own predictive band, and a point is anomalous
when its residual exceeds the band's spread at a configurable severity.
This module is that verb for the served JAX artifact, in two legs that
share one scorer:

* **Request leg** — ``POST /detect_anomalies`` with ``{"points":
  [{<keys>, "ds": ..., "y": ...}, ...]}``: the batch aligns against ONE
  batched predict (routed through the server's :class:`RequestBatcher`
  when micro-batching is on — the same ``execute`` path /invocations
  uses, so concurrent detection requests coalesce into shared device
  dispatches) and every point comes back with ``anomaly_score`` +
  ``is_anomaly``.  The sharded front door routes the batch per shard and
  regroups results in request order (``serving/sharding.py``).
* **Streaming leg** — with ``stream_scoring`` on, every validated
  ``/ingest`` batch is scored against the CURRENT bands before the state
  update applies (a point must not vouch for itself), emitting
  ``dftpu_anomaly_*`` counters and appending flagged points to a JSONL
  anomaly stream on the quality-store machinery
  (:class:`monitoring.store.TimeSeriesStore`).  A scoring failure never
  fails the ingest — the WAL append already happened.

Scoring contract (same sigma recovery as ``monitoring/monitor.py``'s
batch ``detect_anomalies``): ``sigma = (yhat_upper - yhat) / z_w`` from
the UPPER half-band only (lower bounds may be clamped — croston floors
at 0, multiplicative bands are asymmetric), ``score = |y - yhat| /
sigma``, flagged when ``score > threshold``.  The default threshold is
the band's own z (points outside the band flag, for symmetric bands),
so the endpoint agrees with what ``/invocations`` clients see as the
interval.  Bands are the CALIBRATED ones — ``BatchForecaster.predict``
applies the conformal ``interval_scale`` (``engine/calibrate.py``) — so
detection severity tracks the shipped coverage, not the raw model band.

Conf block ``serving.anomaly`` (strict)::

    serving:
      anomaly:
        enabled: true
        threshold: 0.0            # robust-z severity; 0 -> the band's z
        max_horizon: 365          # bounds the predict grid a request forces
        max_points_per_request: 10000
        stream_scoring: true      # score /ingest batches too
        stream_store_dir: ""      # "" -> <env.root>/anomaly_stream
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import period_ordinals
from distributed_forecasting_tpu.engine.calibrate import config_interval_width
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.utils import get_logger

_EPS = 1e-9


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """The ``serving.anomaly`` conf block."""

    enabled: bool = False
    threshold: float = 0.0          # 0 -> z of the served interval width
    max_horizon: int = 365
    max_points_per_request: int = 10000
    stream_scoring: bool = True
    stream_store_dir: str = ""      # "" -> caller supplies a default root

    def __post_init__(self):
        if self.threshold < 0:
            raise ValueError("threshold must be >= 0 (0 means the band z)")
        if self.max_horizon < 1:
            raise ValueError("max_horizon must be >= 1")
        if self.max_points_per_request < 1:
            raise ValueError("max_points_per_request must be >= 1")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "AnomalyConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like treshold must not silently keep the default
            raise ValueError(
                f"unknown serving.anomaly conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


class AnomalyScorer:
    """Batched residual scoring of actuals against the served bands.

    One ``score()`` call runs ONE batched predict for the whole point set
    (through the server's coalescing ``execute`` once bound — see
    :meth:`bind_execute`) plus host-side alignment; no per-series loop.
    Thread-safe: all state is read-only after construction except the
    metrics registry (internally synchronized) and the stream store
    (internally synchronized).
    """

    def __init__(self, forecaster, config: Optional[AnomalyConfig] = None,
                 store=None):
        self.forecaster = forecaster
        self.config = config or AnomalyConfig(enabled=True)
        self.store = store              # JSONL anomaly stream (optional)
        self.logger = get_logger("AnomalyScorer")
        self._execute = None            # bound by ForecastServer
        width = config_interval_width(getattr(forecaster, "config", None))
        # z of the served band width — the sigma divisor AND the default
        # severity (same inverse-normal the model modules use; jax is a
        # hard dependency, scipy is not)
        from jax.scipy.special import ndtri

        self._z_w = float(ndtri(0.5 + width / 2.0))
        self.threshold = float(self.config.threshold) or self._z_w

        r = MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "dftpu_anomaly_requests_total",
            "POST /detect_anomalies calls")
        self.points_total = r.counter(
            "dftpu_anomaly_points_total",
            "actuals scored against served bands (request leg)")
        self.flagged_total = r.counter(
            "dftpu_anomaly_flagged_total",
            "points flagged anomalous (request leg)")
        self.skipped_total = r.counter(
            "dftpu_anomaly_skipped_total",
            "points not scored: unknown series, unmatched dates, or "
            "beyond max_horizon")
        self.stream_points = r.counter(
            "dftpu_anomaly_stream_points_total",
            "ingest points scored by the streaming leg")
        self.stream_flagged = r.counter(
            "dftpu_anomaly_stream_flagged_total",
            "ingest points flagged anomalous by the streaming leg")
        self.last_flagged = r.gauge(
            "dftpu_anomaly_last_batch_flagged",
            "flagged count of the most recent scored batch (either leg)")
        self.threshold_gauge = r.gauge(
            "dftpu_anomaly_threshold",
            "the robust-z severity a point must exceed to flag")
        self.threshold_gauge.set(self.threshold)

    # -- wiring ---------------------------------------------------------------
    def bind_execute(self, execute) -> None:
        """Late-bind the server's coalescing dispatch (the /invocations
        ``execute`` signature) so detection batches ride the same
        RequestBatcher as forecast traffic — called by ``ForecastServer``
        at construction."""
        self._execute = execute

    def _predict(self, req: pd.DataFrame, horizon: int, on_missing: str):
        if self._execute is not None:
            return self._execute(
                req, horizon=horizon, include_history=True,
                quantiles=None, on_missing=on_missing, xreg=None)
        return self.forecaster.predict(
            req, horizon=horizon, include_history=True,
            on_missing=on_missing)

    # -- scoring --------------------------------------------------------------
    def score(self, points: pd.DataFrame, on_missing: str = "skip",
              threshold: Optional[float] = None,
              source: str = "endpoint") -> Dict:
        """Score a batch of actuals; returns per-point results in request
        order plus summary counts.

        ``points``: long frame with the forecaster's key columns, ``ds``
        (date-like) or ``_ord`` (period ordinal), and ``y``.
        ``threshold`` overrides the configured severity for this request.
        """
        fc = self.forecaster
        self.requests.inc()
        sev = float(threshold) if threshold else self.threshold
        key_names = list(fc.key_names)
        need = key_names + ["y"]
        missing = [c for c in need if c not in points.columns]
        if missing:
            raise ValueError(f"points missing column(s) {missing}")
        if "ds" not in points.columns and "_ord" not in points.columns:
            raise ValueError("points need a 'ds' (date) column")
        obs = points[[c for c in (*need, "ds", "_ord")
                      if c in points.columns]].copy()
        obs["y"] = pd.to_numeric(obs["y"], errors="coerce")
        n_in = len(obs)
        freq = getattr(fc, "freq", "D")
        if "_ord" not in obs.columns:
            obs["ds"] = pd.to_datetime(obs["ds"])
            obs["_ord"] = period_ordinals(obs["ds"], freq)
        obs["_row"] = np.arange(n_in)  # request order survives the merge
        obs = obs[np.isfinite(obs["y"].to_numpy(float))]

        day1 = getattr(fc, "day1", None)
        if day1 is not None:
            horizon = int(np.clip(obs["_ord"].max() - day1, 1,
                                  self.config.max_horizon)) if len(obs) else 1
            obs = obs[obs["_ord"] <= day1 + self.config.max_horizon]
        else:  # composite artifacts: serve whatever predict covers
            horizon = self.config.max_horizon
        if obs.empty:
            self.skipped_total.inc(n_in)
            return {"results": [], "n_scored": 0, "n_flagged": 0,
                    "n_skipped": n_in, "threshold": sev}

        with get_tracer().span("anomaly.score", rows=n_in, source=source):
            req = obs[key_names].drop_duplicates()
            pred = self._predict(req, horizon, on_missing)
            pred = pred[key_names + ["ds", "yhat", "yhat_lower",
                                     "yhat_upper"]]
            merged = obs.merge(
                pred.assign(_ord=period_ordinals(pred["ds"], freq))
                    .drop(columns=["ds"]),
                on=key_names + ["_ord"], how="inner")
        merged = merged.sort_values("_row", kind="stable")
        y = merged["y"].to_numpy(float)
        yhat = merged["yhat"].to_numpy(float)
        hi = merged["yhat_upper"].to_numpy(float)
        # sigma from the UPPER half-band only (module docstring; the same
        # rationale as monitoring/monitor.detect_anomalies)
        sigma = np.maximum((hi - yhat) / self._z_w, _EPS)
        score = np.abs(y - yhat) / sigma
        flagged = score > sev

        results: List[Dict] = []
        epoch = pd.Timestamp("1970-01-01")
        for i, (_, row) in enumerate(merged.iterrows()):
            ds = row.get("ds")
            if ds is None or ds != ds:
                ds = epoch + pd.Timedelta(days=int(row["_ord"]))
            results.append({
                **{k: int(row[k]) for k in key_names},
                "ds": str(pd.Timestamp(ds).date()),
                "y": float(y[i]),
                "yhat": float(yhat[i]),
                "yhat_lower": float(row["yhat_lower"]),
                "yhat_upper": float(row["yhat_upper"]),
                "anomaly_score": round(float(score[i]), 6),
                "is_anomaly": bool(flagged[i]),
            })
        n_scored = len(results)
        n_flagged = int(flagged.sum())
        if source == "ingest":
            self.stream_points.inc(n_scored)
            self.stream_flagged.inc(n_flagged)
        else:
            self.points_total.inc(n_scored)
            self.flagged_total.inc(n_flagged)
        self.skipped_total.inc(n_in - n_scored)
        self.last_flagged.set(n_flagged)
        if n_flagged:
            self._stream_flagged(
                [r for r in results if r["is_anomaly"]], source)
        return {"results": results, "n_scored": n_scored,
                "n_flagged": n_flagged, "n_skipped": n_in - n_scored,
                "threshold": sev}

    def score_ingest(self, rows: List[Dict]) -> Dict:
        """Streaming leg: score validated ``/ingest`` WAL rows (compact
        ``{"k": [...], "d": n, "y": v}`` form) against the CURRENT bands.
        Returns the summary WITHOUT per-point results (an ingest ack must
        stay small); flagged points land on the anomaly stream."""
        key_names = list(self.forecaster.key_names)
        frame = pd.DataFrame(
            [dict(zip(key_names, r["k"]), _ord=r["d"], y=r["y"])
             for r in rows])
        out = self.score(frame, on_missing="skip", source="ingest")
        return {"scored": out["n_scored"], "flagged": out["n_flagged"],
                "skipped": out["n_skipped"], "threshold": out["threshold"]}

    def _stream_flagged(self, flagged: List[Dict], source: str) -> None:
        """Flagged points -> the JSONL anomaly stream (quality-store
        segments: atomic O_APPEND lines, retention, torn-line-tolerant
        readers).  A stream failure must not fail scoring."""
        if self.store is None:
            return
        at = time.time()  # dflint: disable=nondeterminism — stream rows are wall-clock telemetry
        key_names = list(self.forecaster.key_names)
        points = [{
            "ts": at, "name": "dftpu_anomaly_point",
            "labels": {**{k: str(r[k]) for k in key_names},
                       "ds": r["ds"], "source": source},
            "value": r["anomaly_score"],
        } for r in flagged]
        try:
            self.store.append(points)  # dflint: disable=unlocked-shared-state — TimeSeriesStore is internally synchronized
        except OSError:
            self.logger.exception("anomaly stream append failed")

    # -- exposition -----------------------------------------------------------
    def render_metrics(self) -> str:
        return self.registry.render_prometheus()

    def snapshot(self) -> Dict:
        out: Dict = {"threshold": self.threshold,
                     "band_z": self._z_w,
                     "stream_scoring": self.config.stream_scoring}
        if self.store is not None:
            out["stream_store"] = self.store.stats()
        return out


def build_anomaly_runtime(conf: Optional[dict], forecaster,
                          default_store_dir: Optional[str] = None,
                          ) -> Optional[AnomalyScorer]:
    """``serving.anomaly`` conf block -> a wired scorer (or None when the
    block is absent/disabled).  ``default_store_dir`` backs an empty
    ``stream_store_dir``; replicas pass a port-suffixed path so two
    processes never share an append cursor."""
    config = AnomalyConfig.from_conf(conf)
    if not config.enabled:
        return None
    store = None
    directory = config.stream_store_dir or default_store_dir
    if directory:
        from distributed_forecasting_tpu.monitoring.store import (
            TimeSeriesStore,
        )

        store = TimeSeriesStore(directory)
    return AnomalyScorer(forecaster, config=config, store=store)
