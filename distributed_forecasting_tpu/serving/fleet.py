"""Serving fleet: N replica processes behind one front door.

The reference scales serving by fanning its PyFunc model out across a Spark
cluster — each executor re-resolves and re-loads per-series models
(``notebooks/prophet/04_inference.py:4-16``).  Here the scale-out unit is a
whole server process running the batched artifact (``serving/server.py``):

  * :class:`FleetSupervisor` spawns N replicas (``serving/replica.py``
    subprocesses by default; tests inject in-process fakes), polls their
    ``/readyz``, restarts crashed ones with capped exponential backoff, and
    terminates the fleet gracefully on drain;
  * :class:`FrontDoorServer` is the single client-facing HTTP endpoint: it
    round-robins ``POST /invocations`` (and pass-through GETs) across READY
    replicas, retries connection-level failures on the next replica
    (predict is idempotent, so a replica dying mid-request is retriable,
    not an error the client sees), and serves ``GET /metrics`` as the SUM
    of every replica's exposition plus the fleet's own gauges/counters.

Replicas share one on-disk AOT executable store (``engine/compile_cache``,
multi-process-safe writes), so the fleet's Nth cold boot deserializes the
bucket ladder the 1st one compiled — the ARIMA_PLUS-style "many workers
over shared fingerprinted state" posture (PAPERS.md, arXiv:2510.24452).

Lock discipline (dflint's blocking-under-lock + unlocked-shared-state rules
gate this file): the supervisor takes its lock only to snapshot or update
replica state; every blocking action — health probes, process spawn/wait,
sleeps — happens OUTSIDE the critical section on the snapshot.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Callable, List, Optional

from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.serving.dataplane import (
    ConnectionPool,
    HttpConfig,
    KeepAliveHandlerMixin,
    PooledHTTPServer,
    pooled_get,
)
from distributed_forecasting_tpu.serving.resilience import (
    OPEN,
    CircuitBreaker,
    LatencyReservoir,
    ResilienceConfig,
    deadline_from_headers,
    remaining_ms,
)
from distributed_forecasting_tpu.serving.sharding import (
    ShardingConfig,
    TokenBucket,
    compute_assignments,
    merge_detect_responses,
    merge_ingest_responses,
    merge_invocation_responses,
    plan_request,
)
from distributed_forecasting_tpu.utils import get_logger


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """The ``serving.fleet`` conf block (see conf/tasks/serve_config.yml)."""

    enabled: bool = False
    replicas: int = 2
    replica_host: str = "127.0.0.1"   # replicas are local children
    base_port: int = 0                # 0: pick free ports; else base_port+i
    health_poll_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    ready_timeout_s: float = 300.0    # cold warmup can compile for minutes
    restart_backoff_s: float = 0.5    # first restart delay after a crash
    restart_backoff_max_s: float = 30.0
    drain_timeout_s: float = 10.0     # SIGTERM -> SIGKILL grace per drain
    proxy_timeout_s: float = 120.0    # per-attempt forward timeout
    retry_window_s: float = 10.0      # front-door budget to find a replica
    mesh_devices: int = 0             # >1: each replica shards predict over
                                      # a device mesh of this size (layer 1)

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.restart_backoff_s <= 0:
            raise ValueError("restart_backoff_s must be > 0")
        if self.restart_backoff_max_s < self.restart_backoff_s:
            raise ValueError(
                "restart_backoff_max_s must be >= restart_backoff_s")
        if self.health_poll_interval_s <= 0:
            raise ValueError("health_poll_interval_s must be > 0")
        if self.mesh_devices < 0:
            raise ValueError(
                f"mesh_devices must be >= 0, got {self.mesh_devices}")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "FleetConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like restart_backof_s must not silently lose its value
            raise ValueError(
                f"unknown serving.fleet conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        # YAML already types the values; the cast normalizes "8080" -> 8080
        # in hand-built dicts and keeps every field its declared scalar type
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf
        }
        return cls(**kwargs)


def _free_port(host: str) -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _probe_ready(host: str, port: int, timeout: float,
                 pool: Optional[ConnectionPool] = None) -> bool:
    """One /readyz probe.  With a pool the probe rides (and health-checks)
    the same keep-alive sockets the forward path reuses; without one it
    dials fresh (boot-time callers that predate the supervisor's pool)."""
    if pool is not None:
        try:
            status, _ = pooled_get(pool, host, port, "/readyz", timeout)
            return status == 200
        except (OSError, http.client.HTTPException):
            return False
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", "/readyz")
        return conn.getresponse().status == 200
    except (OSError, http.client.HTTPException):
        return False
    finally:
        conn.close()


def _fetch(host: str, port: int, path: str, timeout: float,
           pool: Optional[ConnectionPool] = None) -> Optional[bytes]:
    if pool is not None:
        try:
            status, body = pooled_get(pool, host, port, path, timeout)
            return body if status == 200 else None
        except (OSError, http.client.HTTPException):
            return None
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        if resp.status != 200:
            return None
        return resp.read()
    except (OSError, http.client.HTTPException):
        return None
    finally:
        conn.close()


# -- Prometheus aggregation --------------------------------------------------

def _fmt_value(v: float) -> str:
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _split_label_pairs(body: str) -> List[str]:
    """Split the inside of a ``{...}`` label block on commas OUTSIDE quoted
    values (label values may contain escaped commas/quotes)."""
    parts: List[str] = []
    buf: List[str] = []
    in_quotes = False
    escaped = False
    for ch in body:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
            buf.append(ch)
            continue
        if ch == "," and not in_quotes:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts


def _le_value(raw: str) -> float:
    return float("inf") if raw == "+Inf" else float(raw)


#: gauges describing SHARED fleet state (the one ingest WAL on disk, the
#: converged applied frontier) — max-merged, not summed, across replicas
_GAUGE_MAX_MERGE = frozenset({
    "dftpu_ingest_wal_bytes",
    "dftpu_ingest_wal_segments",
    "dftpu_ingest_applied_day",
    # a FRACTION (pad rows / dispatched rows): summing is meaningless,
    # the worst replica is the capacity-waste signal — the underlying
    # dftpu_cost_padding_rows_total counters still SUM
    "dftpu_cost_padding_waste",
    # per-shard resident-series gauges: with replication > 1 every owner
    # of a shard reports the SAME resident count for that shard's label,
    # so summing would multiply series by the replication factor
    "dftpu_shard_series",
    # forecast-cache staleness headline: the fleet's oldest materialized
    # frame anywhere — summing per-replica ages would fabricate an age no
    # frame has (the hit/miss/invalidation counters still SUM)
    "dftpu_cache_entry_age_seconds",
    # ratios / thresholds / enum states: the fleet-level signal is the
    # worst (largest) replica's value, never the arithmetic sum
    "dftpu_anomaly_threshold",
    "dftpu_data_quality_gap_ratio",
    "dftpu_fleet_breaker_state",
    "dftpu_ingest_pending_days",
    "dftpu_quality_metric",
    "dftpu_quality_nominal_coverage",
})

#: gauges that are genuinely ADDITIVE across replicas (per-replica counts
#: and resource totals) — listed explicitly so the metrics-merge-drift lint
#: can prove every ``dftpu_*`` gauge has a deliberate fleet-merge policy
_GAUGE_SUM_MERGE = frozenset({
    "dftpu_anomaly_last_batch_flagged",
    "dftpu_cache_bytes",
    "dftpu_cache_entries",
    # per-replica busy worker counts are additive: the fleet-level signal
    # is total in-flight handler occupancy across the worker pools
    "dftpu_http_workers_busy",
    # a fraction per replica, but summing is the HISTORICAL contract the
    # cost tests pin (callers divide by replica count downstream)
    "dftpu_cost_device_saturation",
    "dftpu_data_quality_rows",
    "dftpu_data_quality_series",
    "dftpu_data_quality_duplicate_rows",
    "dftpu_data_quality_negative_sales",
    "dftpu_data_quality_nonfinite_sales",
    "dftpu_data_quality_short_series",
    "dftpu_data_quality_constant_series",
    "dftpu_data_quality_issues",
    "dftpu_ingest_dirty_series",
    "dftpu_ingest_refit_backlog",
    "dftpu_quality_series_observed",
    "dftpu_shard_owned",
    "dftpu_shard_resident_series",
})

#: max-merged gauge FAMILIES: SLO burn/firing state (an SLO burning on ANY
#: replica is burning fleet-wide) and per-replica capacity watermarks
#: (host RSS, device bytes in use — fleet headroom is set by the WORST
#: replica, and summing would invent memory pressure no single process has)
_GAUGE_MAX_PREFIXES = ("dftpu_slo_", "dftpu_cost_watermark_")

#: compiled-program cost registry gauges — REPLICATED, not summed: every
#: replica shares one AOT store and reports the same program fingerprints,
#: so the first replica's copy stands for the fleet (summing would
#: multiply FLOPs by the replica count)
_GAUGE_REPLICATE_PREFIX = "dftpu_cost_program_"


def aggregate_prometheus(texts: List[str]) -> str:
    """Merge replica ``/metrics`` expositions, TYPE-aware.

    ``# HELP`` / ``# TYPE`` lines keep the first replica's wording, and the
    TYPE map drives the fold per family:

      * **histograms** merge BUCKET-WISE: ``_bucket`` samples group by
        family + non-``le`` labels, bounds union across replicas, and each
        replica contributes its cumulative count carried forward from its
        largest own bound at or below each merged bound — so replicas whose
        bucket ladders differ (a rolling config change mid-fleet) still
        produce one monotone cumulative ladder instead of an interleaved
        corrupt one.  ``_sum``/``_count`` sum as before.
      * **``dftpu_slo_*`` gauges** merge by MAX: an SLO burning or firing
        on ANY replica is burning fleet-wide — summing would overstate burn
        rates by the replica count, and averaging would hide a single
        burning replica behind healthy peers.  The shared-WAL ingest gauges
        (:data:`_GAUGE_MAX_MERGE`) merge the same way: every replica
        reports the SAME on-disk log and applied frontier, so summing a
        3-replica fleet would triple the WAL size and the convergence
        point is the furthest-ahead replica.  The per-replica capacity
        watermarks (:data:`_GAUGE_MAX_PREFIXES` — host RSS, device bytes)
        also merge by MAX: headroom is set by the worst replica.
      * **``dftpu_cost_program_*`` gauges** REPLICATE — first replica
        wins: the fleet shares one AOT store, every replica reports the
        same compiled-program fingerprints, and summing would multiply a
        program's FLOPs by the replica count.
      * everything else — counters and the additive gauges enumerated in
        :data:`_GAUGE_SUM_MERGE` (queue depth in flight across the fleet,
        ``dftpu_cost_device_saturation``) — sums by name+labels.
    The metrics-merge-drift lint rule holds this section honest: every
    ``dftpu_*`` gauge in the tree must appear in exactly one policy set
    (or match a policy prefix) or ``make lint`` fails.
    """
    entries: List[tuple] = []      # ("meta", raw) | ("sample", key) |
    #                                ("hist", group_key), in first-seen order
    values: dict = {}              # sample key -> folded value
    seen_meta: set = set()
    types: dict = {}               # family name -> prometheus kind
    # (family, other-labels str) -> per-replica {le_float: cumulative}
    hist_groups: dict = {}
    hist_le_str: dict = {}         # le_float -> original le token
    for replica_i, text in enumerate(texts):
        for raw in text.splitlines():
            if not raw.strip():
                continue
            if raw.startswith("#"):
                parts = raw.split(None, 3)
                if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                    if parts[1] == "TYPE" and len(parts) >= 4:
                        types[parts[2]] = parts[3].strip()
                    meta_key = (parts[1], parts[2])
                    if meta_key not in seen_meta:
                        seen_meta.add(meta_key)
                        entries.append(("meta", raw))
                continue
            key, _, val = raw.rpartition(" ")
            if not key:
                continue
            try:
                v = float(val)
            except ValueError:
                continue
            name = key.partition("{")[0]
            if name.endswith("_bucket") and \
                    types.get(name[: -len("_bucket")]) == "histogram":
                brace = key.find("{")
                body = key[brace + 1: key.rfind("}")] if brace >= 0 else ""
                pairs = _split_label_pairs(body)
                le_raw = None
                others = []
                for p in pairs:
                    k, _, lv = p.partition("=")
                    if k.strip() == "le":
                        le_raw = lv.strip().strip('"')
                    else:
                        others.append(p)
                if le_raw is None:
                    continue  # malformed bucket line; drop rather than guess
                le = _le_value(le_raw)
                hist_le_str[le] = le_raw
                gkey = (name, ",".join(others))
                group = hist_groups.setdefault(gkey, {})
                if not group:
                    entries.append(("hist", gkey))
                group.setdefault(replica_i, {})[le] = v
                continue
            if key in values:
                if (name.startswith(_GAUGE_MAX_PREFIXES)
                        or name in _GAUGE_MAX_MERGE) and \
                        types.get(name) == "gauge":
                    values[key] = max(values[key], v)
                elif (name.startswith(_GAUGE_REPLICATE_PREFIX)
                        and types.get(name) == "gauge"):
                    pass  # replicated registry value: keep the first copy
                else:
                    values[key] += v
            else:
                values[key] = v
                entries.append(("sample", key))
    out = []
    for kind, payload in entries:
        if kind == "meta":
            out.append(payload)
        elif kind == "sample":
            out.append(f"{payload} {_fmt_value(values[payload])}")
        else:
            name, others = payload
            per_replica = hist_groups[payload]
            bounds = sorted({le for m in per_replica.values() for le in m})
            for le in bounds:
                total = 0.0
                for m in per_replica.values():
                    own = [b for b in m if b <= le]
                    if own:  # carry the replica's last cumulative forward
                        total += m[max(own)]
                label_body = ",".join(
                    ([others] if others else []) +
                    [f'le="{hist_le_str[le]}"'])
                out.append(f"{name}{{{label_body}}} {_fmt_value(total)}")
    return "\n".join(out) + ("\n" if out else "")


# -- replica bookkeeping -----------------------------------------------------

class Replica:
    """Per-replica state.  Deliberately lock-free: every field except the
    immutable identity is read and written ONLY while the supervisor holds
    its lock (the supervisor snapshots under the lock and acts outside)."""

    def __init__(self, index: int, port: int):
        self.index = index
        self.port = port
        self.proc = None            # Popen-compatible handle (poll/terminate/
        self.ready = False          # kill/wait) or an injected fake
        self.restarts = 0
        self.backoff_s = 0.0        # current restart delay (0 = next crash
        self.next_restart_at = 0.0  # restarts immediately); monotonic clock
        self.shards: tuple = ()     # owned shards (sharded fleets only);
        #                             rewritten under the lock on rebalance

    def describe(self) -> dict:
        alive = self.proc is not None and self.proc.poll() is None
        return {
            "index": self.index,
            "port": self.port,
            "alive": alive,
            "ready": self.ready,
            "restarts": self.restarts,
            "shards": list(self.shards),
        }


#: spawn_fn(index, port) for round-robin fleets; sharded fleets call it as
#: spawn_fn(index, port, shards) so the child knows its assignment at boot
SpawnFn = Callable[..., object]


def default_spawn_fn(
    config: FleetConfig,
    artifact_dir: str,
    serving_conf: Optional[dict] = None,
    env_extra: Optional[dict] = None,
    sharding: Optional[ShardingConfig] = None,
) -> SpawnFn:
    """A spawn_fn launching ``serving/replica.py`` subprocesses.

    Each child loads the artifact itself (no pickled state crosses the
    process boundary), binds its assigned port with ``/readyz`` at 503,
    warms the bucket ladder, then flips ready.  ``env_extra`` typically
    carries ``DFTPU_COMPILE_CACHE`` so every replica shares one AOT store.
    With ``sharding``, the supervisor passes each replica its shard
    assignment and the child subsets its params/state/WAL to those shards
    before marking ready.
    """
    serving_conf = dict(serving_conf or {})
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def spawn(index: int, port: int, shards=None):
        replica_conf = {
            "artifact_dir": artifact_dir,
            "host": config.replica_host,
            "port": port,
            "warmup_sizes": serving_conf.get("warmup_sizes"),
            "warmup_horizon": serving_conf.get("warmup_horizon", 90),
            "batching": serving_conf.get("batching"),
            "tracing": serving_conf.get("tracing"),
            "model_version": serving_conf.get("model_version"),
            "mesh_devices": config.mesh_devices,
            # quality/store/slo conf (tasks/fleet.py passes the top-level
            # monitoring block through); the replica suffixes its store
            # directory with the port so two processes never share a
            # segment cursor
            "monitoring": serving_conf.get("monitoring"),
            # streaming ingest conf: unlike the quality store, wal_dir is
            # shared verbatim — replicas converge by following one log
            # (the replica defaults apply_mode to "interval" in a fleet)
            "ingest": serving_conf.get("ingest"),
            # anomaly scoring conf: each replica scores its own shards'
            # points; the front door scatter-gathers /detect_anomalies
            "anomaly": serving_conf.get("anomaly"),
            # materialized forecast cache: each replica caches exactly its
            # owned series' frames and invalidates on its OWN state installs
            # (WAL apply/refit) — no cross-replica fan-out needed because a
            # shard's writes only ever land at its owners
            "cache": serving_conf.get("cache"),
            # HTTP data plane: one serving.http block tunes keep-alive,
            # worker-pool size and idle timeout on replica AND front door
            "http": serving_conf.get("http"),
            # series partition: the child subsets its forecaster/WAL to
            # these shards and follows only their wal_dir/shard-<k>/ logs
            "sharding": (None if sharding is None
                         else dataclasses.asdict(sharding)),
            "shards": None if shards is None else sorted(shards),
        }
        env = dict(os.environ)
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = (
            pkg_root + (os.pathsep + existing if existing else ""))
        if env_extra:
            env.update(env_extra)
        return subprocess.Popen(
            [sys.executable, "-m",
             "distributed_forecasting_tpu.serving.replica",
             "--conf", json.dumps(replica_conf)],
            env=env,
        )

    return spawn


# -- the supervisor ----------------------------------------------------------

class FleetSupervisor:
    """Spawns, health-polls, and restarts the replica set.

    Thread-safety: ``_lock`` guards every Replica field and the round-robin
    cursor.  The poll loop snapshots under the lock, probes/spawns/waits
    OUTSIDE it, then applies observations under the lock again — no
    blocking call ever runs inside the critical section.
    """

    def __init__(self, config: FleetConfig, spawn_fn: SpawnFn,
                 sharding: Optional[ShardingConfig] = None,
                 key_names: Optional[tuple] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 request_timeout_s: Optional[float] = None,
                 http: Optional[HttpConfig] = None):
        self._config = config
        self._spawn = spawn_fn
        self.resilience = resilience or ResilienceConfig()
        self.http = http or HttpConfig()
        # satellite of the deadline work: every forwarded leg gets an
        # explicit timeout bounded by the replica's own request timeout
        # (plus slack for transport), so a hung socket can no longer pin
        # a front-door worker for the full proxy_timeout_s
        self.request_timeout_s = request_timeout_s
        self._breakers: dict = {}       # port -> CircuitBreaker, under _lock
        self.leg_latency = LatencyReservoir()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._rr = 0
        ports = [
            _free_port(config.replica_host) if config.base_port == 0
            else config.base_port + i
            for i in range(config.replicas)
        ]
        self._replicas = [Replica(i, p) for i, p in enumerate(ports)]
        # series partition (None = classic round-robin fleet).  The
        # assignment table and per-replica shard tuples are shared state
        # under _lock like every Replica field; the sharding config and
        # quota bucket are immutable/internally-locked.
        self.sharding = sharding
        self._assignments: dict = {}
        self._schema_key_names: Optional[tuple] = (
            tuple(key_names) if key_names else None)
        self.quota = None
        if sharding is not None:
            self._assignments = compute_assignments(
                sharding, range(config.replicas))
            for rep in self._replicas:
                rep.shards = tuple(sorted(
                    k for k, owners in self._assignments.items()
                    if rep.index in owners))
            if sharding.quota_rps > 0:
                self.quota = TokenBucket(
                    sharding.quota_rps, sharding.quota_burst)
        self.logger = get_logger("FleetSupervisor")
        self.registry = MetricsRegistry()
        # keep-alive connections to replicas, shared by every forward/
        # scatter/health leg; its dftpu_http_pool_* counters land on this
        # registry and ride the front door's /metrics exposition
        self.pool = ConnectionPool(self.http, registry=self.registry)
        self._g_total = self.registry.gauge(
            "fleet_replicas_total", "replicas the supervisor manages")
        self._g_ready = self.registry.gauge(
            "fleet_replicas_ready", "replicas currently passing /readyz")
        self._c_restarts = self.registry.counter(
            "fleet_restarts_total", "replica processes (re)spawned after "
            "the initial launch")
        self._c_conn_failures = self.registry.counter(
            "fleet_connection_failures_total",
            "front-door forwards that failed at the connection level")
        self._c_retries = self.registry.counter(
            "fleet_retries_total",
            "requests the front door retried on another replica")
        self._c_unrouted = self.registry.counter(
            "fleet_unrouted_total",
            "requests that exhausted the retry window with no ready replica")
        self._c_unowned = self.registry.counter(
            "fleet_unowned_shard_total",
            "requests for a shard with no owner in the assignment table — "
            "retryable (503 + Retry-After), distinct from no-ready-replica")
        self._c_routed = self.registry.counter(
            "dftpu_shard_routed_total",
            "single-shard requests forwarded straight to an owning replica")
        self._c_scatter = self.registry.counter(
            "dftpu_shard_scatter_total",
            "multi-shard requests fanned out to owners and merged")
        self._c_shard_unrouted = self.registry.counter(
            "dftpu_shard_unrouted_total",
            "POSTs that could not be shard-planned (missing key columns, "
            "unknown path) and fell back to round-robin")
        self._c_rebalance = self.registry.counter(
            "dftpu_shard_rebalance_total",
            "shard-assignment changes applied (resize or owner hand-off)")
        self._c_quota_rejected = self.registry.counter(
            "dftpu_shard_quota_rejected_total",
            "requests rejected 429 by per-tenant admission at the front "
            "door")
        self._g_breaker = self.registry.labeled_gauge(
            "dftpu_fleet_breaker_state", ("port",),
            "per-replica circuit breaker state "
            "(0 closed / 1 open / 2 half-open)")
        self._c_breaker_open = self.registry.counter(
            "dftpu_fleet_breaker_skipped_total",
            "forward attempts skipped because the replica's breaker was "
            "open")
        self._c_deadline_exhausted = self.registry.counter(
            "dftpu_fleet_deadline_exhausted_total",
            "requests shed at the front door with their deadline budget "
            "spent (HTTP 503)")
        self._c_hedges = self.registry.counter(
            "dftpu_fleet_hedges_total",
            "duplicate scatter legs fired after the hedge delay")
        self._c_hedge_wins = self.registry.counter(
            "dftpu_fleet_hedge_wins_total",
            "scatter legs where the hedged duplicate answered first")
        self._c_hedge_cancelled = self.registry.counter(
            "dftpu_fleet_hedge_cancelled_total",
            "losing duplicate legs discarded after first-response-wins")
        self._g_total.set(config.replicas)
        # dftsan (no-op unless DFTPU_TSAN armed): the routing tables the
        # PR-16 stop() race corrupted are exactly the guarded set
        sanitizer.attach(self, cls=FleetSupervisor, guards={
            "_lock": ("_replicas", "_rr", "_assignments")})

    # -- introspection (snapshot under lock, return plain data) -------------
    @property
    def config(self) -> FleetConfig:
        return self._config

    @property
    def host(self) -> str:
        return self._config.replica_host

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def describe(self) -> List[dict]:
        with self._lock:
            return [r.describe() for r in self._replicas]

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.ready)

    def all_ports(self) -> List[int]:
        with self._lock:
            return [r.port for r in self._replicas]

    def rotation(self) -> List[int]:
        """Ready ports, rotated round-robin per call: the first entry is
        this request's primary, the rest its retry order."""
        with self._lock:
            ports = [r.port for r in self._replicas if r.ready]
            if not ports:
                return []
            start = self._rr % len(ports)
            self._rr += 1
        return ports[start:] + ports[:start]

    # -- shard routing (sharded fleets only) ---------------------------------
    def assignments(self) -> dict:
        """shard -> owner replica-index list, one locked snapshot."""
        with self._lock:
            return {k: list(v) for k, v in self._assignments.items()}

    def shard_owners(self, shard: int) -> List[int]:
        with self._lock:
            return list(self._assignments.get(int(shard), ()))

    def owner_rotation(self, shard: int) -> List[int]:
        """Ready ports among the shard's owners, rotated per call — the
        shard-restricted analogue of :meth:`rotation`."""
        with self._lock:
            owners = set(self._assignments.get(int(shard), ()))
            ports = [r.port for r in self._replicas
                     if r.index in owners and r.ready]
            if not ports:
                return []
            start = self._rr % len(ports)
            self._rr += 1
        return ports[start:] + ports[:start]

    def key_names(self) -> Optional[tuple]:
        with self._lock:
            return self._schema_key_names

    def set_key_names(self, names) -> None:
        """Cache the artifact's key columns (the front door discovers them
        from a replica's ``/schema`` on the first routed request)."""
        with self._lock:
            self._schema_key_names = tuple(names)

    # -- front-door feedback ------------------------------------------------
    def report_failure(self, port: int) -> None:
        """A connection-level forward failure: stop routing to this replica
        until the next successful health probe flips it back.  Its pooled
        idle connections drain too — they point at a peer that just proved
        unreliable, and a later checkout must dial (and re-verify) fresh."""
        self._c_conn_failures.inc()
        self.pool.drain(self._config.replica_host, port)
        with self._lock:
            for r in self._replicas:
                if r.port == port:
                    r.ready = False

    # -- circuit breakers + deadline budgets ---------------------------------
    def breaker_for(self, port: int) -> Optional[CircuitBreaker]:
        """The port's breaker (created lazily), or None when disabled."""
        res = self.resilience
        if res.breaker_failures < 1:
            return None
        with self._lock:
            br = self._breakers.get(port)
            if br is None:
                br = CircuitBreaker(
                    res.breaker_failures, res.breaker_open_s,
                    slow_s=res.breaker_slow_s)
                self._breakers[port] = br
        return br

    def breaker_allow(self, port: int) -> bool:
        """Routing gate: False ejects the port from this attempt.  Every
        True MUST be followed by breaker_success/breaker_failure, or a
        half-open probe slot stays claimed forever."""
        br = self.breaker_for(port)
        if br is None:
            return True
        ok = br.allow()
        if not ok:
            self._c_breaker_open.inc()
        self._g_breaker.set(br.state, port=str(port))
        return ok

    def breaker_success(self, port: int, elapsed_s: float) -> None:
        self.leg_latency.observe(elapsed_s)
        br = self.breaker_for(port)
        if br is not None:
            br.record_success(elapsed_s)
            self._g_breaker.set(br.state, port=str(port))

    def breaker_failure(self, port: int) -> None:
        br = self.breaker_for(port)
        if br is not None:
            br.record_failure()
            self._g_breaker.set(br.state, port=str(port))
            if br.state == OPEN:
                # breaker-aware eviction: an ejected replica's idle
                # keep-alive sockets must not survive into its half-open
                # probe — the probe decides on a FRESH connection
                self.pool.drain(self._config.replica_host, port)

    def request_deadline(self, headers) -> Optional[float]:
        """Monotonic deadline for an incoming request (header or conf
        default), or None when unbounded."""
        return deadline_from_headers(
            headers, self.resilience.default_deadline_ms)

    def leg_timeout_s(self, deadline: Optional[float] = None) -> float:
        """Socket timeout for one forwarded leg: the proxy cap, tightened
        by the replica's own request timeout (+5s transport slack — we
        wait for the replica's 503, not for a hung socket) and by the
        request's remaining deadline budget."""
        leg = self._config.proxy_timeout_s
        if self.request_timeout_s is not None:
            leg = min(leg, self.request_timeout_s + 5.0)
        rem = remaining_ms(deadline)
        if rem is not None:
            leg = min(leg, max(
                rem / 1000.0,
                self.resilience.min_leg_timeout_ms / 1000.0))
        return leg

    def hedge_delay_s(self) -> float:
        """How long a scatter leg may stay silent before its duplicate
        fires: the conf's fixed delay, or the observed leg p95."""
        res = self.resilience
        if res.hedge_delay_ms > 0:
            return res.hedge_delay_ms / 1000.0
        floor = res.hedge_min_delay_ms / 1000.0
        p95 = self.leg_latency.p95()
        return max(p95, floor) if p95 is not None else floor

    def note_deadline_exhausted(self) -> None:
        self._c_deadline_exhausted.inc()

    def note_hedge(self) -> None:
        self._c_hedges.inc()

    def note_hedge_win(self) -> None:
        self._c_hedge_wins.inc()

    def note_hedge_cancelled(self) -> None:
        self._c_hedge_cancelled.inc()

    def note_retry(self) -> None:
        self._c_retries.inc()

    def note_unrouted(self) -> None:
        self._c_unrouted.inc()

    def note_unowned(self, shard: int) -> None:
        self._c_unowned.inc()
        self.logger.warning("request for shard %d, which has no owner "
                            "in the assignment table", shard)

    def note_routed(self) -> None:
        self._c_routed.inc()

    def note_scatter(self) -> None:
        self._c_scatter.inc()

    def note_shard_unrouted(self) -> None:
        self._c_shard_unrouted.inc()

    def note_quota_rejected(self) -> None:
        self._c_quota_rejected.inc()

    def render_metrics(self) -> str:
        return self.registry.render_prometheus()

    # -- lifecycle ----------------------------------------------------------
    def _spawn_replica(self, index: int, port: int, shards):
        """Sharded fleets pass the assignment; classic spawn fns (and every
        pre-sharding test fake) keep their two-argument signature."""
        if self.sharding is not None:
            return self._spawn(index, port, shards)
        return self._spawn(index, port)

    def start(self) -> None:
        """Spawn every replica and start the health-poll loop."""
        with self._lock:
            replicas = list(self._replicas)
        spawned = [(rep, self._spawn_replica(rep.index, rep.port, rep.shards))
                   for rep in replicas]
        thread = threading.Thread(
            target=self._poll_loop, name="fleet-health-poll", daemon=True)
        with self._lock:
            for rep, proc in spawned:
                rep.proc = proc
            self._poll_thread = thread
        self.logger.info(
            "spawned %d replica(s) on ports %s", len(spawned),
            [rep.port for rep, _ in spawned])
        thread.start()

    def wait_ready(self, min_ready: int = 1,
                   timeout: Optional[float] = None) -> bool:
        """Block until ``min_ready`` replicas pass /readyz (True) or the
        timeout/stop arrives (False)."""
        budget = self._config.ready_timeout_s if timeout is None else timeout
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            if self.ready_count() >= min_ready:
                return True
            if self._stop.wait(timeout=0.05):
                return False
        return self.ready_count() >= min_ready

    def poll_once(self) -> None:
        """One health sweep: probe outside the lock, update under it,
        restart crashed replicas (capped exponential backoff) outside it."""
        with self._lock:
            snapshot = [(r, r.proc, r.port) for r in self._replicas]
        cfg = self._config
        observed = []
        for rep, proc, port in snapshot:
            alive = proc is not None and proc.poll() is None
            ready = alive and _probe_ready(cfg.replica_host, port,
                                           cfg.probe_timeout_s,
                                           pool=self.pool)
            if not alive:
                # a dead replica's pooled sockets are dead too; drop them
                # before the restart brings a new process up on the port
                self.pool.drain(cfg.replica_host, port)
            observed.append((rep, alive, ready))
        now = time.monotonic()
        to_restart = []
        with self._lock:
            if self._stop.is_set():
                # a sweep that straddled stop() must not write back its
                # pre-stop observations (or respawn a draining replica)
                return
            for rep, alive, ready in observed:
                if alive:
                    rep.ready = ready
                    if ready:
                        rep.backoff_s = 0.0  # healthy: reset the backoff
                else:
                    rep.ready = False
                    if now >= rep.next_restart_at:
                        rep.backoff_s = min(
                            cfg.restart_backoff_s if rep.backoff_s == 0.0
                            else rep.backoff_s * 2.0,
                            cfg.restart_backoff_max_s,
                        )
                        rep.next_restart_at = now + rep.backoff_s
                        rep.restarts += 1
                        to_restart.append(rep)
            n_ready = sum(1 for r in self._replicas if r.ready)
        self._g_ready.set(n_ready)
        for rep in to_restart:
            self._c_restarts.inc()
            self.logger.warning(
                "replica %d (port %d) is down; restarting "
                "(attempt %d, next backoff %.1fs)",
                rep.index, rep.port, rep.restarts, rep.backoff_s)
            with self._lock:
                shards = rep.shards  # current assignment, not spawn-time's
            try:
                proc = self._spawn_replica(rep.index, rep.port, shards)
            except Exception:
                self.logger.exception(
                    "respawn of replica %d failed; will retry after backoff",
                    rep.index)
                continue
            if self.sharding is not None:
                # the respawn IS the hand-off: the child replays its shard
                # WALs and loads the shard state before /readyz flips
                self._c_rebalance.inc()
            with self._lock:
                rep.proc = proc

    def kill_replica(self, index: int) -> None:
        """Chaos hook (bench/CI smoke): SIGKILL one replica's process.  The
        poll loop restarts it with its current shard assignment — in a
        sharded fleet that restart IS the hand-off path (shard WAL replay
        + state load before /readyz), which is exactly what the smoke
        gates on converging."""
        proc = None
        with self._lock:
            for r in self._replicas:
                if r.index == int(index):
                    proc = r.proc
                    r.ready = False
                    break
            else:
                raise ValueError(f"no replica with index {index}")
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass
        with self._lock:
            port = next((r.port for r in self._replicas
                         if r.index == int(index)), None)
        if port is not None:
            # pooled keep-alive sockets into the killed process would fail
            # on next reuse; drop them now so forwards dial the restart
            self.pool.drain(self._config.replica_host, port)

    def resize(self, replicas: int) -> None:
        """Grow or shrink the replica set and rebalance shard ownership.

        The consistent-hash ring makes the diff small (adding one replica
        to N remaps ~1/(N+1) of the shards); a replica whose assignment
        changed is terminated and the poll loop respawns it with the new
        shard set — the respawned owner replays the shard WALs and loads
        the shard state sidecar before ``/readyz`` flips, so hand-off
        never serves a half-loaded shard.  Ports/spawns happen OUTSIDE the
        lock; only the table/replica-list swap is inside it.
        """
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        cfg = self._config
        with self._lock:
            current = len(self._replicas)
        new_ports = [
            _free_port(cfg.replica_host) if cfg.base_port == 0
            else cfg.base_port + i
            for i in range(current, replicas)
        ]
        new_assign = (compute_assignments(self.sharding, range(replicas))
                      if self.sharding is not None else {})
        added = [Replica(current + i, p) for i, p in enumerate(new_ports)]
        to_terminate = []
        to_spawn = []
        changed = 0
        with self._lock:
            victims = self._replicas[replicas:]
            self._replicas = self._replicas[:replicas] + added
            self._assignments = new_assign
            for rep in self._replicas:
                shards = tuple(sorted(
                    k for k, owners in new_assign.items()
                    if rep.index in owners))
                if self.sharding is not None and shards != rep.shards:
                    rep.shards = shards
                    if rep not in added:
                        changed += 1
                        rep.ready = False
                        to_terminate.append(rep.proc)
                else:
                    rep.shards = shards
            for rep in victims:
                rep.ready = False
                to_terminate.append(rep.proc)
            to_spawn = list(added)
        if self.sharding is not None and (changed or added or victims):
            self._c_rebalance.inc(changed + len(added) + len(victims))
        self._g_total.set(replicas)
        for rep in to_spawn:
            try:
                proc = self._spawn_replica(rep.index, rep.port, rep.shards)
            except Exception:
                self.logger.exception(
                    "spawn of replica %d failed; the poll loop will retry",
                    rep.index)
                continue
            with self._lock:
                rep.proc = proc
        for proc in to_terminate:
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass
        self.logger.info(
            "resized fleet to %d replica(s) (%d reassigned, %d added, "
            "%d removed)", replicas, changed, len(added), len(victims))

    def _poll_loop(self) -> None:
        while not self._stop.wait(
                timeout=self._config.health_poll_interval_s):
            self.poll_once()

    def stop(self) -> None:
        """Graceful drain: stop polling, SIGTERM every replica (each drains
        its own batcher — server.shutdown), escalate to SIGKILL after
        ``drain_timeout_s``."""
        self._stop.set()
        with self._lock:
            thread = self._poll_thread
            procs = [r.proc for r in self._replicas]
        if thread is not None:
            thread.join(timeout=5.0)
        with self._lock:
            # cleared AFTER the join: a health sweep in flight when _stop
            # was set can no longer resurrect a pre-stop ready=True
            for r in self._replicas:
                r.ready = False
        self._g_ready.set(0)
        # close idle keep-alive sockets BEFORE the SIGTERMs: a drain must
        # not leave half-open connections for the replicas to time out on
        self.pool.close()
        for proc in procs:
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self._config.drain_timeout_s
        for proc in procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except Exception:
                try:
                    proc.kill()
                except OSError:
                    pass
        self.logger.info("fleet stopped")


# -- the front door ----------------------------------------------------------

class _DeadlineExhausted(Exception):
    """A request's deadline budget ran out inside the front door — the
    routing loops raise it so every caller converges on one distinct 503
    (shed, not "no ready replica")."""


class _FrontDoorHandler(KeepAliveHandlerMixin, BaseHTTPRequestHandler):
    server_version = "dftpu-fleet/1.0"

    def log_message(self, fmt, *args):
        self.server.logger.info("%s " + fmt, self.address_string(), *args)

    def _send_json(self, code: int, payload: dict, extra_headers=()) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        tid = self._trace_id()
        if tid:
            # echo the sanitized correlation id on every front-door-built
            # response (sheds, scatter merges, health) — same contract as
            # the replica handler, so error bodies stay greppable by trace
            self.send_header("X-Trace-Id", tid)
        for name, value in extra_headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        sup = self.server.supervisor
        if self.path == "/healthz":
            # the front door's own liveness, independent of the fleet
            self._send_json(200, {"status": "ok"})
        elif self.path == "/readyz":
            n = sup.ready_count()
            self._send_json(
                200 if n > 0 else 503,
                {"ready": n > 0, "ready_replicas": n, "replicas": sup.size},
                extra_headers=(() if n > 0
                               else (("Retry-After", "1"),)))
        elif self.path == "/fleet":
            self._send_json(200, {"replicas": sup.describe()})
        elif self.path == "/metrics":
            # the aggregation legs deliberately run under probe_timeout_s,
            # not the request budget: a scrape should see every replica
            # even when the scraper sent a tight X-Deadline-Ms
            # dflint: disable=deadline-propagation — probe-budgeted scrape
            self._metrics()
        else:
            # /health, /schema, ... answer the same on any replica
            self._proxy("GET", None, sup.request_deadline(self.headers))

    def _send_deadline_shed(self) -> None:
        self.server.supervisor.note_deadline_exhausted()
        self._send_json(
            503,
            {"error": "deadline budget exhausted",
             "detail": "the request's X-Deadline-Ms budget ran out before "
                       "a replica answered; retry with a larger budget"},
            extra_headers=(("Retry-After", "1"),))

    def do_POST(self):
        sup = self.server.supervisor
        deadline = sup.request_deadline(self.headers)
        rem = remaining_ms(deadline)
        if rem is not None and rem <= 0:
            # shed before reading the body: exhausted work gets its
            # terminal status immediately instead of a doomed forward
            self._send_deadline_shed()
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        if sup.sharding is not None:
            if self._routed_post(body, deadline):
                return
        self._proxy("POST", body, deadline)

    def _metrics(self) -> None:
        sup = self.server.supervisor
        cfg = sup.config
        texts = []
        for port in sup.all_ports():
            # every live replica contributes, ready or not (a draining
            # replica's counters still belong in the fleet totals)
            payload = _fetch(cfg.replica_host, port, "/metrics",
                             cfg.probe_timeout_s, pool=sup.pool)
            if payload is not None:
                texts.append(payload.decode())
        body = (aggregate_prometheus(texts) + sup.render_metrics()).encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _forward(self, host: int, port: int, method: str, body,
                 deadline: Optional[float] = None):
        sup = self.server.supervisor
        # fault site for EVERY front-door -> replica leg: an injected
        # OSError takes the callers' report-failure-and-retry path, an
        # injected sleep models a hung socket against the leg timeout
        failpoint("fleet.forward")
        timeout = sup.leg_timeout_s(deadline)
        headers = {"Content-Type": self.headers.get(
            "Content-Type", "application/json")} if body is not None else {}
        rem = remaining_ms(deadline)
        if rem is not None:
            # the remaining budget travels downstream; a replica that
            # receives <= 0 sheds before dispatch (serving/server.py)
            headers["X-Deadline-Ms"] = str(int(rem))
        tid = self._trace_id()
        if tid:
            # the correlation id crosses the fleet hop too, so replica
            # spans join the same trace the front door opened
            headers["X-Trace-Id"] = tid
        for attempt in (0, 1):
            conn, reused = sup.pool.acquire(host, port, timeout)
            try:
                conn.request(method, self.path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, http.client.HTTPException):
                sup.pool.discard(conn)
                if reused and attempt == 0:
                    # the half-closed keep-alive race (replica restarted or
                    # reaped the idle socket a beat before us), not a sick
                    # replica: retry ONCE on a guaranteed-fresh connection
                    # so the race never becomes a client-visible failure.
                    # predict is idempotent, so the replay is safe.
                    continue
                raise
            # a response the server is about to close (HTTP/1.0 replica,
            # Connection: close) is not reusable; everything else is
            sup.pool.release(conn, healthy=not resp.will_close)
            return resp.status, resp.getheader(
                "Content-Type", "application/json"), payload

    # -- routed dispatch (sharded fleets) ------------------------------------

    def _trace_id(self) -> Optional[str]:
        """Same header sanitation as the replica handler: a hostile
        X-Trace-Id must not ride into span files."""
        raw = (self.headers.get("X-Trace-Id") or "").strip()
        if 1 <= len(raw) <= 64 and all(c.isalnum() or c in "-_" for c in raw):
            return raw
        return None

    def _schema_key_names(self) -> Optional[tuple]:
        """The artifact's key columns, discovered once from any ready
        replica's ``/schema`` and cached on the supervisor."""
        sup = self.server.supervisor
        names = sup.key_names()
        if names:
            return names
        cfg = sup.config
        for port in sup.rotation():
            payload = _fetch(cfg.replica_host, port, "/schema",
                             cfg.probe_timeout_s, pool=sup.pool)
            if payload is None:
                continue
            try:
                names = tuple(json.loads(payload).get("key_names") or ())
            except (ValueError, AttributeError):
                continue
            if names:
                sup.set_key_names(names)
                return names
        return None

    def _forward_with_retry(self, ports_fn, method: str, body,
                            deadline: Optional[float] = None):
        """Retry-on-next-port over ``ports_fn()`` until the retry window
        (or the request's deadline budget) closes.  Returns ``(status,
        ctype, payload, port)`` or ``None`` — unlike :meth:`_proxy` it
        never writes the response itself, so scatter threads can call it
        concurrently.  Raises :class:`_DeadlineExhausted` when the budget
        runs out with no response."""
        sup = self.server.supervisor
        cfg = sup.config
        window = time.monotonic() + cfg.retry_window_s
        attempts = 0
        while True:
            for port in ports_fn():
                rem = remaining_ms(deadline)
                if rem is not None and rem <= 0:
                    raise _DeadlineExhausted()
                if not sup.breaker_allow(port):
                    continue
                attempts += 1
                if attempts > 1:
                    sup.note_retry()
                t0 = time.monotonic()
                try:
                    status, ctype, payload = self._forward(
                        cfg.replica_host, port, method, body,
                        deadline=deadline)
                except (OSError, http.client.HTTPException):
                    sup.breaker_failure(port)
                    sup.report_failure(port)
                    continue
                sup.breaker_success(port, time.monotonic() - t0)
                return status, ctype, payload, port
            rem = remaining_ms(deadline)
            if rem is not None and rem <= 0:
                raise _DeadlineExhausted()
            if time.monotonic() >= window:
                return None
            # no ready owner right now; wait for the poll loop's hand-off
            time.sleep(0.05)

    def _routed_post(self, body, deadline: Optional[float] = None) -> bool:
        """Shard-route a POST.  Returns True when the request was fully
        handled here; False falls back to round-robin ``_proxy`` (body not
        shard-plannable: unknown path, missing key columns, non-JSON)."""
        sup = self.server.supervisor
        # once-per-boot cached /schema discovery bounded by probe_timeout_s;
        # not per-request work, so it does not spend the request's budget
        # dflint: disable=deadline-propagation — probe-budgeted discovery
        names = self._schema_key_names()
        if names is None:
            return False
        try:
            parsed = json.loads(body or b"{}")
        except ValueError:
            return False
        tid = self._trace_id()
        tracer = get_tracer()
        with tracer.root_span("route.lookup", trace_id=tid,
                              path=self.path) as span:
            plan = plan_request(self.path, parsed, names,
                                sup.sharding.num_shards)
            if plan is not None:
                span.set_attribute("shards", len(plan.shards))
                span.set_attribute("series", len(plan.key_order))
        if plan is None:
            sup.note_shard_unrouted()
            return False
        quota = sup.quota
        if quota is not None:
            for tenant, charge in sorted(plan.tenants.items()):
                if not quota.allow(tenant, charge):
                    sup.note_quota_rejected()
                    self._send_json(
                        429,
                        {"error": f"tenant {tenant} over admission quota",
                         "tenant": tenant, "charge": charge},
                        extra_headers=(("Retry-After", "1"),))
                    return True
        if len(plan.shards) == 1:
            return self._routed_single(plan, body, deadline)
        return self._scatter(plan, parsed, tid, deadline)

    def _routed_single(self, plan, body,
                       deadline: Optional[float] = None) -> bool:
        """Single-shard fast path: the original body forwards VERBATIM to
        an owning replica, so the client sees that replica's exact bytes —
        the round-robin path's contract, now shard-aware."""
        sup = self.server.supervisor
        shard = plan.shards[0]
        if not sup.shard_owners(shard):
            sup.note_unowned(shard)
            self._send_json(
                503,
                {"error": "shard has no owner", "shard": shard,
                 "detail": "assignment table maps this shard to no "
                           "replica; retry after rebalance"},
                extra_headers=(("Retry-After", "1"),))
            return True
        try:
            res = self._forward_with_retry(
                lambda: sup.owner_rotation(shard), "POST", body,
                deadline=deadline)
        except _DeadlineExhausted:
            self._send_deadline_shed()
            return True
        if res is None:
            sup.note_unrouted()
            self._send_json(
                503,
                {"error": "no ready replica for shard", "shard": shard},
                extra_headers=(("Retry-After", "1"),))
            return True
        sup.note_routed()
        status, ctype, payload, port = res
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("X-Fleet-Replica", str(port))
        self.send_header("X-Fleet-Shard", str(shard))
        self.end_headers()
        self.wfile.write(payload)
        return True

    def _hedged_forward(self, ports_fn, method: str, body,
                        deadline: Optional[float] = None):
        """First-response-wins over a primary leg and (after the hedge
        delay) a duplicate to the next owner.  Same return contract as
        :meth:`_forward_with_retry`, which is also the fallback when
        hedging is off, fewer than two owners are up, or both legs die.
        The losing duplicate is counted and discarded, never awaited —
        its thread still reports its breaker outcome when it lands."""
        sup = self.server.supervisor
        cfg = sup.config
        if not sup.resilience.hedge_enabled:
            return self._forward_with_retry(ports_fn, method, body,
                                            deadline=deadline)
        ports = ports_fn()
        if len(ports) < 2:
            return self._forward_with_retry(ports_fn, method, body,
                                            deadline=deadline)
        done = threading.Event()
        lock = threading.Lock()
        winner: list = []
        tracer = get_tracer()
        # hedge legs run on bare daemon threads: without this capture any
        # span a leg opens would detach from the request's trace
        ctx = tracer.current()

        def leg(port: int, is_hedge: bool):
            with tracer.context(ctx):
                t0 = time.monotonic()
                try:
                    status, ctype, payload = self._forward(
                        cfg.replica_host, port, method, body,
                        deadline=deadline)
                except (OSError, http.client.HTTPException):
                    sup.breaker_failure(port)
                    sup.report_failure(port)
                    return
                sup.breaker_success(port, time.monotonic() - t0)
                with lock:
                    if winner:
                        # the race is over: this duplicate's answer is
                        # discarded (the replica already did the work;
                        # predict is idempotent, so discarding is safe)
                        sup.note_hedge_cancelled()
                        return
                    winner.append((status, ctype, payload, port, is_hedge))
                done.set()

        threading.Thread(
            target=leg, args=(ports[0], False), daemon=True).start()
        if not done.wait(sup.hedge_delay_s()):
            sup.note_hedge()
            threading.Thread(
                target=leg, args=(ports[1], True), daemon=True).start()
        done.wait(sup.leg_timeout_s(deadline))
        with lock:
            res = winner[0] if winner else None
        if res is None:
            # both legs failed or are still hung: the classic retry loop
            # owns the remaining window (and the deadline bookkeeping)
            return self._forward_with_retry(ports_fn, method, body,
                                            deadline=deadline)
        status, ctype, payload, port, is_hedge = res
        if is_hedge:
            sup.note_hedge_win()
        return status, ctype, payload, port

    def _scatter(self, plan, parsed: dict, tid,
                 deadline: Optional[float] = None) -> bool:
        """Fan a multi-shard request out to one owner per shard and merge.

        A failed shard degrades to per-key ``errors`` entries in the merged
        body — the other shards' results still ship (partial failure is
        NOT a whole-request 5xx; only every-shard-failed is)."""
        sup = self.server.supervisor
        responses: dict = {}
        tracer = get_tracer()

        def one(shard: int):
            with tracer.context(ctx):
                return _one(shard)

        def _one(shard: int):
            if not sup.shard_owners(shard):
                sup.note_unowned(shard)
                return 503, json.dumps(
                    {"error": "shard has no owner"}).encode()
            sub = json.dumps(plan.sub_body(parsed, shard)).encode()
            try:
                res = self._hedged_forward(
                    lambda: sup.owner_rotation(shard), "POST", sub,
                    deadline=deadline)
            except _DeadlineExhausted:
                sup.note_deadline_exhausted()
                return 503, json.dumps(
                    {"error": "deadline budget exhausted"}).encode()
            if res is None:
                sup.note_unrouted()
                return 503, json.dumps(
                    {"error": "no ready replica for shard"}).encode()
            status, _, payload, _ = res
            return status, payload

        with tracer.root_span("route.scatter", trace_id=tid, path=self.path,
                              shards=len(plan.shards)):
            # per-shard work runs on bare threads: capture the scatter span
            # context here so each leg's forward spans stay under it
            ctx = tracer.current()
            threads = [
                threading.Thread(
                    target=lambda k=shard: responses.__setitem__(k, one(k)),
                    daemon=True)
                for shard in plan.shards
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        sup.note_scatter()
        # /ingest and /detect_anomalies share the "points" field, so the
        # merge dispatches on the path, not the plan's field name
        if plan.field == "inputs":
            status, merged = merge_invocation_responses(
                # dflint: disable=deadline-propagation — cached discovery
                plan, self._schema_key_names() or (), responses)
        elif self.path == "/detect_anomalies":
            status, merged = merge_detect_responses(
                # dflint: disable=deadline-propagation — cached discovery
                plan, self._schema_key_names() or (), responses)
        else:
            status, merged = merge_ingest_responses(plan, responses)
        headers = [("X-Fleet-Scatter", str(len(plan.shards)))]
        if status >= 500:
            headers.append(("Retry-After", "1"))
        self._send_json(status, merged, extra_headers=tuple(headers))
        return True

    def _proxy(self, method: str, body,
               deadline: Optional[float] = None) -> None:
        """Round-robin with retry-on-next-replica.

        Connection-level failures (refused/reset/timeout before a response
        arrives) mean the replica died or is mid-restart; predict is
        idempotent, so the request replays on the next ready replica and
        the client never sees the crash.  Application-level responses —
        including a replica's own 4xx/5xx — pass through untouched.
        Replicas with an open circuit breaker are skipped exactly like
        not-ready ones, and a spent deadline budget ends the loop with a
        distinct 503 instead of more doomed attempts.
        """
        sup = self.server.supervisor
        cfg = sup.config
        window = time.monotonic() + cfg.retry_window_s
        attempts = 0
        last_err: Optional[str] = None
        while True:
            for port in sup.rotation():
                rem = remaining_ms(deadline)
                if rem is not None and rem <= 0:
                    self._send_deadline_shed()
                    return
                if not sup.breaker_allow(port):
                    continue
                attempts += 1
                if attempts > 1:
                    sup.note_retry()
                t0 = time.monotonic()
                try:
                    status, ctype, payload = self._forward(
                        cfg.replica_host, port, method, body,
                        deadline=deadline)
                except (OSError, http.client.HTTPException) as e:
                    sup.breaker_failure(port)
                    sup.report_failure(port)
                    last_err = f"{type(e).__name__}: {e}"
                    continue
                sup.breaker_success(port, time.monotonic() - t0)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("X-Fleet-Replica", str(port))
                self.end_headers()
                self.wfile.write(payload)
                return
            rem = remaining_ms(deadline)
            if rem is not None and rem <= 0:
                self._send_deadline_shed()
                return
            if time.monotonic() >= window:
                break
            # no ready replica right now (all crashed or mid-restart):
            # wait for the supervisor's poll loop to bring one back
            time.sleep(0.05)
        sup.note_unrouted()
        self._send_json(
            503,
            {"error": "no ready replica",
             "detail": last_err or "fleet has no ready replicas",
             "attempts": attempts},
            extra_headers=(("Retry-After", "1"),),
        )


class FrontDoorServer(PooledHTTPServer):
    # keep-alive, TCP_NODELAY, backlog and the bounded worker pool come
    # from PooledHTTPServer — same serving.http block as the replicas.
    # No busy gauge here: the replicas already register
    # dftpu_http_workers_busy, and the front door's /metrics aggregates
    # their expositions — a second registration would duplicate the family.

    def __init__(self, addr, supervisor: FleetSupervisor,
                 http: Optional[HttpConfig] = None):
        super().__init__(addr, _FrontDoorHandler,
                         http=http if http is not None else supervisor.http)
        self.supervisor = supervisor
        self.logger = get_logger("FrontDoor")


def start_fleet(
    config: FleetConfig,
    artifact_dir: Optional[str] = None,
    serving_conf: Optional[dict] = None,
    front_host: str = "127.0.0.1",
    front_port: int = 0,
    env_extra: Optional[dict] = None,
    spawn_fn: Optional[SpawnFn] = None,
    wait: bool = True,
    sharding: Optional[ShardingConfig] = None,
    key_names: Optional[tuple] = None,
    resilience: Optional[ResilienceConfig] = None,
):
    """Boot the whole subsystem: supervisor + replicas + front door.

    Returns ``(supervisor, front_door_server)``; the front door runs on a
    daemon thread (its bound port is ``front.server_address[1]``).  Callers
    stop with ``front.shutdown(); supervisor.stop()``.  With ``sharding``
    the front door routes by series key instead of round-robinning
    (``key_names`` pre-seeds the routing schema; omitted, it is discovered
    from a replica's ``/schema``).  ``resilience`` arms the degradation
    layer (deadline budgets, breakers, hedging) and — when its
    ``failpoints`` spec is non-empty — the front door's OWN failpoint
    registry (replica children arm via the ``DFTPU_FAILPOINTS`` env var
    that tasks/fleet.py sets from the same conf block).
    """
    if sharding is not None and not sharding.enabled:
        sharding = None
    if resilience is not None and resilience.failpoints:
        from distributed_forecasting_tpu.monitoring import failpoints as _fp
        _fp.configure(resilience.failpoints, seed=resilience.failpoint_seed)
    # the replica's own request timeout bounds each forwarded leg
    # (satellite: a hung replica socket must not pin a front-door worker)
    request_timeout_s = None
    batching = (serving_conf or {}).get("batching") or {}
    if batching.get("request_timeout_s") is not None:
        request_timeout_s = float(batching["request_timeout_s"])
    # one serving.http block tunes the whole data plane: the supervisor's
    # outbound keep-alive pool, the front door's worker pool, and (via
    # default_spawn_fn's pass-through) every replica's server
    http = HttpConfig.from_conf((serving_conf or {}).get("http"))
    if spawn_fn is None:
        if artifact_dir is None:
            raise ValueError(
                "pass artifact_dir (for the default subprocess spawner) or "
                "an explicit spawn_fn")
        spawn_fn = default_spawn_fn(
            config, artifact_dir, serving_conf, env_extra=env_extra,
            sharding=sharding)
    supervisor = FleetSupervisor(config, spawn_fn, sharding=sharding,
                                 key_names=key_names,
                                 resilience=resilience,
                                 request_timeout_s=request_timeout_s,
                                 http=http)
    supervisor.start()
    if wait and not supervisor.wait_ready(min_ready=1):
        supervisor.stop()
        raise RuntimeError(
            f"no replica became ready within {config.ready_timeout_s}s")
    front = FrontDoorServer((front_host, front_port), supervisor, http=http)
    t = threading.Thread(target=front.serve_forever, daemon=True)
    t.start()
    supervisor.logger.info(
        "front door on %s:%d over %d replica(s)",
        front_host, front.server_address[1], supervisor.size)
    return supervisor, front
