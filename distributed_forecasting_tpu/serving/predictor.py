"""Batched inference model — the PyFunc-equivalent, minus the anti-patterns.

The reference serves inference through a custom ``mlflow.pyfunc.PythonModel``
that, per (store, item) group, looks a run up by name in a pickled run table,
sleeps 0.5 s as a rate-limit guard, and downloads + loads the per-series
Prophet model *inside every predict call* (reference
``notebooks/prophet/model_wrapper.py:11-73``), dispatched by another
``applyInPandas`` fan-out that also re-resolves the registered model per group
(``notebooks/prophet/04_inference.py:4-16``).  SURVEY.md §2.3-2/3 documents
the cost: >=250 s of sleep plus 1000+ registry/artifact round trips per batch.

:class:`BatchForecaster` is the TPU-native replacement: ONE artifact holding
the fitted parameter pytree for ALL series plus the key table; loaded once;
``predict`` selects the requested series by key and runs one compiled
forecast for the whole request.  Unseen keys raise a clear error (or are
skipped) instead of the reference's IndexError (§2.3-3).
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.models.base import get_model
from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.cost import cost_metrics
from distributed_forecasting_tpu.monitoring.trace import (
    clock as trace_clock,
    device_annotation,
    get_tracer,
)
# JSON round-trips tuples as lists; configs are static jit args and must
# stay hashable — shared freeze() restores tuples recursively
from distributed_forecasting_tpu.utils.config import freeze as _freeze

_PARAMS_FILE = "params.npz"
_META_FILE = "forecaster.json"
_SCALE_FILE = "interval_scale.npy"


def _to_jsonable(x):
    from distributed_forecasting_tpu.utils.config import to_jsonable

    return to_jsonable(x, strict=True)


def save_params_npz(path: str, params) -> str:
    """Serialize a flat-dataclass param pytree (fields = arrays/scalars) to a
    single .npz — the one-artifact-for-all-series persistence this framework
    uses where the reference stores one serialized Prophet model per series
    run (``notebooks/prophet/02_training.py:193-196``).  No pickle: plain
    arrays + a recorded dataclass type for reconstruction."""
    fields = {
        f.name: np.asarray(getattr(params, f.name))
        for f in dataclasses.fields(params)
    }
    np.savez(path, **fields)
    cls = type(params)
    return f"{cls.__module__}:{cls.__qualname__}"


def load_params_npz(path: str, params_type: str):
    """Reconstruct a param dataclass from its npz.

    Fields the class declares but the artifact lacks are back-filled from
    the class's ``_LEGACY_DEFAULTS`` registry (name -> fn(fields)), so
    artifacts serialized before a param class grew a field keep loading —
    e.g. pre-damped-trend HWParams npz's have no ``phi``; phi=1 is exactly
    the behavior they were fit with.  A missing field with no registered
    default still raises the constructor's natural TypeError.
    """
    module, qualname = params_type.split(":")
    cls = getattr(importlib.import_module(module), qualname)
    with np.load(path) as z:
        fields = {k: jnp.asarray(z[k]) for k in z.files}
    declared = {f.name for f in dataclasses.fields(cls)}
    backfill = getattr(cls, "_LEGACY_DEFAULTS", {})
    for name in sorted(declared - fields.keys()):
        if name in backfill:
            fields[name] = backfill[name](fields)
    return cls(**fields)


class UnknownSeriesError(KeyError):
    pass


def quantile_columns(quantiles) -> list:
    """Column names for quantile result frames (``q0.1``, ``q0.5``, ...).

    Single source of the naming rule: BatchForecaster emits these and the
    composite forecasters (bucketed/ensemble) must build matching empty
    frames for on_missing='skip' requests.
    """
    return [f"q{float(q):g}" for q in quantiles]


def _ladder_value(k: int) -> int:
    """Smallest pow2x3 ladder value >= k: {2^i} ∪ {3·2^i} = 1, 2, 3, 4, 6,
    8, 12, 16, 24, 32, ...

    The kernel round replaced the pure power-of-two request ladder: pow2
    wastes up to ~47% of dispatched rows as padding just past a boundary
    (k=17 -> bucket 32, 15 pad rows), while interleaving the 3·2^i rungs
    caps the waste at ~29% (k=17 -> 24) for one extra compiled program per
    octave — O(2·log S) programs total, still warmup-coverable.  The
    ``dftpu_cost_padding_waste`` gauge measures the fraction this buys.
    """
    if k <= 1:
        return 1
    p = 1 << (k - 1).bit_length()       # next power of two >= k
    three_quarters = 3 * (p >> 2)       # the 3·2^(i-2) rung below p
    return three_quarters if three_quarters >= k else p


def _bucket_ladder(sizes) -> tuple:
    """Every pow2x3 request bucket up to the largest requested size.

    Composite forecasters (ensemble/bucketed) split a request across
    members by per-series routing, so a listed warmup size can reach a
    member as ANY smaller sub-request; warming the whole ladder covers
    every possible split.  (1, 2, 3, 4, 6, ..., bucket(max(sizes))).
    """
    top_bucket = _ladder_value(max(max(int(k), 1) for k in sizes))
    ladder, b = [], 1
    while b <= top_bucket:
        ladder.append(b)
        if 3 * (b >> 1) > b:            # the 3·2^(i-1) rung between b and 2b
            ladder.append(3 * (b >> 1))
        b <<= 1
    return tuple(v for v in ladder if v <= top_bucket)


def result_block_index(out: pd.DataFrame, key_names) -> tuple:
    """``(T, {key tuple: block index})`` for a long predict result frame.

    Every serving predict returns one contiguous ``T``-row block per series
    (``_frame_skeleton`` tiles dates per series); the micro-batching
    coalescer (``serving/batcher.py``) uses this map to scatter a merged
    result back into per-request slices: request ``r``'s rows are its keys'
    blocks concatenated in ``r``'s own first-occurrence order — exactly what
    a solo ``predict(r)`` would have returned.
    """
    uniq = out[list(key_names)].drop_duplicates()
    n = len(uniq)
    if n == 0:
        return 0, {}
    T = len(out) // n
    return T, {tuple(row): i for i, row in enumerate(uniq.itertuples(index=False))}


class BatchForecaster:
    """Loads once, predicts every requested series in one compiled call."""

    # predict/predict_quantiles return request-order per-series T-row blocks
    # that are BIT-IDENTICAL across request-size buckets (vectorized along
    # the series axis, no cross-series reductions) — the property the
    # serving coalescer needs to merge concurrent requests and scatter
    # byte-identical slices back.  Composite forecasters (ensemble/
    # bucketed) reorder rows by member family, so they don't set this.
    coalesce_safe = True

    def __init__(
        self,
        model: str,
        config,
        params,
        keys: np.ndarray,
        key_names: tuple,
        day0: int,
        day1: int,
        interval_scale: Optional[np.ndarray] = None,
        freq: str = "D",
    ):
        self.model = model
        self.config = config
        self.params = params
        self.keys = np.asarray(keys)
        self.key_names = tuple(key_names)
        self.day0 = int(day0)  # first training period ordinal (day number
        self.day1 = int(day1)  # at the default daily cadence); see freq
        # grid cadence ("D"/"W"/"M") — horizons are in STEPS of it and ds
        # columns render as its period-start timestamps
        self.freq = str(freq)
        # (S,) per-series conformal band scale (engine/calibrate) — applied
        # multiplicatively to both half-bands at predict time; None = the
        # model's parametric bands ship as-is
        self.interval_scale = (
            None if interval_scale is None
            else np.asarray(interval_scale, dtype=np.float32)
        )
        if self.interval_scale is not None and (
            self.interval_scale.shape != (self.keys.shape[0],)
        ):
            raise ValueError(
                f"interval_scale must be ({self.keys.shape[0]},) — one scale "
                f"per trained series — got {self.interval_scale.shape}"
            )
        self._index = {tuple(k): i for i, k in enumerate(self.keys.tolist())}
        # optional device mesh (enable_mesh): predict shards the series axis
        self._mesh = None
        # streaming state swap (serving/ingest): _state_lock makes the
        # (params, day1) pair one atomic unit — a predict must never pair a
        # pre-update day1 with post-update params or vice versa.  Held only
        # for the reference swap/snapshot, never across device work or I/O.
        self._state_lock = threading.Lock()
        # generation-numbered state epochs: every swap_state bumps this
        # counter under _state_lock, so a consumer that tags derived data
        # (the materialized forecast cache) with the generation it read can
        # later tell "still the state I computed from" apart from "a writer
        # installed something newer" without comparing pytrees.  Listeners
        # registered via register_state_listener are invoked AFTER the swap,
        # outside the lock (they may predict / take their own locks).
        self._state_gen = 0
        self._state_listeners: list = []
        # time-grid bucket (engine/state_store sets this when streaming is
        # attached): the forecast grid end is padded up to the next multiple
        # of this many days so the per-apply day1 advance reuses O(T/B)
        # compiled shapes instead of one per day; 1 = exact grid (default,
        # every non-streaming forecaster).  Per-day forecast values of the
        # scan families are invariant to trailing grid padding (the padded
        # rows are computed then trimmed before include_history logic).
        self.time_bucket = 1
        # dftsan (no-op unless DFTPU_TSAN armed): the atomic state unit plus
        # the generation counter and listener table swap_state mutates
        sanitizer.attach(self, cls=BatchForecaster, guards={
            "_state_lock": ("params", "day1", "_state_gen",
                            "_state_listeners")})

    # -- construction -------------------------------------------------------
    @classmethod
    def from_fit(cls, batch, params, model: str, config,
                 interval_scale=None) -> "BatchForecaster":
        # one host pull for both grid endpoints (meta needs python ints)
        day0, day1 = np.asarray(batch.day[jnp.asarray([0, -1])]).tolist()
        return cls(
            model=model,
            config=config,
            params=params,
            keys=batch.keys,
            key_names=batch.key_names,
            day0=day0,
            day1=day1,
            interval_scale=interval_scale,
            freq=batch.freq,
        )

    # -- persistence --------------------------------------------------------
    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        # one consistent (params, day1) unit: a save racing a streaming
        # apply must not persist post-update params with a pre-update day1
        params, day1 = self._state_snapshot()
        params_type = save_params_npz(
            os.path.join(directory, _PARAMS_FILE), params
        )
        scale_path = os.path.join(directory, _SCALE_FILE)
        if self.interval_scale is not None:
            # own file, not meta JSON: (S,) floats would bloat the meta at
            # the 50k-artifact scale
            np.save(scale_path, self.interval_scale)
        elif os.path.exists(scale_path):
            # re-saving an uncalibrated forecaster into a reused directory
            # must not resurrect a previous run's scales (load() keys on
            # the file's existence)
            os.remove(scale_path)
        meta = {
            "params_type": params_type,
            "model": self.model,
            "config": dataclasses.asdict(self.config),
            "key_names": list(self.key_names),
            "keys": self.keys.tolist(),
            "day0": self.day0,
            "day1": day1,
            "freq": self.freq,
            "serving_schema": self.serving_schema,
        }
        with open(os.path.join(directory, _META_FILE), "w") as f:
            # dataclasses.asdict does not recurse into FrozenMap (a Mapping,
            # not a dict): dict-valued config fields serialize here, and
            # load() re-freezes them
            json.dump(meta, f, indent=2, default=_to_jsonable)

    @classmethod
    def load(cls, directory: str) -> "BatchForecaster":
        with open(os.path.join(directory, _META_FILE)) as f:
            meta = json.load(f)
        params = load_params_npz(
            os.path.join(directory, _PARAMS_FILE), meta["params_type"]
        )
        fns = get_model(meta["model"])
        config = fns.config_cls(
            **{k: _freeze(v) for k, v in meta["config"].items()}
        )
        scale_path = os.path.join(directory, _SCALE_FILE)
        interval_scale = np.load(scale_path) if os.path.exists(scale_path) else None
        return cls(
            model=meta["model"],
            config=config,
            params=params,
            keys=np.asarray(meta["keys"], dtype=np.int64),
            key_names=tuple(meta["key_names"]),
            day0=meta["day0"],
            day1=meta["day1"],
            interval_scale=interval_scale,
            freq=meta.get("freq", "D"),  # pre-cadence artifacts are daily
        )

    # -- mesh-parallel predict ----------------------------------------------
    @property
    def mesh(self):
        """The device mesh predict shards over, or None (single-device)."""
        return self._mesh

    def enable_mesh(self, mesh) -> None:
        """Shard every predict's series axis over ``mesh``.

        One ``/invocations`` dispatch then runs SPMD over the mesh: request
        buckets are padded up to mesh multiples (``_bucket``), the gathered
        params/scale/xreg are placed with ``NamedSharding(P("series"))``
        (``parallel.shard_forecast_inputs``), and XLA's partitioner splits
        the same jitted forecast across devices with zero cross-chip traffic.
        Output is byte-identical to single-device predict — forecasts are
        per-series independent, so partitioning changes placement, not math.
        Warmup routes through the same bucketing, so a warmed ladder covers
        exactly the sharded shapes live traffic will hit.
        """
        n = int(mesh.devices.size)
        if n < 1:
            raise ValueError("mesh has no devices")
        self._mesh = mesh  # dflint: disable=unlocked-shared-state — deploy-time toggle, flipped before traffic is admitted

    def disable_mesh(self) -> None:
        """Back to single-device predict (mesh-size-1 buckets)."""
        self._mesh = None  # dflint: disable=unlocked-shared-state — deploy-time toggle, flipped before traffic is admitted

    def _aot_entry(self, kind: str) -> str:
        """AOT-store entry name for this forecaster's predict programs.

        The mesh size rides the entry name (``@mesh4``): executables are
        compiled against sharded inputs, and the store fingerprint does not
        hash input shardings — distinct entries keep a warm store valid
        across mesh-shape changes (single-device and every mesh size
        coexist instead of colliding on one key).
        """
        entry = f"{kind}:{self.model}"
        if self._mesh is not None:
            entry += f"@mesh{int(self._mesh.devices.size)}"
        return entry

    # -- inference ----------------------------------------------------------
    @property
    def family(self) -> str:
        """Registry model_family tag — uniform accessor across the four
        serving classes so DeployTask never duck-types artifact kinds."""
        return self.model

    @property
    def serving_schema(self) -> str:
        """The schema string the reference stores as a model-version tag
        (``03_deploy.py:44-58``) — single source for artifact meta and the
        /schema endpoint."""
        return (
            "ds date, "
            + ", ".join(f"{k} int" for k in self.key_names)
            + ", yhat double, yhat_upper double, yhat_lower double"
        )

    def series_indices(
        self, request: pd.DataFrame, on_missing: str = "raise"
    ) -> np.ndarray:
        if on_missing not in ("raise", "skip"):
            # a typo like "Raise" must not silently become skip-and-drop
            raise ValueError(
                f"on_missing must be 'raise' or 'skip', got {on_missing!r}"
            )
        # hot path for every read (dispatch AND cache hit): plain numpy
        # column pulls + a first-occurrence dedup set — semantically the
        # old drop_duplicates().astype(int64).itertuples() pipeline, minus
        # ~1ms of pandas machinery per request
        cols = [np.asarray(request[name].to_numpy()) for name in self.key_names]
        n = len(request)
        idx = []
        seen = set()
        for i in range(n):
            key = tuple(int(c[i]) for c in cols)
            if key in seen:
                continue
            seen.add(key)
            if key in self._index:
                idx.append(self._index[key])
            elif on_missing == "raise":
                raise UnknownSeriesError(
                    f"series {dict(zip(self.key_names, key))} was not in the "
                    f"training set ({len(self._index)} known series)"
                )
            # on_missing == 'skip': drop silently
        return np.asarray(idx, dtype=np.int64)

    def swap_state(self, params=None, day1: Optional[int] = None) -> None:
        """Atomically install updated filter state — the streaming ingest /
        background-refit commit point.  ``params`` (when given) must be the
        same pytree structure as the current params; ``day1`` advances the
        last-observed day the forecast grid ends at.  Concurrent predicts
        either see the whole old state or the whole new one, never a mix
        (:meth:`_state_snapshot`).

        Every install bumps the state generation and then notifies the
        registered listeners OUTSIDE the lock — ALL serving write paths
        (streaming apply, full-refit install, windowed tail-refit, the
        day1-only grid advance, autoprep re-levels riding a refit) funnel
        through this one method, which is what makes it the single
        invalidation choke point the forecast cache hangs off.
        """
        with self._state_lock:
            if params is not None:
                self.params = params
            if day1 is not None:
                self.day1 = int(day1)
            self._state_gen += 1
            listeners = tuple(self._state_listeners)
        for fn in listeners:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a cache hiccup must not fail the write
                import logging

                logging.getLogger("BatchForecaster").exception(
                    "state listener failed (state swap itself committed)")

    def register_state_listener(self, fn) -> None:
        """Subscribe ``fn()`` to state installs (see :meth:`swap_state`).

        Called after every committed swap, outside ``_state_lock``, on the
        WRITER's thread — listeners may predict, persist, or take their own
        locks, but must never raise expectations back into the writer."""
        with self._state_lock:
            self._state_listeners.append(fn)

    def state_generation(self) -> int:
        """Monotonic install counter — the epoch number derived-data caches
        tag their frames with (0 until the first :meth:`swap_state`)."""
        with self._state_lock:
            return self._state_gen

    def _state_snapshot(self):
        """(params, day1) as one consistent unit; see :meth:`swap_state`."""
        with self._state_lock:
            return self.params, self.day1

    def _state_snapshot_versioned(self):
        """(params, day1, generation) as one consistent unit — the cache's
        read form: the returned generation is exactly the epoch the pair
        belongs to, so derived frames can be tagged without a race between
        snapshotting state and reading the counter."""
        with self._state_lock:
            return self.params, self.day1, self._state_gen

    def gather_params(self, sidx: np.ndarray, params=None):
        """Row-gather the requested series out of the param pytree.

        Leaves whose leading axis is the series axis (shape[0] == S) are
        indexed down to the request; scalars and global leaves pass through.
        This is what makes ``predict`` cost O(k) for a k-series request
        instead of O(S_trained) — the scale regime (50k-series artifacts,
        BASELINE #4) where forecasting everything and row-selecting after
        would reintroduce the reference's serve-everything cost profile.
        ``params`` overrides the live pytree (the request path passes its
        own snapshot so a concurrent swap cannot tear a request).
        """
        S = self.keys.shape[0]
        take = jnp.asarray(sidx)
        if params is None:
            params, _ = self._state_snapshot()

        def g(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.ndim >= 1 and leaf.shape[0] == S:
                return leaf[take]
            return leaf

        return jax.tree_util.tree_map(g, params)

    def _prepare_request(self, request, horizon, on_missing, xreg):
        """Shared predict prologue: resolve series, bucket the request size,
        gather params, validate/gather xreg.

        ALWAYS forecasts over the full history+future grid (callers trim):
        the model forecast contract (see arima._forecast_impl) sizes its
        static forecast-path length as T_all - T_fit for grids longer than
        the fit grid, which is only exact when such grids start at day0; the
        history part is a cheap gather, so the full grid costs almost
        nothing and keeps every request pattern exact.  The request size is
        bucketed to the next pow2x3 ladder value (capped at S) so a serving
        process sees O(log S) compiled shapes; padding rows repeat sidx[0]
        and are dropped by the caller.

        Returns ``(sidx, params, day_all, fc_kwargs, scale, t_end, n_real)``:
        ``(params, t_end)`` are one atomic state snapshot (a concurrent
        streaming swap cannot tear the pair), and ``n_real`` is the count
        of grid rows the caller keeps — with ``time_bucket > 1`` the grid
        end is padded up to the next bucket multiple so streaming day1
        advances reuse compiled shapes, and the trailing padded rows are
        trimmed (before any include_history logic) rather than served.
        """
        sidx = self.series_indices(request, on_missing=on_missing)
        if sidx.size == 0:
            return sidx, None, None, None, None, None, 0
        params_snap, day1_snap = self._state_snapshot()
        span = day1_snap - self.day0 + 1
        if self.time_bucket > 1:
            b = int(self.time_bucket)
            span = ((span + b - 1) // b) * b
        day_all = jnp.arange(
            self.day0, self.day0 + span + horizon, dtype=jnp.int32
        )
        n_real = day1_snap - self.day0 + horizon + 1
        k = int(sidx.size)
        bucket = self._bucket(k)
        padded = np.concatenate([sidx, np.full(bucket - k, sidx[0], sidx.dtype)])
        params = self.gather_params(padded, params=params_snap)
        scale = (
            None if self.interval_scale is None
            else jnp.asarray(self.interval_scale[padded])
        )
        fc_kwargs = {}
        if xreg is not None:
            fns = get_model(self.model)
            if not fns.supports_xreg:
                raise ValueError(
                    f"model {self.model!r} does not accept exogenous "
                    f"regressors"
                )
            xreg = jnp.asarray(xreg, jnp.float32)
            if xreg.ndim not in (2, 3):
                raise ValueError(
                    f"xreg must be (T_all, R) or (S_trained, T_all, R), got "
                    f"{xreg.ndim}-D"
                )
            T_grid = int(day_all.shape[0])
            if xreg.shape[-2] == n_real and n_real != T_grid:
                # time-bucketed grid: callers supply regressors for the REAL
                # day0..day1+horizon rows; the padded tail rows are trimmed
                # from the output, so zero rows are never served
                widths = ([(0, 0)] * (xreg.ndim - 2)
                          + [(0, T_grid - n_real), (0, 0)])
                xreg = jnp.pad(xreg, widths)
            elif xreg.shape[-2] != T_grid:
                raise ValueError(
                    f"xreg time axis is {xreg.shape[-2]}, expected the full "
                    f"history+horizon grid {n_real}"
                )
            if xreg.ndim == 3:
                # the row gather below clamps out-of-bounds indices silently
                # (JAX gather semantics) — a wrong leading dim would serve
                # the wrong series' covariates, so check it explicitly
                S = self.keys.shape[0]
                if xreg.shape[0] != S:
                    raise ValueError(
                        f"per-series xreg leads with {xreg.shape[0]} rows, "
                        f"expected all {S} trained series (rows are gathered "
                        f"down to the request internally)"
                    )
                xreg = xreg[jnp.asarray(padded)]
            fc_kwargs["xreg"] = xreg
        if self._mesh is not None:
            from distributed_forecasting_tpu.parallel.sharded import (
                shard_forecast_inputs,
            )

            params, day_all, scale, fc_kwargs = shard_forecast_inputs(
                params, day_all, scale, fc_kwargs, self._mesh, bucket
            )
        return sidx, params, day_all, fc_kwargs, scale, day1_snap, n_real

    def _frame_skeleton(self, sidx, day_all):
        """ds + key columns for a long result frame over ``day_all`` —
        shared by predict and predict_quantiles so the date/key assembly
        cannot drift between them."""
        from distributed_forecasting_tpu.data.tensorize import ordinals_to_dates

        T = day_all.shape[0]
        dates = ordinals_to_dates(np.asarray(day_all, dtype="int64"),
                                  self.freq)
        frame = {"ds": np.tile(dates.values, len(sidx))}
        for j, name in enumerate(self.key_names):
            frame[name] = np.repeat(self.keys[sidx, j], T)
        return frame

    @property
    def n_series(self) -> int:
        """Trained-series count — uniform accessor across BatchForecaster /
        MultiModelForecaster / BucketedForecaster (the serve task and the
        /health endpoint must not reach for `.keys`, which the bucketed
        composite does not have)."""
        return int(self.keys.shape[0])

    def _bucket(self, k: int) -> int:
        """Request-size bucket: next pow2x3 ladder value, capped at S.

        The ONE bucketing policy — shared by the live request path
        (`_prepare_request`) and `warmup`, so startup always compiles
        exactly the shapes production requests will hit.  The ladder
        interleaves 3·2^i rungs between the powers of two
        (:func:`_ladder_value`) to cap pad-row waste at ~29% instead of
        pow2's ~47%.  With a mesh enabled the bucket additionally rounds
        up to a mesh multiple so every device gets an identical static
        shard (the padding rows repeat sidx[0] like any other bucket
        padding).
        """
        S = self.keys.shape[0]
        bucket = min(_ladder_value(k), S)
        bucket = max(bucket, k)  # k == S but S not on the ladder
        if self._mesh is not None:
            n = int(self._mesh.devices.size)
            bucket = ((bucket + n - 1) // n) * n
        return bucket

    def warmup(self, horizon: int = 90, sizes=(1,)) -> int:
        """Precompile the predict path for the given request-size buckets.

        A long-lived scorer compiles one program per (bucket, horizon)
        shape; without warmup the FIRST request of each bucket size pays
        that compile (~seconds, 20-40 s on a cold TPU) inside its latency.
        Runs one throwaway predict per distinct bucket so production
        requests hit the cache.  Covered: `predict` at this horizon, the
        listed sizes, shared-covariate models (warmed with a zero (T_all,
        R) calendar).  NOT covered — first use still compiles: other
        horizons, `predict_quantiles` (one program per quantile tuple),
        per-series (S, T_all, R) covariate requests.  Returns the number
        of distinct buckets compiled.

        Sizes beyond the trained-series count clamp to S (a serve conf
        sized for a big artifact must not make a small one compile — and
        report — phantom buckets).

        With a compile cache configured (engine/compile_cache), each
        bucket's program is loaded from the AOT store when present instead
        of compiled; ``self.last_warmup_from_store`` records how many of
        the warmed buckets came from disk (the serve task logs it).
        """
        from distributed_forecasting_tpu.engine.compile_cache import (
            cache_stats,
        )

        S = self.keys.shape[0]
        buckets = sorted({
            self._bucket(min(max(int(k), 1), S)) for k in sizes
        })
        xreg = None
        R = getattr(self.config, "n_regressors", 0)
        if R:
            _, day1 = self._state_snapshot()
            T_all = day1 - self.day0 + horizon + 1
            xreg = jnp.zeros((T_all, R), jnp.float32)
        hits0 = cache_stats()["hits"]
        for b in buckets:
            req = pd.DataFrame(self.keys[:b], columns=self.key_names)
            self.predict(req, horizon=horizon, xreg=xreg)
        self.last_warmup_from_store = int(cache_stats()["hits"] - hits0)  # dflint: disable=unlocked-shared-state — warmup stat, written at boot before concurrent traffic
        return len(buckets)

    def predict(
        self,
        request: pd.DataFrame,
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """Forecast every requested (store, item) ``horizon`` days past the
        end of training.  ``request`` needs the key columns only (extra
        columns — e.g. the history the reference ships to its UDF — are
        ignored; the fitted params already encode history).

        ``xreg``: future-covering exogenous regressor values when the model
        was fit with ``n_regressors > 0`` — (T_all, R) shared or
        (S_trained, T_all, R) per-series over the FULL day0..day1+horizon
        grid (per-series rows are gathered down to the request)."""
        (sidx, params, day_all, fc_kwargs, scale, t_end,
         n_real) = self._prepare_request(request, horizon, on_missing, xreg)
        if sidx.size == 0:
            return pd.DataFrame(
                columns=["ds", *self.key_names, "yhat", "yhat_upper", "yhat_lower"]
            )
        fns = get_model(self.model)
        k = int(sidx.size)
        # the bucket-ladder predict is an AOT-store entrypoint
        # (engine/compile_cache): with a warm store, warmup() and the first
        # live request of each bucket load the per-(family, config, bucket)
        # executable from disk instead of trace+compiling it.  Families
        # whose forecast is a plain wrapper (arima) bypass to jit inside
        # aot_call and still get the persistent XLA cache.
        # NOT donated: the kernel round measured donation of the gathered
        # params across all families — XLA finds zero usable aliases here
        # (every forecast output is (bucket, T_all), matching no param
        # leaf's shape), so donating would invalidate request buffers and
        # warn per compile for no copy saved.  Donation lives where it
        # pays: ops/update.apply_update and the refit fit dispatch.
        from distributed_forecasting_tpu.engine.compile_cache import aot_call

        entry = self._aot_entry("serving_predict")
        with get_tracer().span(
            "serving.predict", model=self.model, k=k,
            bucket=self._bucket(k), horizon=int(horizon),
        ) as span:
            # device-time attribution (monitoring/cost.py): the interval
            # from dispatch through the np.asarray host pulls below, on the
            # span clock — what this request cost in device-seconds
            t_disp = trace_clock()
            # the annotation stamps this dispatch onto the device timeline
            # of a profiler capture, keyed like the AOT entry
            with device_annotation(entry):
                yhat, lo, hi = aot_call(
                    entry, fns.forecast,
                    args=(params, day_all, jnp.float32(t_end)),
                    static_kwargs={"config": self.config},
                    dynamic_kwargs={"key": key, **fc_kwargs},
                )
            if n_real < int(day_all.shape[0]):
                # drop the time-bucket padding rows BEFORE the history trim
                # so [-horizon:] lands on the real last training day
                day_all = day_all[:n_real]
                yhat, lo, hi = (yhat[:, :n_real], lo[:, :n_real],
                                hi[:, :n_real])
            if scale is not None:
                from distributed_forecasting_tpu.engine.calibrate import (
                    apply_interval_scale,
                )

                yhat, lo, hi = apply_interval_scale(yhat, lo, hi, scale,
                                                    floor=fns.band_floor)
            if not include_history:
                day_all = day_all[-horizon:]
                yhat, lo, hi = (yhat[:, -horizon:], lo[:, -horizon:],
                                hi[:, -horizon:])
            frame = self._frame_skeleton(sidx, day_all)
            # the np.asarray pulls are the host sync: they sit inside the
            # span so device wait shows up as serving.predict time
            frame["yhat"] = np.asarray(yhat)[:k].reshape(-1)
            frame["yhat_upper"] = np.asarray(hi)[:k].reshape(-1)
            frame["yhat_lower"] = np.asarray(lo)[:k].reshape(-1)
            dev = trace_clock() - t_disp
            span.set_attribute("device_seconds", dev)
            cm = cost_metrics()
            cm.record_dispatch(entry, self.model, dev)
            bucket = self._bucket(k)
            cm.record_padding(entry, bucket, bucket - k)
            return pd.DataFrame(frame)

    def predict_quantiles(
        self,
        request: pd.DataFrame,
        quantiles=(0.1, 0.5, 0.9),
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """Probabilistic forecast: one column per requested quantile level
        (``q0.1``, ``q0.5``, ...), M5-uncertainty style.  Every built-in
        family registers a ``forecast_quantiles`` implementation
        (transform-aware for the curve model, exact Gaussian-band recovery
        for the others — ``models/base.gaussian_quantiles``); levels are
        priced from the same predictive distribution the central interval
        uses."""
        fns = get_model(self.model)
        if fns.forecast_quantiles is None:
            raise ValueError(
                f"model {self.model!r} registered no quantile forecast "
                f"implementation"
            )
        quantiles = tuple(float(q) for q in quantiles)
        (sidx, params, day_all, fc_kwargs, scale, t_end,
         n_real) = self._prepare_request(request, horizon, on_missing, xreg)
        qcols = quantile_columns(quantiles)
        if sidx.size == 0:
            return pd.DataFrame(columns=["ds", *self.key_names, *qcols])
        k = int(sidx.size)
        entry = self._aot_entry("serving_predict_quantiles")
        with get_tracer().span(
            "serving.predict_quantiles", model=self.model, k=k,
            bucket=self._bucket(k), horizon=int(horizon),
            n_quantiles=len(quantiles),
        ) as span:
            # conformal scaling spreads every level around the median, so
            # the median is priced alongside when calibration is on (one
            # extra column in the same compiled program) and dropped if
            # not requested
            priced = quantiles
            if scale is not None and 0.5 not in priced:
                priced = tuple(sorted((*priced, 0.5)))
            t_disp = trace_clock()
            with device_annotation(entry):
                yq = fns.forecast_quantiles(
                    params, day_all, jnp.float32(t_end), self.config,
                    priced, key, **fc_kwargs,
                )  # (bucket, Q, T_all)
            if n_real < int(day_all.shape[0]):
                day_all = day_all[:n_real]
                yq = yq[:, :, :n_real]
            if scale is not None:
                med = yq[:, priced.index(0.5), :][:, None, :]
                yq = med + scale[:, None, None] * (yq - med)
                if fns.band_floor is not None:
                    # re-apply the family's hard clamp (gaussian_quantiles
                    # floors the raw levels; widening must not undo it)
                    yq = jnp.maximum(yq, fns.band_floor)
            if priced != quantiles:
                keep = jnp.asarray([priced.index(q) for q in quantiles])
                yq = yq[:, keep, :]
            if not include_history:
                day_all = day_all[-horizon:]
                yq = yq[:, :, -horizon:]
            yq = np.asarray(yq)[:k]
            dev = trace_clock() - t_disp
            span.set_attribute("device_seconds", dev)
            cm = cost_metrics()
            cm.record_dispatch(entry, self.model, dev)
            bucket = self._bucket(k)
            cm.record_padding(entry, bucket, bucket - k)
            frame = self._frame_skeleton(sidx, day_all)
            for qi, col in enumerate(qcols):
                frame[col] = yq[:, qi, :].reshape(-1)
            return pd.DataFrame(frame)
