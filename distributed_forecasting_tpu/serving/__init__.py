from distributed_forecasting_tpu.serving.predictor import BatchForecaster

__all__ = ["BatchForecaster"]
