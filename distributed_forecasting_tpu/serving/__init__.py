from distributed_forecasting_tpu.serving.predictor import BatchForecaster
from distributed_forecasting_tpu.serving.batcher import (
    BatchingConfig,
    QueueFullError,
    RequestBatcher,
    ServingMetrics,
    ShuttingDownError,
)
from distributed_forecasting_tpu.serving.bucketed import BucketedForecaster
from distributed_forecasting_tpu.serving.dataplane import (
    ConnectionPool,
    HttpConfig,
    PooledHTTPServer,
)
from distributed_forecasting_tpu.serving.ensemble import (
    BlendedForecaster,
    MultiModelForecaster,
)
from distributed_forecasting_tpu.serving.forecast_cache import (
    CacheConfig,
    ForecastCache,
    build_forecast_cache,
)
from distributed_forecasting_tpu.serving.fleet import (
    FleetConfig,
    FleetSupervisor,
    FrontDoorServer,
    aggregate_prometheus,
    start_fleet,
)
from distributed_forecasting_tpu.serving.server import (
    ForecastServer,
    load_forecaster,
    resolve_from_registry,
    serve,
    start_server,
)

__all__ = [
    "BatchForecaster",
    "BatchingConfig",
    "BucketedForecaster",
    "MultiModelForecaster",
    "BlendedForecaster",
    "CacheConfig",
    "ConnectionPool",
    "FleetConfig",
    "FleetSupervisor",
    "ForecastCache",
    "ForecastServer",
    "FrontDoorServer",
    "HttpConfig",
    "PooledHTTPServer",
    "QueueFullError",
    "RequestBatcher",
    "ServingMetrics",
    "ShuttingDownError",
    "aggregate_prometheus",
    "build_forecast_cache",
    "load_forecaster",
    "resolve_from_registry",
    "serve",
    "start_fleet",
    "start_server",
]
