from distributed_forecasting_tpu.serving.predictor import BatchForecaster
from distributed_forecasting_tpu.serving.batcher import (
    BatchingConfig,
    QueueFullError,
    RequestBatcher,
    ServingMetrics,
    ShuttingDownError,
)
from distributed_forecasting_tpu.serving.bucketed import BucketedForecaster
from distributed_forecasting_tpu.serving.ensemble import (
    BlendedForecaster,
    MultiModelForecaster,
)
from distributed_forecasting_tpu.serving.server import (
    ForecastServer,
    load_forecaster,
    resolve_from_registry,
    serve,
    start_server,
)

__all__ = [
    "BatchForecaster",
    "BatchingConfig",
    "BucketedForecaster",
    "MultiModelForecaster",
    "BlendedForecaster",
    "ForecastServer",
    "QueueFullError",
    "RequestBatcher",
    "ServingMetrics",
    "ShuttingDownError",
    "load_forecaster",
    "resolve_from_registry",
    "serve",
    "start_server",
]
