from distributed_forecasting_tpu.serving.predictor import BatchForecaster
from distributed_forecasting_tpu.serving.ensemble import MultiModelForecaster

__all__ = ["BatchForecaster", "MultiModelForecaster"]
