"""Serving for length-bucketed fits: one BatchForecaster per span bucket.

Companion to ``engine.fit_forecast_bucketed`` the way
``serving.ensemble.MultiModelForecaster`` is the companion to the
cross-family auto-select path: the buckets partition the series key space,
each bucket keeps its own trimmed-grid predictor, and a request is routed
to the buckets owning its keys — one compiled predict per bucket PRESENT in
the request, never per series (the reference anti-pattern,
``notebooks/prophet/model_wrapper.py:57-58``).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.serving.predictor import (
    BatchForecaster,
    UnknownSeriesError,
    quantile_columns,
)

_META_FILE = "buckets.json"


class BucketedForecaster:
    def __init__(self, forecasters: List[BatchForecaster]):
        if not forecasters:
            raise ValueError("need at least one bucket forecaster")
        self.forecasters = list(forecasters)
        self.key_names = self.forecasters[0].key_names
        # host-side key -> bucket routing table; buckets partition the keys
        self._route = {}
        for j, fc in enumerate(self.forecasters):
            for row in np.asarray(fc.keys):
                k = tuple(int(v) for v in row)
                if k in self._route:
                    raise ValueError(f"series key {k} appears in two buckets")
                self._route[k] = j

    @classmethod
    def from_bucketed_fit(cls, buckets, model: str, config=None
                          ) -> "BucketedForecaster":
        """Build from ``engine.fit_forecast_bucketed``'s ``buckets`` output
        (``(indices, sub_batch, params)`` triples)."""
        if config is None:
            from distributed_forecasting_tpu.models.base import get_model

            config = get_model(model).config_cls()
        return cls([
            BatchForecaster.from_fit(sub, params, model, config)
            for _, sub, params in buckets
        ])

    @property
    def n_series(self) -> int:
        return len(self._route)

    @property
    def model(self) -> str:
        """All span buckets share one family (from_bucketed_fit contract) —
        surface it so /health reports the real model, not a placeholder."""
        return self.forecasters[0].model

    @property
    def family(self) -> str:
        return self.model

    @property
    def serving_schema(self) -> str:
        return self.forecasters[0].serving_schema

    # -- persistence --------------------------------------------------------
    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        for j, fc in enumerate(self.forecasters):
            fc.save(os.path.join(directory, f"bucket_{j}"))
        with open(os.path.join(directory, _META_FILE), "w") as f:
            json.dump({"n_buckets": len(self.forecasters)}, f)

    @classmethod
    def load(cls, directory: str) -> "BucketedForecaster":
        with open(os.path.join(directory, _META_FILE)) as f:
            meta = json.load(f)
        return cls([
            BatchForecaster.load(os.path.join(directory, f"bucket_{j}"))
            for j in range(meta["n_buckets"])
        ])

    # -- inference ----------------------------------------------------------
    def _route_request(self, request: pd.DataFrame, on_missing: str, xreg):
        """Shared routing prologue for predict/predict_quantiles: validate
        the request and xreg shape, map keys to buckets.  Returns
        ``{bucket_index: [key tuples]}``.

        ``xreg``: a SHARED (T, R) regressor calendar over the union grid
        ``min(bucket day0) .. day1 + horizon`` when the buckets were fit
        with ``n_regressors > 0``.  Per-series regressor tensors are not
        routable here (buckets partition the key space with no global row
        order) — serve those through the per-bucket ``BatchForecaster``
        directly.
        """
        if xreg is not None and np.asarray(xreg).ndim != 2:
            raise ValueError(
                "BucketedForecaster accepts only a shared (T, R) xreg "
                "calendar; for per-series regressors predict through the "
                "per-bucket BatchForecaster objects"
            )
        if on_missing not in ("raise", "skip"):
            # same guard as BatchForecaster.series_indices: a typo like
            # 'Raise' must not silently become skip-and-drop
            raise ValueError(
                f"on_missing must be 'raise' or 'skip', got {on_missing!r}"
            )
        names = list(self.key_names)
        missing_cols = [c for c in names if c not in request.columns]
        if missing_cols:
            raise KeyError(f"request lacks key column(s) {missing_cols}")
        req_keys = [tuple(int(v) for v in row)
                    for row in request[names].itertuples(index=False)]
        unknown = sorted(set(k for k in req_keys if k not in self._route))
        if unknown and on_missing == "raise":
            raise UnknownSeriesError(
                f"{len(unknown)} requested series not in any bucket "
                f"(first: {unknown[:3]})"
            )
        per_bucket = {}
        for k in req_keys:
            j = self._route.get(k)
            if j is not None:
                per_bucket.setdefault(j, []).append(k)
        return per_bucket

    def _bucket_xreg(self, fc: BatchForecaster, xreg, horizon: int):
        """Slice the union-grid calendar down to one bucket's window."""
        if xreg is None:
            return None
        d0_union = min(f.day0 for f in self.forecasters)
        xr = jnp.asarray(xreg, jnp.float32)
        T_need = fc.day1 + horizon - d0_union + 1
        # exact length required: a longer calendar would be sliced from the
        # wrong origin and silently serve time-shifted covariates
        if xr.shape[0] != T_need:
            raise ValueError(
                f"xreg covers {xr.shape[0]} days, expected exactly the "
                f"union grid of {T_need} days "
                f"(min bucket day0 .. last day + horizon)"
            )
        return xr[fc.day0 - d0_union: fc.day1 + horizon - d0_union + 1]

    def warmup(self, horizon: int = 90, sizes=(1,)) -> int:
        """Precompile every span bucket's predict path (see
        ``BatchForecaster.warmup``).

        Requests route to per-bucket forecasters by key, so a listed size
        may split into any smaller sub-request — warm the full power-of-two
        ladder up to the largest requested size in every member.

        With a warm AOT store (engine/compile_cache) each bucket loads its
        serialized executable from disk instead of compiling, so this call
        drops from seconds per bucket to the deserialize cost.
        """
        from distributed_forecasting_tpu.serving.predictor import _bucket_ladder

        return sum(
            fc.warmup(horizon=horizon, sizes=_bucket_ladder(sizes))
            for fc in self.forecasters
        )

    def predict(
        self,
        request: pd.DataFrame,
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """One batched predict per bucket present in the request (see
        ``_route_request`` for the xreg calendar contract)."""
        per_bucket = self._route_request(request, on_missing, xreg)
        names = list(self.key_names)
        parts = []
        for j in sorted(per_bucket):
            fc = self.forecasters[j]
            sub_req = pd.DataFrame(per_bucket[j], columns=names)
            parts.append(fc.predict(
                sub_req, horizon=horizon, include_history=include_history,
                key=key, xreg=self._bucket_xreg(fc, xreg, horizon),
            ))
        if not parts:
            return pd.DataFrame(
                columns=["ds", *names, "yhat", "yhat_upper", "yhat_lower"]
            )
        return pd.concat(parts, ignore_index=True)

    def predict_quantiles(
        self,
        request: pd.DataFrame,
        quantiles=(0.1, 0.5, 0.9),
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """Per-bucket quantile forwarding (same routing and xreg contract
        as ``predict``)."""
        per_bucket = self._route_request(request, on_missing, xreg)
        names = list(self.key_names)
        parts = []
        for j in sorted(per_bucket):
            fc = self.forecasters[j]
            sub_req = pd.DataFrame(per_bucket[j], columns=names)
            parts.append(fc.predict_quantiles(
                sub_req, quantiles=quantiles, horizon=horizon,
                include_history=include_history, key=key,
                xreg=self._bucket_xreg(fc, xreg, horizon),
            ))
        qcols = quantile_columns(quantiles)
        if not parts:
            return pd.DataFrame(columns=["ds", *names, *qcols])
        return pd.concat(parts, ignore_index=True)
