"""Background full-refit scheduling for the streaming ingest path.

Incremental updates (engine/state_store) keep filter STATE exact, but the
HYPERPARAMETERS (smoothing grid winners, seasonal profile, sigma regime)
stay frozen at fit time — ARIMA_PLUS re-trains for the same reason.  The
:class:`RefitScheduler` watches three signals and, when any fires, runs a
full grid-search refit as a background pipeline experiment through
``engine/executor.TrainingExecutor`` — prep/dispatch on the scheduler
thread, the swap (with replay of points applied mid-fit) on the
executor's writer thread, atomically, under a ``refit.swap`` span:

* **backlog** — points applied incrementally since the last refit
  (``max_applied_points``): the cheap staleness proxy;
* **staleness** — wall seconds since the last refit
  (``max_staleness_s``): bounds hyperparameter age even under a trickle;
* **drift** — the PR-8 quality gauges: when rolling interval coverage
  strays more than ``drift_coverage_tol`` from nominal, the sigma regime
  no longer matches reality and incremental updates cannot fix it.

Serving keeps answering from the last-good state throughout — the swap
is the only moment ingest appliers and the refit contend, and it is a
pure in-memory pointer install.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Dict, Optional

from distributed_forecasting_tpu.engine.executor import (
    PipelineConfig,
    TrainingExecutor,
)
from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.utils import get_logger

# stop()'s drain patience before declaring the scheduler thread stuck
# (module-level so tests can shrink it without a 10s wall stall).
_JOIN_TIMEOUT_S = 10.0


@dataclasses.dataclass(frozen=True)
class RefitConfig:
    """The ``serving.ingest.refit`` conf block."""

    enabled: bool = False
    max_applied_points: int = 5000
    max_staleness_s: float = 3600.0
    check_interval_s: float = 5.0
    drift_coverage_tol: float = 0.15  # |coverage - nominal| trigger; <= 0
                                      # disables the drift signal

    def __post_init__(self):
        if self.max_applied_points < 1:
            raise ValueError("max_applied_points must be >= 1")
        if self.max_staleness_s <= 0:
            raise ValueError("max_staleness_s must be > 0")
        if self.check_interval_s <= 0:
            raise ValueError("check_interval_s must be > 0")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "RefitConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like max_stalenes_s must not silently drop a trigger
            raise ValueError(
                f"unknown serving.ingest.refit conf key(s) "
                f"{sorted(unknown)}; valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


class RefitScheduler:
    """Watches staleness/drift; schedules at most one refit in flight."""

    def __init__(self, store, config: RefitConfig, quality=None,
                 metrics=None):
        self.store = store
        self.config = config
        self.quality = quality
        self.metrics = metrics
        self.logger = get_logger("RefitScheduler")
        # own executor: refits must never queue behind (or hold slots
        # from) a training task's pipeline, and one in flight is plenty
        self._executor = TrainingExecutor(
            config=PipelineConfig(enabled=True, max_in_flight=1,
                                  prefetch_depth=0, async_tracking=False))
        # _lock guards _handle/_refits_done/_last_trigger: the scheduler
        # thread, forced maybe_refit() callers, and wait() all touch them
        self._lock = threading.Lock()
        self._handle = None
        self._submitting = False
        self._refits_done = 0
        self._last_trigger = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- trigger logic -------------------------------------------------------
    def due(self) -> str:
        """The name of the first firing trigger, or "" when fresh."""
        st = self.store.stats()
        if st["applied_since_refit"] >= self.config.max_applied_points:
            return "backlog"
        if st["seconds_since_refit"] >= self.config.max_staleness_s:
            return "staleness"
        if self.config.drift_coverage_tol > 0 and self.quality is not None:
            monitor = getattr(self.quality, "monitor", None)
            if monitor is not None:
                cov = monitor.coverage()
                if (not math.isnan(cov)
                        and abs(cov - monitor.nominal_coverage)
                        > self.config.drift_coverage_tol):
                    return "coverage_drift"
        return ""

    def _reap(self) -> Optional[Dict]:
        """Collect a finished refit handle exactly once.

        The ONLY place ``_handle`` is cleared and ``_refits_done``
        incremented — ``wait()`` and the scheduler loop both funnel
        through here, so a refit a caller waited on is never also counted
        by the loop.  Surfaces stage errors (the handle is cleared first,
        matching the loop's old drop-on-error behavior)."""
        with self._lock:
            handle = self._handle
            if handle is None or not handle.done():
                return None
            self._handle = None
        result = handle.result(timeout=0)
        with self._lock:
            self._refits_done += 1
        return result

    def maybe_refit(self, force: bool = False) -> Optional[str]:
        """Submit a refit if a trigger fired (or ``force``) and none is in
        flight; returns the trigger name when one was submitted."""
        self._reap()
        trigger = "forced" if force else self.due()
        if not trigger:
            return None
        # claim the submission slot under the lock, but run submit()
        # outside it — prep/dispatch execute inline in the caller (history
        # snapshot + the fit dispatch, possibly a compile), far too long
        # to hold _lock across
        with self._lock:
            if self._handle is not None or self._submitting:
                return None
            self._submitting = True
        try:
            # inside the claim/release window: an injected failure exercises
            # the same finally-path a real refit_stages() error would
            failpoint("refit.submit")
            prep, dispatch, complete = self.store.refit_stages()
            handle = self._executor.submit(
                f"refit:{trigger}", prep, dispatch, complete)
            with self._lock:
                self._last_trigger = trigger
                self._handle = handle
        finally:
            with self._lock:
                self._submitting = False
        self.logger.info("refit submitted (trigger=%s)", trigger)
        return trigger

    def wait(self, timeout: Optional[float] = None) -> Optional[Dict]:
        """Block until the in-flight refit (if any) has swapped in."""
        with self._lock:
            handle = self._handle
        if handle is None:
            return None
        result = handle.result(timeout=timeout)
        # _reap() counts it unless the scheduler loop got there first, in
        # which case the result is still the one we waited on
        reaped = self._reap()
        return result if reaped is None else reaped

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if not self.config.enabled or self._thread is not None:
            return
        self._stop.clear()  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
        self._thread = threading.Thread(  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
            target=self._run, name="refit-scheduler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.config.check_interval_s):
            try:
                # maybe_refit reaps first, so stage-C errors surface here
                # instead of silently retrying (a failed handle is cleared
                # by _reap before its result re-raises)
                self.maybe_refit()
            except Exception:
                self.logger.exception("refit cycle failed")

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=_JOIN_TIMEOUT_S)
            if thread.is_alive():
                # a refit dispatch is wedged under _run: the daemon thread
                # leaks past this shutdown — surface it instead of
                # pretending the drain succeeded
                if self.metrics is not None:
                    self.metrics.refit_shutdown_stuck_total.inc()
                self.logger.error(
                    "refit scheduler thread still alive after %.0fs join; "
                    "leaking it (daemon) — shutdown is NOT clean",
                    _JOIN_TIMEOUT_S)
            else:
                self._thread = None  # dflint: disable=unlocked-shared-state — lifecycle field touched only by the owning thread
        self._executor.close()

    def snapshot(self) -> Dict:
        with self._lock:
            in_flight = bool(self._handle is not None
                             and not self._handle.done())
            refits_done = self._refits_done
            last_trigger = self._last_trigger
        return {
            "enabled": self.config.enabled,
            "in_flight": in_flight,
            "refits_done": refits_done,
            "last_trigger": last_trigger,
            "due": self.due(),
        }
