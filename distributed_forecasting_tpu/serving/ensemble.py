"""Mixed-family batched serving: one artifact, per-series winning model.

Companion to ``engine/select.py``: serving-side object that holds one
``BatchForecaster`` per model family plus the per-series assignment vector,
and dispatches each requested series to its winning family — still one
compiled predict call *per family present in the request*, never per series
(the anti-pattern this framework exists to fix, reference
``notebooks/prophet/model_wrapper.py:57-58``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.serving.predictor import (
    BatchForecaster,
    quantile_columns,
)

_META_FILE = "ensemble.json"


class MultiModelForecaster:
    def __init__(
        self,
        forecasters: Dict[str, BatchForecaster],
        assignment: np.ndarray,
    ):
        if not forecasters:
            raise ValueError("need at least one family forecaster")
        self.forecasters = dict(forecasters)
        self.models = tuple(sorted(self.forecasters))
        first = self.forecasters[self.models[0]]
        self.keys = first.keys
        self.key_names = first.key_names
        self.assignment = np.asarray(assignment)
        if self.assignment.shape[0] != self.keys.shape[0]:
            raise ValueError(
                f"assignment covers {self.assignment.shape[0]} series, "
                f"params cover {self.keys.shape[0]}"
            )

    @classmethod
    def from_fit(cls, batch, params_by_family, configs, selection
                 ) -> "MultiModelForecaster":
        """Build from ``engine.fit_forecast_auto`` outputs.  ``configs`` maps
        family name -> config (missing names use the family default).
        ``params_by_family`` holds only families that won >=1 series."""
        from distributed_forecasting_tpu.models.base import get_model

        fcs = {}
        for name, params in params_by_family.items():
            cfg = (configs or {}).get(name) or get_model(name).config_cls()
            fcs[name] = BatchForecaster.from_fit(batch, params, name, cfg)
        # store assignment as family-name indices into self.models (sorted),
        # independent of selection.models ordering
        name_per_series = selection.chosen
        unknown = sorted(set(name_per_series) - set(fcs))
        if unknown:
            raise ValueError(
                f"selection assigns series to famil{'ies' if len(unknown) > 1 else 'y'} "
                f"{unknown} absent from params_by_family (has {sorted(fcs)})"
            )
        order = {n: j for j, n in enumerate(sorted(fcs))}
        assignment = np.asarray([order[n] for n in name_per_series])
        return cls(fcs, assignment)

    @property
    def family(self) -> str:
        return "auto:" + ",".join(self.models)

    @property
    def day0(self) -> int:
        # all members were fit on the SAME batch grid (from_fit contract)
        return self.forecasters[self.models[0]].day0

    @property
    def day1(self) -> int:
        return self.forecasters[self.models[0]].day1

    @property
    def serving_schema(self) -> str:
        """Ensemble output adds the winning-family column to the base schema."""
        return self.forecasters[self.models[0]].serving_schema + ", model string"

    # -- persistence --------------------------------------------------------
    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        for name, fc in self.forecasters.items():
            fc.save(os.path.join(directory, name))
        with open(os.path.join(directory, _META_FILE), "w") as f:
            json.dump(
                {"models": list(self.models),
                 "assignment": self.assignment.tolist()}, f
            )

    @classmethod
    def load(cls, directory: str) -> "MultiModelForecaster":
        with open(os.path.join(directory, _META_FILE)) as f:
            meta = json.load(f)
        fcs = {
            name: BatchForecaster.load(os.path.join(directory, name))
            for name in meta["models"]
        }
        return cls(fcs, np.asarray(meta["assignment"]))

    # -- inference ----------------------------------------------------------
    @property
    def n_series(self) -> int:
        return int(self.keys.shape[0])

    def warmup(self, horizon: int = 90, sizes=(1,)) -> int:
        """Precompile every family's predict path (see
        ``BatchForecaster.warmup``).

        A mixed request splits by per-series assignment, so the member
        sub-request sizes are unpredictable — warm the FULL power-of-two
        ladder up to the largest requested size in every family, which
        covers any split of a listed size.

        With a warm AOT store (engine/compile_cache) each (family, bucket)
        program loads from disk instead of compiling.
        """
        from distributed_forecasting_tpu.serving.predictor import (
            _bucket_ladder,
        )

        return sum(
            self.forecasters[m].warmup(
                horizon=horizon, sizes=_bucket_ladder(sizes)
            )
            for m in self.models
        )

    def predict(
        self,
        request: pd.DataFrame,
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """One batched predict per family present in the request.

        ``xreg`` is forwarded to the families that support exogenous
        regressors (the curve model); raises if no held family does.
        """
        from distributed_forecasting_tpu.models.base import get_model

        if xreg is not None:
            if not any(get_model(n).supports_xreg for n in self.models):
                raise ValueError(
                    f"none of the held families {self.models} accepts "
                    f"exogenous regressors"
                )
        first = self.forecasters[self.models[0]]
        sidx = first.series_indices(request, on_missing=on_missing)
        if sidx.size == 0:
            return pd.DataFrame(
                columns=["ds", *self.key_names, "yhat", "yhat_upper",
                         "yhat_lower", "model"]
            )
        parts = []
        for j, name in enumerate(self.models):
            sub = sidx[self.assignment[sidx] == j]
            if sub.size == 0:
                continue
            req = pd.DataFrame(self.keys[sub], columns=list(self.key_names))
            kw = {}
            if xreg is not None and get_model(name).supports_xreg:
                kw["xreg"] = xreg
            out = self.forecasters[name].predict(
                req, horizon=horizon, include_history=include_history, key=key,
                **kw,
            )
            out["model"] = name
            parts.append(out)
        return pd.concat(parts, ignore_index=True)

    def predict_quantiles(
        self,
        request: pd.DataFrame,
        quantiles=(0.1, 0.5, 0.9),
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        """Per-family quantile forwarding; every series' winning family must
        provide a quantile implementation (else a clear error names it)."""
        from distributed_forecasting_tpu.models.base import get_model

        first = self.forecasters[self.models[0]]
        sidx = first.series_indices(request, on_missing=on_missing)
        qcols = quantile_columns(quantiles)
        if sidx.size == 0:
            return pd.DataFrame(
                columns=["ds", *self.key_names, *qcols, "model"]
            )
        parts = []
        for j, name in enumerate(self.models):
            sub = sidx[self.assignment[sidx] == j]
            if sub.size == 0:
                continue
            fns = get_model(name)
            if fns.forecast_quantiles is None:
                raise ValueError(
                    f"requested series are assigned to family {name!r}, "
                    f"which has no quantile forecast implementation"
                )
            req = pd.DataFrame(self.keys[sub], columns=list(self.key_names))
            kw = {"xreg": xreg} if (xreg is not None and fns.supports_xreg) else {}
            out = self.forecasters[name].predict_quantiles(
                req, quantiles=quantiles, horizon=horizon,
                include_history=include_history, key=key, on_missing=on_missing,
                **kw,
            )
            out["model"] = name
            parts.append(out)
        return pd.concat(parts, ignore_index=True)


_BLEND_META_FILE = "blend.json"
_BLEND_WEIGHTS_FILE = "blend_weights.npy"
_BLEND_SCALE_FILE = "blend_interval_scale.npy"


class BlendedForecaster:
    """Linear-pool serving for ``engine.fit_forecast_blend``: every family
    predicts every requested series and the (S, F) weight matrix combines
    them — point paths as the weighted mean, band half-widths linearly
    (the perfectly-correlated rule; see ``engine/blend``), quantile levels
    as the weighted level-wise pool (exact under location shifts, the
    standard linear-pool approximation otherwise).

    Cost: F batched predicts per request instead of the dispatch
    composite's one-per-family-PRESENT — the price of smooth combination;
    still never per series.
    """

    def __init__(
        self,
        forecasters: Dict[str, BatchForecaster],
        weights: np.ndarray,
        models: Optional[tuple] = None,
        interval_scale: Optional[np.ndarray] = None,
    ):
        if not forecasters:
            raise ValueError("need at least one family forecaster")
        self.forecasters = dict(forecasters)
        # weight COLUMNS follow this order — explicit, never re-sorted
        self.models = tuple(models) if models is not None else tuple(sorted(forecasters))
        if set(self.models) != set(self.forecasters):
            raise ValueError(
                f"models order {self.models} does not cover forecasters "
                f"{sorted(self.forecasters)}"
            )
        first = self.forecasters[self.models[0]]
        self.keys = first.keys
        self.key_names = first.key_names
        self.weights = np.asarray(weights, dtype=np.float32)
        if self.weights.shape != (self.keys.shape[0], len(self.models)):
            raise ValueError(
                f"weights must be ({self.keys.shape[0]}, {len(self.models)}) "
                f"— one row per series, one column per family — got "
                f"{self.weights.shape}"
            )
        # (S,) conformal scale for the POOLED band (engine/blend
        # calibrate=True) — applied after blending, mirroring
        # BatchForecaster.interval_scale
        self.interval_scale = (
            None if interval_scale is None
            else np.asarray(interval_scale, dtype=np.float32)
        )
        if self.interval_scale is not None and (
            self.interval_scale.shape != (self.keys.shape[0],)
        ):
            raise ValueError(
                f"interval_scale must be ({self.keys.shape[0]},), got "
                f"{self.interval_scale.shape}"
            )

    @classmethod
    def from_fit(cls, batch, params_by_family, configs, blend
                 ) -> "BlendedForecaster":
        """Build from ``engine.fit_forecast_blend`` outputs (params for
        EVERY family in ``blend.models``; weight columns follow it)."""
        from distributed_forecasting_tpu.models.base import get_model

        missing = sorted(set(blend.models) - set(params_by_family))
        if missing:
            raise ValueError(
                f"blend weights cover famil{'ies' if len(missing) > 1 else 'y'} "
                f"{missing} absent from params_by_family"
            )
        fcs = {}
        for name in blend.models:
            cfg = (configs or {}).get(name) or get_model(name).config_cls()
            fcs[name] = BatchForecaster.from_fit(
                batch, params_by_family[name], name, cfg
            )
        return cls(fcs, blend.weights, models=blend.models,
                   interval_scale=blend.interval_scale)

    @property
    def family(self) -> str:
        return "blend:" + ",".join(self.models)

    @property
    def day0(self) -> int:
        # all members were fit on the SAME batch grid (from_fit contract)
        return self.forecasters[self.models[0]].day0

    @property
    def day1(self) -> int:
        return self.forecasters[self.models[0]].day1

    @property
    def serving_schema(self) -> str:
        return self.forecasters[self.models[0]].serving_schema

    @property
    def n_series(self) -> int:
        return int(self.keys.shape[0])

    # -- persistence --------------------------------------------------------
    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        for name, fc in self.forecasters.items():
            fc.save(os.path.join(directory, name))
        np.save(os.path.join(directory, _BLEND_WEIGHTS_FILE), self.weights)
        scale_path = os.path.join(directory, _BLEND_SCALE_FILE)
        if self.interval_scale is not None:
            np.save(scale_path, self.interval_scale)
        elif os.path.exists(scale_path):
            os.remove(scale_path)  # never resurrect a stale scale
        with open(os.path.join(directory, _BLEND_META_FILE), "w") as f:
            json.dump({"models": list(self.models)}, f)

    @classmethod
    def load(cls, directory: str) -> "BlendedForecaster":
        with open(os.path.join(directory, _BLEND_META_FILE)) as f:
            meta = json.load(f)
        fcs = {
            name: BatchForecaster.load(os.path.join(directory, name))
            for name in meta["models"]
        }
        weights = np.load(os.path.join(directory, _BLEND_WEIGHTS_FILE))
        scale_path = os.path.join(directory, _BLEND_SCALE_FILE)
        scale = np.load(scale_path) if os.path.exists(scale_path) else None
        return cls(fcs, weights, models=tuple(meta["models"]),
                   interval_scale=scale)

    def warmup(self, horizon: int = 90, sizes=(1,)) -> int:
        """Every family serves every request, so each warms the requested
        sizes directly (no split-ladder needed — see MultiModelForecaster)."""
        return sum(
            self.forecasters[m].warmup(horizon=horizon, sizes=sizes)
            for m in self.models
        )

    # -- inference ----------------------------------------------------------
    def _family_kwargs(self, name, xreg):
        from distributed_forecasting_tpu.models.base import get_model

        if xreg is not None and get_model(name).supports_xreg:
            return {"xreg": xreg}
        return {}

    def predict(
        self,
        request: pd.DataFrame,
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        first = self.forecasters[self.models[0]]
        sidx = first.series_indices(request, on_missing=on_missing)
        if sidx.size == 0:
            return pd.DataFrame(
                columns=["ds", *self.key_names, "yhat", "yhat_upper",
                         "yhat_lower"]
            )
        req = pd.DataFrame(self.keys[sidx], columns=list(self.key_names))
        out = None
        for i, name in enumerate(self.models):
            part = self.forecasters[name].predict(
                req, horizon=horizon, include_history=include_history,
                key=key, **self._family_kwargs(name, xreg),
            )
            # identical request + shared day grid => frames align row-for-row
            T_rows = len(part) // sidx.size
            w = np.repeat(self.weights[sidx, i], T_rows)
            yh = part["yhat"].to_numpy()
            up = w * (part["yhat_upper"].to_numpy() - yh)
            dn = w * (yh - part["yhat_lower"].to_numpy())
            if out is None:
                out = part[["ds", *self.key_names]].copy()
                out["yhat"] = w * yh
                out["_up"], out["_dn"] = up, dn
            else:
                out["yhat"] += w * yh
                out["_up"] += up
                out["_dn"] += dn
        up, dn = out.pop("_up"), out.pop("_dn")
        if self.interval_scale is not None:
            from distributed_forecasting_tpu.engine.blend import (
                blend_band_floor,
            )

            T_rows = len(out) // sidx.size
            sc = np.repeat(self.interval_scale[sidx], T_rows)
            up, dn = sc * up, sc * dn
            floor = blend_band_floor(self.models)
            if floor is not None:
                dn = np.minimum(dn, out["yhat"].to_numpy() - floor)
        out["yhat_upper"] = out["yhat"] + up
        out["yhat_lower"] = out["yhat"] - dn
        return out[["ds", *self.key_names, "yhat", "yhat_upper", "yhat_lower"]]

    def predict_quantiles(
        self,
        request: pd.DataFrame,
        quantiles=(0.1, 0.5, 0.9),
        horizon: int = 90,
        include_history: bool = False,
        key: Optional[jax.Array] = None,
        on_missing: str = "raise",
        xreg=None,
    ) -> pd.DataFrame:
        from distributed_forecasting_tpu.models.base import get_model

        for name in self.models:
            if get_model(name).forecast_quantiles is None:
                raise ValueError(
                    f"family {name!r} has no quantile forecast implementation"
                )
        first = self.forecasters[self.models[0]]
        sidx = first.series_indices(request, on_missing=on_missing)
        qcols = quantile_columns(quantiles)
        if sidx.size == 0:
            return pd.DataFrame(columns=["ds", *self.key_names, *qcols])
        req = pd.DataFrame(self.keys[sidx], columns=list(self.key_names))
        # conformal scaling spreads levels around the pooled median, so it
        # is priced alongside when calibration is on and dropped after
        priced = tuple(quantiles)
        if self.interval_scale is not None and 0.5 not in priced:
            priced = tuple(sorted((*priced, 0.5)))
        pcols = quantile_columns(priced)
        out = None
        for i, name in enumerate(self.models):
            part = self.forecasters[name].predict_quantiles(
                req, quantiles=priced, horizon=horizon,
                include_history=include_history, key=key,
                **self._family_kwargs(name, xreg),
            )
            T_rows = len(part) // sidx.size
            w = np.repeat(self.weights[sidx, i], T_rows)
            if out is None:
                out = part[["ds", *self.key_names]].copy()
                for c in pcols:
                    out[c] = w * part[c].to_numpy()
            else:
                for c in pcols:
                    out[c] += w * part[c].to_numpy()
        if self.interval_scale is not None:
            from distributed_forecasting_tpu.engine.blend import (
                blend_band_floor,
            )

            T_rows = len(out) // sidx.size
            sc = np.repeat(self.interval_scale[sidx], T_rows)
            med = out["q0.5"].to_numpy().copy()
            floor = blend_band_floor(self.models)
            for c in pcols:
                scaled = med + sc * (out[c].to_numpy() - med)
                out[c] = scaled if floor is None else np.maximum(scaled, floor)
        return out[["ds", *self.key_names, *qcols]]
