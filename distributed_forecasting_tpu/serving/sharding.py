"""Series-partitioned fleet: consistent-hash routing + per-shard state.

The reference's whole pitch is per-key fan-out over independent series
(PAPER.md §0: ``groupBy().applyInPandas`` over 500+ models); ARIMA_PLUS
(arXiv:2510.24452) is the existence proof that the product at scale is
millions of multi-tenant series.  Before this module every fleet replica
held the FULL param/filter-state set and followed EVERY tenant's WAL
writes, so per-replica memory and ingest-apply work scaled with total S
regardless of replica count.  This module makes the fleet data-parallel
over series:

    series key ──(stable hash)──► shard ──(HashRing over replicas,
                                           vnodes, replication)──► owners

* **key → shard** is a pure stable hash mod ``num_shards`` — fixed for
  the lifetime of a deployment, so a key's WAL/state namespace
  (``wal_dir/shard-<k>/``) never moves when the replica set changes;
* **shard → replica set** rides a consistent-hash ring over replica
  indices with ``vnodes`` virtual points each: adding one replica to an
  N-replica ring remaps ~1/(N+1) of the shards (and therefore of the
  keys), never reshuffles everything;
* each replica loads ONLY its shards' params/state
  (:func:`subset_for_shards`) and follows ONLY its shards' WAL
  directories (:class:`ShardedWAL`), so resident series per replica is
  ~S * owned_shards / num_shards and tenant A's ingest is never applied
  by a non-owning replica;
* the front door routes single-shard requests straight to an owner and
  scatter-gathers multi-shard ones (:func:`plan_invocations`,
  :func:`merge_invocation_responses` — merge is in key order, partial
  failure degrades to per-key error entries, not a whole-request 5xx);
* per-tenant admission (:class:`TokenBucket`) reuses the batcher's
  429/Retry-After posture at the front door.

AOT executables are deliberately NOT shard-suffixed: compiled programs
are keyed by entry x config x shape bucket (engine/compile_cache), and a
shard subset only changes runtime *data*, so shards whose bucket shapes
coincide share one deserialized program — the shard-distinct shapes
(per-shard S in fit/update entrypoints) already produce distinct store
keys where the program genuinely differs.  State sidecars (history rows,
WAL segments) ARE data and live under shard-suffixed namespaces.

Everything here is hash-deterministic (hashlib, never ``hash()``) so two
processes — or the same process across restarts — always agree on the
routing table without coordination.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from bisect import bisect_right
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.monitoring.monitor import MetricsRegistry
from distributed_forecasting_tpu.serving.ingest import WriteAheadLog


@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """The ``serving.sharding`` conf block (see conf/tasks/serve_config.yml)."""

    enabled: bool = False
    num_shards: int = 8        # fixed key->shard partition count; state
    #                            namespaces are per shard, so changing this
    #                            is a redeploy, not a rebalance
    replication: int = 1       # replicas owning each shard (reads can land
    #                            on any owner; all owners follow the WAL)
    vnodes: int = 64           # virtual ring points per replica: higher =
    #                            smoother shard spread, slower ring build
    quota_rps: float = 0.0     # per-tenant admitted series-rows/s at the
    #                            front door; 0 disables admission control
    quota_burst: float = 0.0   # token-bucket capacity; 0 -> 2 * quota_rps

    def __post_init__(self):
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {self.num_shards}")
        if self.replication < 1:
            raise ValueError(
                f"replication must be >= 1, got {self.replication}")
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {self.vnodes}")
        if self.quota_rps < 0:
            raise ValueError("quota_rps must be >= 0")
        if self.quota_burst < 0:
            raise ValueError("quota_burst must be >= 0")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "ShardingConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like num_shard must not silently serve unpartitioned
            raise ValueError(
                f"unknown serving.sharding conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


# -- deterministic hashing ----------------------------------------------------

def stable_hash(token: str) -> int:
    """64-bit hash that is identical across processes and Python runs —
    ``hash()`` is salted per process and would split the fleet's brain."""
    return int.from_bytes(
        hashlib.md5(token.encode("utf-8")).digest()[:8], "big")


def shard_of_key(key: Sequence[int], num_shards: int) -> int:
    """Series key tuple -> owning shard.  Pure function of the key values
    and the shard count: every replica, the front door, and a WAL replayed
    on a different host all route a key identically."""
    token = "key:" + ",".join(str(int(v)) for v in key)
    return stable_hash(token) % int(num_shards)


class HashRing:
    """Consistent-hash ring over opaque node ids with virtual nodes.

    Immutable once built — rebalance = build a NEW ring and swap it under
    the owner's lock (see FleetSupervisor), never mutate one in place
    under concurrent readers.
    """

    def __init__(self, nodes: Sequence, vnodes: int = 64):
        if not nodes:
            raise ValueError("ring needs at least one node")
        points: List[Tuple[int, object]] = []
        for node in nodes:
            for v in range(int(vnodes)):
                points.append((stable_hash(f"node:{node}:vnode:{v}"), node))
        points.sort(key=lambda p: p[0])
        self._hashes = [h for h, _ in points]
        self._nodes = [n for _, n in points]
        self.size = len(set(nodes))

    def lookup(self, token: str):
        """First node clockwise of the token's hash."""
        i = bisect_right(self._hashes, stable_hash(token)) % len(self._hashes)
        return self._nodes[i]

    def lookup_n(self, token: str, n: int) -> List:
        """``n`` DISTINCT nodes walking clockwise (the replication set)."""
        start = bisect_right(self._hashes, stable_hash(token))
        out: List = []
        for step in range(len(self._hashes)):
            node = self._nodes[(start + step) % len(self._hashes)]
            if node not in out:
                out.append(node)
                if len(out) >= min(int(n), self.size):
                    break
        return out


def compute_assignments(
    config: ShardingConfig, replica_indices: Sequence[int],
) -> Dict[int, List[int]]:
    """shard -> ordered owner replica-index list, deterministic in
    (config, replica set).  The first owner is the shard's primary (ingest
    routes there); the rest are read replicas following the shard WAL."""
    ring = HashRing(list(replica_indices), vnodes=config.vnodes)
    return {
        k: ring.lookup_n(f"shard:{k}", config.replication)
        for k in range(config.num_shards)
    }


# -- per-shard artifact subsetting -------------------------------------------

def shard_indices(keys, shards: Sequence[int], num_shards: int):
    """Row indices of ``keys`` (S, n_key_cols) whose shard is owned."""
    import numpy as np

    owned = set(int(s) for s in shards)
    return np.asarray(
        [i for i, k in enumerate(np.asarray(keys).tolist())
         if shard_of_key(k, num_shards) in owned],
        dtype=np.int64)


def subset_for_shards(forecaster, shards: Sequence[int], num_shards: int):
    """(forecaster restricted to its owned shards, owned row indices).

    Gathers every param leaf whose leading axis is the series axis — the
    same S-leading convention ``BatchForecaster.gather_params`` routes on
    — plus the key table and the per-series conformal scales.  The result
    is a first-class forecaster: predict, warmup, mesh, streaming state
    swap all work on the subset, and its AOT programs share the store with
    any other shard whose bucket shapes coincide.
    """
    import jax.tree_util as jtu
    import numpy as np

    idx = shard_indices(forecaster.keys, shards, num_shards)
    S = int(forecaster.keys.shape[0])
    params, day1 = forecaster._state_snapshot()

    def g(leaf):
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == S:
            return arr[idx]
        return leaf

    sub_params = jtu.tree_map(g, params)
    scale = forecaster.interval_scale
    sub = type(forecaster)(
        model=forecaster.model,
        config=forecaster.config,
        params=sub_params,
        keys=np.asarray(forecaster.keys)[idx],
        key_names=forecaster.key_names,
        day0=forecaster.day0,
        day1=day1,
        interval_scale=None if scale is None else np.asarray(scale)[idx],
        freq=forecaster.freq,
    )
    sub.time_bucket = forecaster.time_bucket
    return sub, idx


# -- per-shard WAL namespaces -------------------------------------------------

class ShardedWAL:
    """``WriteAheadLog`` facade over ``wal_dir/shard-<k>/`` namespaces.

    Duck-types the single-log API the ingest runtime consumes (``append``
    / ``read_new`` / ``stats`` / ``directory``) but keeps one real WAL per
    shard: appends route each record by its key's shard, and the follower
    read covers ONLY the owned shards — a record for tenant A is durable
    in shard(A)'s directory the moment any replica accepts it, and only
    shard(A)'s owners ever replay it into model state.  Rows for shards
    this replica does NOT own still append durably (a mis-routed request
    must never lose a write); they are simply never followed here.
    """

    def __init__(self, directory: str, owned_shards: Sequence[int],
                 num_shards: int, max_segment_bytes: int = 4194304,
                 on_read: Optional[Callable[[int, int], None]] = None):
        self.directory = str(directory)
        self.num_shards = int(num_shards)
        self.owned_shards = tuple(sorted(int(s) for s in owned_shards))
        self.max_segment_bytes = int(max_segment_bytes)
        self._on_read = on_read
        self._lock = threading.Lock()   # lazily opened per-shard WAL map
        self._wals: Dict[int, WriteAheadLog] = {}
        for k in self.owned_shards:     # owned namespaces exist up front
            self._wal(k)

    def shard_dir(self, shard: int) -> str:
        return os.path.join(self.directory, f"shard-{int(shard)}")

    def _wal(self, shard: int) -> WriteAheadLog:
        with self._lock:
            wal = self._wals.get(shard)
            if wal is None:
                wal = WriteAheadLog(
                    self.shard_dir(shard),
                    max_segment_bytes=self.max_segment_bytes)
                self._wals[shard] = wal
            return wal

    def append(self, records: List[Dict]) -> int:
        """Route each record to its shard's log.  Records carry the compact
        WAL shape (``{"k": [...], ...}``) — the shard is a pure function of
        ``k``, so every appender agrees on the namespace."""
        by_shard: Dict[int, List[Dict]] = {}
        for rec in records:
            shard = shard_of_key(rec["k"], self.num_shards)
            by_shard.setdefault(shard, []).append(rec)
        written = 0
        for shard, rows in sorted(by_shard.items()):
            # per-shard-leg site: a mid-loop fault models one shard's disk
            # failing while the earlier shards already acked their rows —
            # exactly the partial-append case replay has to reconcile
            failpoint("wal.shard.append")
            written += self._wal(shard).append(rows)
        return written

    def read_new(self, cursor: Optional[Dict] = None,
                 ) -> Tuple[List[Dict], Dict]:
        """Follower read across the OWNED shards only; the cursor is a
        per-shard map of the underlying segment cursors."""
        cursor = dict(cursor or {})
        records: List[Dict] = []
        for shard in self.owned_shards:
            rows, sub = self._wal(shard).read_new(cursor.get(str(shard)))
            cursor[str(shard)] = sub
            if rows and self._on_read is not None:
                self._on_read(shard, len(rows))
            records.extend(rows)
        return records, cursor

    def stats(self) -> Dict[str, int]:
        total = {"segments": 0, "bytes": 0}
        for shard in self.owned_shards:
            st = self._wal(shard).stats()
            total["segments"] += st["segments"]
            total["bytes"] += st["bytes"]
        return total


# -- per-tenant admission -----------------------------------------------------

class TokenBucket:
    """Per-tenant token buckets: ``allow(tenant, n)`` admits ``n`` series
    rows or answers False (the caller's 429).  Monotonic-clock refill;
    ``time_fn`` is injectable so tests drive the clock by hand."""

    def __init__(self, rate: float, burst: float = 0.0,
                 time_fn: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError("token bucket needs rate > 0")
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else 2.0 * self.rate
        self._time = time_fn
        self._lock = threading.Lock()
        self._state: Dict[str, Tuple[float, float]] = {}  # tenant ->
        #                                                   (tokens, stamp)

    def allow(self, tenant: str, n: float = 1.0) -> bool:
        now = self._time()
        with self._lock:
            tokens, stamp = self._state.get(tenant, (self.burst, now))
            tokens = min(self.burst, tokens + (now - stamp) * self.rate)
            if tokens >= n:
                self._state[tenant] = (tokens - n, now)
                return True
            self._state[tenant] = (tokens, now)
            return False


def tenant_of_input(item: Dict, key_names: Sequence[str]) -> str:
    """Admission key: the series prefix — the FIRST key column's value
    (store/tenant id in the reference's store-item scheme).  Falls back to
    a shared bucket for inputs that don't carry the key columns."""
    name = key_names[0]
    if isinstance(item, dict):
        raw = item.get("keys", item.get("k"))
        if isinstance(raw, dict) and name in raw:
            return str(raw[name])
        if isinstance(raw, (list, tuple)) and raw:
            return str(raw[0])
        if name in item:
            return str(item[name])
    return "_unkeyed"


# -- request planning (front door) -------------------------------------------

def _input_key(item: Dict, key_names: Sequence[str]) -> Optional[Tuple]:
    try:
        raw = item.get("keys", item.get("k"))
        if raw is None:
            raw = {n: item[n] for n in key_names}
        if isinstance(raw, dict):
            return tuple(int(raw[n]) for n in key_names)
        key = tuple(int(v) for v in raw)
        return key if len(key) == len(key_names) else None
    except (KeyError, TypeError, ValueError):
        return None


@dataclasses.dataclass
class RoutePlan:
    """One routed POST: which shards, and the sub-body per shard."""

    field: str                       # "inputs" | "points" | "observations"
    shard_items: Dict[int, List]     # shard -> that shard's items, in order
    shard_keys: Dict[int, List]      # shard -> unique key tuples, in order
    key_order: List[Tuple]           # unique keys in request order
    tenants: Dict[str, int]          # tenant -> charged rows

    @property
    def shards(self) -> List[int]:
        return sorted(self.shard_items)

    def sub_body(self, base: Dict, shard: int) -> Dict:
        out = dict(base)
        out[self.field] = self.shard_items[shard]
        return out


_ROUTED_FIELDS = {
    "/invocations": "inputs",
    "/predict": "inputs",
    "/ingest": "points",
    "/observe": "observations",
    "/detect_anomalies": "points",
}


def plan_request(path: str, body: Dict, key_names: Sequence[str],
                 num_shards: int) -> Optional[RoutePlan]:
    """Parse a routed POST into a per-shard plan, or None when the body is
    not shardable (unknown path, missing key columns, malformed items) —
    the caller then falls back to round-robin over the full fleet."""
    field = _ROUTED_FIELDS.get(path)
    if field is None or not isinstance(body, dict):
        return None
    items = body.get(field)
    if not isinstance(items, list) or not items:
        return None
    shard_items: Dict[int, List] = {}
    shard_keys: Dict[int, List] = {}
    key_order: List[Tuple] = []
    seen = set()
    tenants: Dict[str, int] = {}
    for item in items:
        key = _input_key(item, key_names)
        if key is None:
            return None  # let the replica's own parser shape the error
        shard = shard_of_key(key, num_shards)
        shard_items.setdefault(shard, []).append(item)
        if key not in seen:
            seen.add(key)
            key_order.append(key)
            shard_keys.setdefault(shard, []).append(key)
        tenant = tenant_of_input(item, key_names)
        tenants[tenant] = tenants.get(tenant, 0) + 1
    return RoutePlan(field=field, shard_items=shard_items,
                     shard_keys=shard_keys, key_order=key_order,
                     tenants=tenants)


def merge_invocation_responses(
    plan: RoutePlan,
    key_names: Sequence[str],
    responses: Dict[int, Tuple[int, bytes]],
) -> Tuple[int, Dict]:
    """Scatter-gather merge for ``/invocations``.

    Successful shards' prediction records regroup by key tuple and emerge
    in the ORIGINAL request key order, so the merged body is byte-identical
    to what one unsharded replica answers for the same request (records
    preserve their JSON field order; per-series forecasts are independent
    of batch composition, PR-1's coalescing contract).  A failed shard
    degrades to per-key ``errors`` entries — the other tenants' forecasts
    still ship, which is the whole point of partitioning the fleet.
    Status: 200 unless EVERY shard failed (503, retryable).
    """
    by_key: Dict[Tuple, List] = {}
    n_series = 0
    errors: List[Dict] = []
    key_names = list(key_names)
    for shard, (status, payload) in sorted(responses.items()):
        if status == 200:
            try:
                parsed = json.loads(payload)
            except ValueError:
                parsed = None
            if not isinstance(parsed, dict):
                status, parsed = 502, {"error": "unparseable shard response"}
            else:
                n_series += int(parsed.get("n_series", 0))
                for rec in parsed.get("predictions", []):
                    try:
                        key = tuple(int(rec[n]) for n in key_names)
                    except (KeyError, TypeError, ValueError):
                        continue
                    by_key.setdefault(key, []).append(rec)
                continue
        try:
            detail = json.loads(payload).get("error", "")
        except (ValueError, AttributeError):
            detail = ""
        for key in plan.shard_keys.get(shard, []):
            entry = dict(zip(key_names, (int(v) for v in key)))
            entry["error"] = detail or f"shard {shard} unavailable"
            entry["status"] = int(status)
            entry["shard"] = int(shard)
            errors.append(entry)
    predictions: List = []
    for key in plan.key_order:
        predictions.extend(by_key.get(key, []))
    merged: Dict = {"predictions": predictions, "n_series": n_series}
    if errors:
        merged["errors"] = errors
        merged["n_failed_series"] = len(errors)
    if not any(status == 200 for status, _ in responses.values()):
        return 503, merged
    return 200, merged


def merge_detect_responses(
    plan: RoutePlan,
    key_names: Sequence[str],
    responses: Dict[int, Tuple[int, bytes]],
) -> Tuple[int, Dict]:
    """Scatter-gather merge for ``/detect_anomalies``.

    Same shape as :func:`merge_invocation_responses`: successful shards'
    per-point results regroup by key tuple in the ORIGINAL request key
    order (scores are per-series computations, independent of batch
    composition), summary counts sum, and a failed shard degrades to
    per-key ``errors`` entries while the other shards' verdicts still
    ship.  Status: 200 unless EVERY shard failed (503, retryable)."""
    by_key: Dict[Tuple, List] = {}
    totals = {"n_scored": 0, "n_flagged": 0, "n_skipped": 0}
    threshold = None
    errors: List[Dict] = []
    key_names = list(key_names)
    for shard, (status, payload) in sorted(responses.items()):
        if status == 200:
            try:
                parsed = json.loads(payload)
            except ValueError:
                parsed = None
            if not isinstance(parsed, dict):
                status, parsed = 502, {"error": "unparseable shard response"}
            else:
                for k in totals:
                    totals[k] += int(parsed.get(k, 0))
                if threshold is None:
                    threshold = parsed.get("threshold")
                for rec in parsed.get("results", []):
                    try:
                        key = tuple(int(rec[n]) for n in key_names)
                    except (KeyError, TypeError, ValueError):
                        continue
                    by_key.setdefault(key, []).append(rec)
                continue
        try:
            detail = json.loads(payload).get("error", "")
        except (ValueError, AttributeError):
            detail = ""
        for key in plan.shard_keys.get(shard, []):
            entry = dict(zip(key_names, (int(v) for v in key)))
            entry["error"] = detail or f"shard {shard} unavailable"
            entry["status"] = int(status)
            entry["shard"] = int(shard)
            errors.append(entry)
    results: List = []
    for key in plan.key_order:
        results.extend(by_key.get(key, []))
    merged: Dict = {"results": results, **totals}
    if threshold is not None:
        merged["threshold"] = threshold
    if errors:
        merged["errors"] = errors
    if not any(status == 200 for status, _ in responses.values()):
        return 503, merged
    return 200, merged


def merge_ingest_responses(
    plan: RoutePlan, responses: Dict[int, Tuple[int, bytes]],
) -> Tuple[int, Dict]:
    """Merge per-shard ``/ingest`` acks: numeric fields sum (written /
    unknown_series / malformed / out_of_range and the nested apply
    counts); failed shards report per-shard error entries.  The append is
    durable on every 200 shard even when a sibling shard failed."""
    totals: Dict[str, float] = {}
    applied: Dict[str, float] = {}
    errors: List[Dict] = []
    ok = 0
    for shard, (status, payload) in sorted(responses.items()):
        if status == 200:
            try:
                parsed = json.loads(payload)
            except ValueError:
                parsed = {}
            ok += 1
            for k, v in parsed.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    totals[k] = totals.get(k, 0) + v
                elif k == "applied" and isinstance(v, dict):
                    for ak, av in v.items():
                        if isinstance(av, (int, float)):
                            applied[ak] = applied.get(ak, 0) + av
        else:
            try:
                detail = json.loads(payload).get("error", "")
            except (ValueError, AttributeError):
                detail = ""
            errors.append({"shard": int(shard), "status": int(status),
                           "points": len(plan.shard_items.get(shard, [])),
                           "error": detail or f"shard {shard} unavailable"})
    out: Dict = {k: int(v) if float(v).is_integer() else v
                 for k, v in totals.items()}
    if applied:
        out["applied"] = {k: int(v) if float(v).is_integer() else v
                          for k, v in applied.items()}
    if errors:
        out["errors"] = errors
    return (200 if ok else 503), out


# -- replica-side shard metrics ----------------------------------------------

class ShardMetrics:
    """``dftpu_shard_*`` replica gauges/counters, appended to the serving
    ``GET /metrics`` exposition and fleet-merged TYPE-aware (per-shard
    series gauges MAX-merge across owners — every owner reports the same
    resident count; the ingest counters SUM, and a non-owning replica
    simply never emits a shard's label)."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.series = self.registry.labeled_gauge(
            "dftpu_shard_series", ("shard",),
            "resident series per owned shard on this replica")
        self.resident_series = self.registry.gauge(
            "dftpu_shard_resident_series",
            "total series resident on this replica (~S*owned/num_shards)")
        self.owned_shards = self.registry.gauge(
            "dftpu_shard_owned", "shards this replica owns")
        self.ingest_points = self.registry.labeled_counter(
            "dftpu_shard_ingest_points_total", ("shard",),
            "WAL records this replica consumed per owned shard — only "
            "owners ever increment a shard's label")

    def observe_assignment(self, keys, shards: Sequence[int],
                           num_shards: int) -> None:
        import numpy as np

        keys = np.asarray(keys)
        self.owned_shards.set(len(set(int(s) for s in shards)))
        self.resident_series.set(int(keys.shape[0]))
        counts: Dict[int, int] = {int(s): 0 for s in shards}
        for k in keys.tolist():
            counts[shard_of_key(k, num_shards)] += 1
        for shard, n in sorted(counts.items()):
            self.series.set(n, shard=str(shard))

    def note_wal_read(self, shard: int, n: int) -> None:
        self.ingest_points.inc(n, shard=str(shard))

    def render(self) -> str:
        return self.registry.render_prometheus()
