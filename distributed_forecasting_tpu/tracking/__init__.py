from distributed_forecasting_tpu.tracking.filestore import FileTracker, Run
from distributed_forecasting_tpu.tracking.registry import ModelRegistry, ModelVersion
from distributed_forecasting_tpu.tracking.mlflow_compat import (
    get_registry,
    get_tracker,
    mlflow_available,
)

__all__ = [
    "FileTracker",
    "Run",
    "ModelRegistry",
    "ModelVersion",
    "get_registry",
    "get_tracker",
    "mlflow_available",
]
