from distributed_forecasting_tpu.tracking.filestore import FileTracker, Run
from distributed_forecasting_tpu.tracking.registry import ModelRegistry, ModelVersion

__all__ = ["FileTracker", "Run", "ModelRegistry", "ModelVersion"]
