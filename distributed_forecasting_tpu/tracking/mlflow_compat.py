"""Optional MLflow-backed tracker — same surface as FileTracker.

SURVEY.md §2.2 recommends keeping MLflow as an *optional* client behind the
tracking interface (it is pure-Python and file/sqlite-backed in the
reference's own unit fixture, reference ``tests/unit/conftest.py:56-62``).
mlflow is not part of this runtime image, so the adapter degrades to a clear
ImportError and the factory falls back to the file store; when mlflow IS
installed, runs/params/metrics/artifacts land in a real MLflow tracking
store, interoperable with the reference's tooling.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional

from distributed_forecasting_tpu.tracking.filestore import FileTracker


def mlflow_available() -> bool:
    try:
        import mlflow  # noqa: F401

        return True
    except ImportError:
        return False


def get_tracker(root: str, kind: str = "auto"):
    """Factory: 'file', 'mlflow', or 'auto' (mlflow when importable)."""
    if kind == "file":
        return FileTracker(root)
    if kind == "mlflow" or (kind == "auto" and mlflow_available()):
        return MlflowTracker(root)
    if kind == "auto":
        return FileTracker(root)
    raise ValueError(f"unknown tracker kind {kind!r}")


class MlflowTracker:
    """FileTracker-compatible adapter over the MLflow client API."""

    def __init__(self, root: str):
        try:
            import mlflow
        except ImportError as e:
            raise ImportError(
                "MlflowTracker requires the optional 'mlflow' package; "
                "install it or use FileTracker (tracking kind 'file')"
            ) from e
        self._mlflow = mlflow
        uri = root if "://" in root else f"file://{os.path.abspath(root)}"
        self._client = mlflow.tracking.MlflowClient(tracking_uri=uri)

    # -- experiments --------------------------------------------------------
    def create_experiment(self, name: str) -> str:
        existing = self._client.get_experiment_by_name(name)
        if existing is not None:
            return existing.experiment_id
        return self._client.create_experiment(name)

    def get_experiment_by_name(self, name: str) -> Optional[str]:
        exp = self._client.get_experiment_by_name(name)
        return None if exp is None else exp.experiment_id

    # -- runs ---------------------------------------------------------------
    def start_run(self, experiment_id: str, run_name: Optional[str] = None,
                  tags: Optional[Dict[str, str]] = None):
        run = self._client.create_run(
            experiment_id, run_name=run_name,
            tags={k: str(v) for k, v in (tags or {}).items()},
        )
        return _MlflowRun(self._client, experiment_id, run.info.run_id)

    def get_run(self, experiment_id: str, run_id: str):
        self._client.get_run(run_id)  # raises if missing
        return _MlflowRun(self._client, experiment_id, run_id)

    def search_runs(self, experiment_id: str, run_name: Optional[str] = None,
                    tags: Optional[Dict[str, str]] = None):
        clauses = []
        if run_name is not None:
            clauses.append(f"attributes.run_name = '{run_name}'")
        for k, v in (tags or {}).items():
            clauses.append(f"tags.`{k}` = '{v}'")
        runs = self._client.search_runs(
            [experiment_id], filter_string=" and ".join(clauses)
        )
        return [
            _MlflowRun(self._client, experiment_id, r.info.run_id) for r in runs
        ]


class _MlflowRun:
    def __init__(self, client, experiment_id: str, run_id: str):
        self._client = client
        self.experiment_id = experiment_id
        self.run_id = run_id

    def log_params(self, params: Dict) -> None:
        for k, v in params.items():
            self._client.log_param(self.run_id, k, v)

    def log_metrics(self, metrics: Dict[str, float], step: int = 0) -> None:
        for k, v in metrics.items():
            self._client.log_metric(self.run_id, k, float(v), step=step)

    def set_tags(self, tags: Dict[str, str]) -> None:
        for k, v in tags.items():
            self._client.set_tag(self.run_id, k, str(v))

    def log_artifact(self, local_path: str, name: Optional[str] = None) -> str:
        self._client.log_artifact(self.run_id, local_path)
        return local_path

    def log_artifact_bytes(self, name: str, data: bytes) -> str:
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, os.path.basename(name))
            with open(p, "wb") as f:
                f.write(data)
            self._client.log_artifact(self.run_id, p)
        return name

    def log_table(self, name: str, df) -> str:
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, name)
            df.to_parquet(p, index=False)
            self._client.log_artifact(self.run_id, p)
        return name

    def artifact_path(self, name: str) -> str:
        return self._client.download_artifacts(self.run_id, name)

    def params(self) -> Dict:
        return dict(self._client.get_run(self.run_id).data.params)

    def metrics(self) -> Dict[str, float]:
        return dict(self._client.get_run(self.run_id).data.metrics)

    def meta(self) -> Dict:
        info = self._client.get_run(self.run_id)
        return {
            "run_id": self.run_id,
            "run_name": info.info.run_name,
            "status": info.info.status,
            "tags": dict(info.data.tags),
        }

    def end(self, status: str = "FINISHED") -> None:
        self._client.set_terminated(self.run_id, status=status)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end("FAILED" if exc_type else "FINISHED")
