"""Optional MLflow-backed tracker — same surface as FileTracker.

SURVEY.md §2.2 recommends keeping MLflow as an *optional* client behind the
tracking interface (it is pure-Python and file/sqlite-backed in the
reference's own unit fixture, reference ``tests/unit/conftest.py:56-62``).
mlflow is not part of this runtime image, so the adapter degrades to a clear
ImportError and the factory falls back to the file store; when mlflow IS
installed, runs/params/metrics/artifacts land in a real MLflow tracking
store, interoperable with the reference's tooling.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional

from distributed_forecasting_tpu.tracking.filestore import FileTracker
from distributed_forecasting_tpu.tracking.registry import ModelRegistry, ModelVersion


def mlflow_available() -> bool:
    try:
        import mlflow  # noqa: F401

        return True
    except ImportError:
        return False


def get_tracker(root: str, kind: str = "auto"):
    """Factory: 'file', 'mlflow', or 'auto' (mlflow when importable)."""
    if kind == "file":
        return FileTracker(root)
    if kind == "mlflow" or (kind == "auto" and mlflow_available()):
        return MlflowTracker(root)
    if kind == "auto":
        return FileTracker(root)
    raise ValueError(f"unknown tracker kind {kind!r}")


def get_registry(root: str, kind: str = "auto"):
    """Factory: 'file', 'mlflow', or 'auto' (mlflow when importable)."""
    if kind == "file":
        return ModelRegistry(root)
    if kind == "mlflow" or (kind == "auto" and mlflow_available()):
        return MlflowRegistry(root)
    if kind == "auto":
        return ModelRegistry(root)
    raise ValueError(f"unknown registry kind {kind!r}")


class MlflowTracker:
    """FileTracker-compatible adapter over the MLflow client API."""

    def __init__(self, root: str):
        try:
            import mlflow
        except ImportError as e:
            raise ImportError(
                "MlflowTracker requires the optional 'mlflow' package; "
                "install it or use FileTracker (tracking kind 'file')"
            ) from e
        self._mlflow = mlflow
        uri = root if "://" in root else f"file://{os.path.abspath(root)}"
        self._client = mlflow.tracking.MlflowClient(tracking_uri=uri)

    # -- experiments --------------------------------------------------------
    def create_experiment(self, name: str) -> str:
        existing = self._client.get_experiment_by_name(name)
        if existing is not None:
            return existing.experiment_id
        return self._client.create_experiment(name)

    def get_experiment_by_name(self, name: str) -> Optional[str]:
        exp = self._client.get_experiment_by_name(name)
        return None if exp is None else exp.experiment_id

    # -- runs ---------------------------------------------------------------
    def start_run(self, experiment_id: str, run_name: Optional[str] = None,
                  tags: Optional[Dict[str, str]] = None):
        run = self._client.create_run(
            experiment_id, run_name=run_name,
            tags={k: str(v) for k, v in (tags or {}).items()},
        )
        return _MlflowRun(self._client, experiment_id, run.info.run_id)

    def get_run(self, experiment_id: str, run_id: str):
        self._client.get_run(run_id)  # raises if missing
        return _MlflowRun(self._client, experiment_id, run_id)

    def search_runs(self, experiment_id: str, run_name: Optional[str] = None,
                    tags: Optional[Dict[str, str]] = None):
        clauses = []
        if run_name is not None:
            clauses.append(f"attributes.run_name = '{run_name}'")
        for k, v in (tags or {}).items():
            clauses.append(f"tags.`{k}` = '{v}'")
        runs = self._client.search_runs(
            [experiment_id], filter_string=" and ".join(clauses)
        )
        return [
            _MlflowRun(self._client, experiment_id, r.info.run_id) for r in runs
        ]


# stage-as-tag emulation key for MLflow versions without registry stages
_STAGE_TAG = "dftpu.stage"


class MlflowRegistry:
    """ModelRegistry-compatible adapter over the MLflow *model registry*.

    The other half of SURVEY.md §2.2's "keep MLflow as optional client"
    (VERDICT r1 missing-#1): the reference's deploy/inference loop runs
    through ``mlflow.register_model`` (``notebooks/prophet/03_deploy.py:34-36``),
    model-version tags (``03_deploy.py:44-58``), latest-version resolution
    and stage transitions (``notebooks/prophet/04_inference.py:10-12,72-76``).
    Same method surface and ``ModelVersion`` return type as the file-backed
    ``ModelRegistry``, so tasks/deploy.py and tasks/inference.py work against
    either.
    """

    def __init__(self, root: str):
        try:
            import mlflow
        except ImportError as e:
            raise ImportError(
                "MlflowRegistry requires the optional 'mlflow' package; "
                "install it or use ModelRegistry (registry kind 'file')"
            ) from e
        uri = root if "://" in root else f"sqlite:///{os.path.abspath(root)}"
        self._client = mlflow.tracking.MlflowClient(
            tracking_uri=uri, registry_uri=uri
        )

    def _to_version(self, mv) -> ModelVersion:
        source = mv.source or ""
        if source.startswith("file://"):
            source = source[len("file://"):]
        tags = dict(mv.tags or {})
        # registry stages were removed in MLflow 3.x; fall back to the
        # stage-as-tag emulation transition_stage() writes there.  The
        # legacy API's "nothing set" value is the STRING "None" (truthy!),
        # which must also defer to the tag.
        cur = getattr(mv, "current_stage", None)
        stage = cur if cur not in (None, "", "None") else tags.get(
            _STAGE_TAG, "None"
        )
        return ModelVersion(
            name=mv.name,
            version=int(mv.version),
            stage=stage or "None",
            run_id=mv.run_id,
            tags=tags,
            artifact_dir=source,
            created_at=(mv.creation_timestamp or 0) / 1000.0,
        )

    def register_model(self, name, artifact_dir, run_id=None, tags=None) -> ModelVersion:
        from mlflow.exceptions import MlflowException

        try:
            self._client.create_registered_model(name)
        except MlflowException as e:
            # error_code spelling varies across mlflow versions — attribute,
            # method, or message-only
            code = getattr(e, "error_code", None)
            if callable(code):  # pragma: no cover - version-dependent
                code = code()
            already = (code == "RESOURCE_ALREADY_EXISTS") or (
                code is None and "already exists" in str(e).lower()
            )
            if not already:
                raise  # real registry failure, don't mask it
        mv = self._client.create_model_version(
            name=name,
            source=f"file://{os.path.abspath(artifact_dir)}",
            run_id=run_id,
            tags={k: str(v) for k, v in (tags or {}).items()},
        )
        return self._to_version(mv)

    def get_version(self, name: str, version: int) -> ModelVersion:
        return self._to_version(self._client.get_model_version(name, str(version)))

    def list_versions(self, name: str):
        mvs = self._client.search_model_versions(f"name='{name}'")
        return sorted((self._to_version(m) for m in mvs), key=lambda v: v.version)

    def latest_version(self, name: str, stage: Optional[str] = None) -> ModelVersion:
        versions = self.list_versions(name)
        if stage is not None:
            versions = [v for v in versions if v.stage == stage]
        if not versions:
            raise KeyError(
                f"no versions of model {name}"
                + (f" in stage {stage}" if stage else "")
            )
        return versions[-1]

    def transition_stage(self, name: str, version: int, stage: str) -> ModelVersion:
        # MLflow <3: real registry stages; MLflow 3.x removed them — emulate
        # with a version tag that _to_version reads back as the stage
        transition = getattr(
            self._client, "transition_model_version_stage", None
        )
        if transition is not None:
            try:
                mv = transition(name, str(version), stage=stage)
                return self._to_version(mv)
            except Exception:  # pragma: no cover - deprecated-API removal path
                pass
        self._client.set_model_version_tag(name, str(version), _STAGE_TAG, stage)
        return self.get_version(name, version)

    def set_version_tag(self, name: str, version: int, key: str, value: str) -> None:
        self._client.set_model_version_tag(name, str(version), key, str(value))

    def models(self):
        return sorted(m.name for m in self._client.search_registered_models())

    def archive_version(self, name: str, version: int) -> ModelVersion:
        return self.transition_stage(name, version, "Archived")

    def delete_version(self, name: str, version: int) -> None:
        self._client.delete_model_version(name, str(version))

    def delete_model(self, name: str) -> None:
        for v in self.list_versions(name):
            self.archive_version(name, v.version)
        self._client.delete_registered_model(name)


class _MlflowRun:
    def __init__(self, client, experiment_id: str, run_id: str):
        self._client = client
        self.experiment_id = experiment_id
        self.run_id = run_id

    def log_params(self, params: Dict) -> None:
        for k, v in params.items():
            self._client.log_param(self.run_id, k, v)

    def log_metrics(self, metrics: Dict[str, float], step: int = 0) -> None:
        for k, v in metrics.items():
            self._client.log_metric(self.run_id, k, float(v), step=step)

    def set_tags(self, tags: Dict[str, str]) -> None:
        for k, v in tags.items():
            self._client.set_tag(self.run_id, k, str(v))

    def log_artifact(self, local_path: str, name: Optional[str] = None) -> str:
        self._client.log_artifact(self.run_id, local_path)
        return local_path

    def log_artifact_bytes(self, name: str, data: bytes) -> str:
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, os.path.basename(name))
            with open(p, "wb") as f:
                f.write(data)
            self._client.log_artifact(self.run_id, p)
        return name

    def log_table(self, name: str, df) -> str:
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, name)
            df.to_parquet(p, index=False)
            self._client.log_artifact(self.run_id, p)
        return name

    def artifact_path(self, name: str) -> str:
        return self._client.download_artifacts(self.run_id, name)

    def params(self) -> Dict:
        return dict(self._client.get_run(self.run_id).data.params)

    def metrics(self) -> Dict[str, float]:
        return dict(self._client.get_run(self.run_id).data.metrics)

    def meta(self) -> Dict:
        info = self._client.get_run(self.run_id)
        return {
            "run_id": self.run_id,
            "run_name": info.info.run_name,
            "status": info.info.status,
            "tags": dict(info.data.tags),
        }

    def end(self, status: str = "FINISHED") -> None:
        self._client.set_terminated(self.run_id, status=status)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end("FAILED" if exc_type else "FINISHED")
