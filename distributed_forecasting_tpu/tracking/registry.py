"""Model registry — versions, stages, tags; the MLflow-registry stand-in.

Reference usage being reproduced: ``mlflow.register_model(model_uri,
"ForecastingModelUDF")`` after deploy (``notebooks/prophet/03_deploy.py:34-36``),
model-version tags carrying serving metadata incl. the schema string
(``03_deploy.py:44-58``), latest-version resolution at inference time
(``notebooks/prophet/04_inference.py:10-12``), and stage transitions
None -> Staging (``04_inference.py:66-76``).

Versions point at an artifact directory (typically a run's artifacts) by
copy, so a registered model is immutable even if the run is deleted.

Layout::

    root/models/<name>/meta.json            # next_version, description
    root/models/<name>/v<version>/meta.json # stage, tags, source, run_id
    root/models/<name>/v<version>/artifacts/...
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from typing import Dict, List, Optional

STAGES = ("None", "Staging", "Production", "Archived")


@dataclasses.dataclass
class ModelVersion:
    name: str
    version: int
    stage: str
    run_id: Optional[str]
    tags: Dict[str, str]
    artifact_dir: str
    created_at: float


class ModelRegistry:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "models"), exist_ok=True)

    def _model_dir(self, name: str) -> str:
        return os.path.join(self.root, "models", name)

    def register_model(
        self,
        name: str,
        artifact_dir: str,
        run_id: Optional[str] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> ModelVersion:
        """Snapshot ``artifact_dir`` as a new version of ``name``."""
        d = self._model_dir(name)
        os.makedirs(d, exist_ok=True)
        meta_path = os.path.join(d, "meta.json")
        meta = self._read(meta_path) or {"name": name, "next_version": 1}
        version = meta["next_version"]
        meta["next_version"] = version + 1
        vdir = os.path.join(d, f"v{version}")
        shutil.copytree(artifact_dir, os.path.join(vdir, "artifacts"))
        self._write(
            os.path.join(vdir, "meta.json"),
            {
                "name": name,
                "version": version,
                "stage": "None",
                "run_id": run_id,
                "tags": {k: str(v) for k, v in (tags or {}).items()},
                "created_at": time.time(),
            },
        )
        self._write(meta_path, meta)
        return self.get_version(name, version)

    def get_version(self, name: str, version: int) -> ModelVersion:
        vdir = os.path.join(self._model_dir(name), f"v{version}")
        meta = self._read(os.path.join(vdir, "meta.json"))
        if meta is None:
            raise KeyError(f"model {name} version {version} not found")
        return ModelVersion(
            name=name,
            version=version,
            stage=meta["stage"],
            run_id=meta.get("run_id"),
            tags=meta.get("tags", {}),
            artifact_dir=os.path.join(vdir, "artifacts"),
            created_at=meta.get("created_at", 0.0),
        )

    def list_versions(self, name: str) -> List[ModelVersion]:
        d = self._model_dir(name)
        if not os.path.isdir(d):
            return []
        versions = sorted(
            int(entry[1:])
            for entry in os.listdir(d)
            if entry.startswith("v") and entry[1:].isdigit()
        )  # numeric sort: lexical would put v10 before v2 (latest == wrong)
        return [self.get_version(name, v) for v in versions]

    def latest_version(
        self, name: str, stage: Optional[str] = None
    ) -> ModelVersion:
        """Latest version, optionally restricted to a stage — the resolution
        rule the reference's ``predict_udf`` uses (``04_inference.py:10-12``:
        ``latest_versions[0]``)."""
        versions = self.list_versions(name)
        if stage is not None:
            versions = [v for v in versions if v.stage == stage]
        if not versions:
            raise KeyError(f"no versions of model {name}" + (f" in stage {stage}" if stage else ""))
        return versions[-1]

    def transition_stage(self, name: str, version: int, stage: str) -> ModelVersion:
        if stage not in STAGES:
            raise ValueError(f"unknown stage {stage!r}; valid: {STAGES}")
        vdir = os.path.join(self._model_dir(name), f"v{version}")
        meta_path = os.path.join(vdir, "meta.json")
        meta = self._read(meta_path)
        if meta is None:
            raise KeyError(f"model {name} version {version} not found")
        meta["stage"] = stage
        self._write(meta_path, meta)
        return self.get_version(name, version)

    def set_version_tag(self, name: str, version: int, key: str, value: str) -> None:
        vdir = os.path.join(self._model_dir(name), f"v{version}")
        meta_path = os.path.join(vdir, "meta.json")
        meta = self._read(meta_path)
        if meta is None:
            raise KeyError(f"model {name} version {version} not found")
        meta.setdefault("tags", {})[key] = str(value)
        self._write(meta_path, meta)

    def models(self) -> List[str]:
        base = os.path.join(self.root, "models")
        return sorted(
            d for d in os.listdir(base) if os.path.isdir(os.path.join(base, d))
        )

    # -- cleanup (reference 05_monitoring_wip.py:40-59 archives every version
    # then deletes the registered model) ------------------------------------
    def archive_version(self, name: str, version: int) -> ModelVersion:
        """Stage transition to Archived — the reference's pre-delete step."""
        return self.transition_stage(name, version, "Archived")

    def delete_version(self, name: str, version: int) -> None:
        vdir = os.path.join(self._model_dir(name), f"v{version}")
        if not os.path.isdir(vdir):
            raise KeyError(f"model {name} version {version} not found")
        shutil.rmtree(vdir)

    def delete_model(self, name: str) -> None:
        """Archive-and-delete every version, then the model itself."""
        d = self._model_dir(name)
        if not os.path.isdir(d):
            raise KeyError(f"model {name} not found")
        for v in self.list_versions(name):
            self.archive_version(name, v.version)
        shutil.rmtree(d)

    @staticmethod
    def _read(path: str):
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    @staticmethod
    def _write(path: str, obj) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=2)
        os.replace(tmp, path)
