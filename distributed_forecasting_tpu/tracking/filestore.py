"""Experiment tracking — the MLflow-tracking stand-in, file/JSON-backed.

The reference tracks every one of its 500 fits as an MLflow run named
``run_item_{item}_store_{store}`` with params, metrics and a model artifact
(reference ``notebooks/prophet/02_training.py:160-196``), then uses
``mlflow.search_runs`` as the inference-time model index
(``notebooks/prophet/model_wrapper.py:27-29``).  Those 500 HTTP round trips
from inside Spark workers are the reference's own tracking bottleneck
(SURVEY.md §2.3-2).

This implementation keeps the same concepts — experiments, runs, params,
metrics (with history), tags, artifacts, ``search_runs`` — as plain local
transactions, and supports the batched layout the TPU engine prefers: ONE run
for the whole batched fit with a per-series metric table attached as an
artifact, alongside optional per-series runs for drill-down parity.  The
storage is a directory tree of JSON files (the same shape MLflow's own
file store uses in the reference's unit-test fixture,
reference ``tests/unit/conftest.py:56-62``), so tests run hermetically.

Layout::

    root/experiments/<eid>/meta.json
    root/experiments/<eid>/runs/<rid>/meta.json      # name, tags, status, times
    root/experiments/<eid>/runs/<rid>/params.json
    root/experiments/<eid>/runs/<rid>/metrics.json   # name -> [(step, value)]
    root/experiments/<eid>/runs/<rid>/artifacts/...
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from typing import Dict, List, Optional



def _now() -> float:
    return time.time()


def _write_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, default=_jsonable)
    os.replace(tmp, path)


def _jsonable(x):
    from distributed_forecasting_tpu.utils.config import to_jsonable

    return to_jsonable(x, strict=False)


def _read_json(path: str, default=None):
    if not os.path.exists(path):
        return default
    with open(path) as f:
        return json.load(f)


class Run:
    """Handle to one tracked run.  Context-manager; mirrors the
    ``mlflow.start_run`` usage pattern of the reference trainer."""

    def __init__(self, tracker: "FileTracker", experiment_id: str, run_id: str):
        self._tracker = tracker
        self.experiment_id = experiment_id
        self.run_id = run_id

    # -- paths --------------------------------------------------------------
    @property
    def _dir(self) -> str:
        return self._tracker._run_dir(self.experiment_id, self.run_id)

    @property
    def artifact_dir(self) -> str:
        d = os.path.join(self._dir, "artifacts")
        os.makedirs(d, exist_ok=True)
        return d

    # -- logging ------------------------------------------------------------
    def log_params(self, params: Dict) -> None:
        path = os.path.join(self._dir, "params.json")
        cur = _read_json(path, {})
        cur.update({k: _jsonable(v) if not isinstance(v, (str, int, float, bool)) else v
                    for k, v in params.items()})
        _write_json(path, cur)

    def log_metrics(self, metrics: Dict[str, float], step: int = 0) -> None:
        path = os.path.join(self._dir, "metrics.json")
        cur = _read_json(path, {})
        for k, v in metrics.items():
            cur.setdefault(k, []).append([int(step), float(v)])
        _write_json(path, cur)

    def set_tags(self, tags: Dict[str, str]) -> None:
        meta_path = os.path.join(self._dir, "meta.json")
        meta = _read_json(meta_path, {})
        meta.setdefault("tags", {}).update({k: str(v) for k, v in tags.items()})
        _write_json(meta_path, meta)

    def log_artifact(self, local_path: str, name: Optional[str] = None) -> str:
        dst = os.path.join(self.artifact_dir, name or os.path.basename(local_path))
        shutil.copyfile(local_path, dst)
        return dst

    def log_artifact_bytes(self, name: str, data: bytes) -> str:
        dst = os.path.join(self.artifact_dir, name)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        with open(dst, "wb") as f:
            f.write(data)
        return dst

    def log_table(self, name: str, df) -> str:
        """Attach a pandas frame (e.g. the per-series metric table of a
        batched fit) as a parquet artifact."""
        dst = os.path.join(self.artifact_dir, name)
        df.to_parquet(dst, index=False)
        return dst

    def artifact_path(self, name: str) -> str:
        return os.path.join(self.artifact_dir, name)

    # -- lifecycle ----------------------------------------------------------
    def end(self, status: str = "FINISHED") -> None:
        meta_path = os.path.join(self._dir, "meta.json")
        meta = _read_json(meta_path, {})
        meta["status"] = status
        meta["end_time"] = _now()
        _write_json(meta_path, meta)

    def __enter__(self) -> "Run":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end("FAILED" if exc_type else "FINISHED")

    # -- reads --------------------------------------------------------------
    def params(self) -> Dict:
        return _read_json(os.path.join(self._dir, "params.json"), {})

    def metrics(self) -> Dict[str, float]:
        """Latest value per metric (like MLflow's run.data.metrics)."""
        hist = _read_json(os.path.join(self._dir, "metrics.json"), {})
        return {k: v[-1][1] for k, v in hist.items() if v}

    def meta(self) -> Dict:
        return _read_json(os.path.join(self._dir, "meta.json"), {})


class FileTracker:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "experiments"), exist_ok=True)

    # -- experiments --------------------------------------------------------
    def create_experiment(self, name: str) -> str:
        existing = self.get_experiment_by_name(name)
        if existing is not None:
            return existing
        eid = uuid.uuid4().hex[:12]
        d = os.path.join(self.root, "experiments", eid)
        os.makedirs(os.path.join(d, "runs"), exist_ok=True)
        _write_json(
            os.path.join(d, "meta.json"),
            {"experiment_id": eid, "name": name, "created_at": _now()},
        )
        return eid

    def get_experiment_by_name(self, name: str) -> Optional[str]:
        base = os.path.join(self.root, "experiments")
        for eid in os.listdir(base):
            meta = _read_json(os.path.join(base, eid, "meta.json"))
            if meta and meta.get("name") == name:
                return eid
        return None

    # -- runs ---------------------------------------------------------------
    def _run_dir(self, eid: str, rid: str) -> str:
        return os.path.join(self.root, "experiments", eid, "runs", rid)

    def start_run(
        self,
        experiment_id: str,
        run_name: Optional[str] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> Run:
        rid = uuid.uuid4().hex[:16]
        d = self._run_dir(experiment_id, rid)
        os.makedirs(os.path.join(d, "artifacts"), exist_ok=True)
        _write_json(
            os.path.join(d, "meta.json"),
            {
                "run_id": rid,
                "run_name": run_name or rid,
                "status": "RUNNING",
                "start_time": _now(),
                "tags": {k: str(v) for k, v in (tags or {}).items()},
            },
        )
        return Run(self, experiment_id, rid)

    def log_runs_batch(self, experiment_id: str, rows: List[Dict]) -> List[str]:
        """Write many small finished runs in one buffered pass.

        ``rows``: dicts with ``run_name`` and optional ``tags`` / ``params``
        / ``metrics``.  Where :meth:`start_run` + ``log_metrics`` + ``end``
        costs ~5 file operations and 3 ``os.replace`` fsync-ish barriers per
        run (pathological for the per-series drill-down loop, which creates
        one run per SERIES), this writes each run's ``meta.json`` /
        ``params.json`` / ``metrics.json`` exactly once with plain buffered
        I/O and issues a single directory fsync at the end of the batch —
        one durability point per experiment batch, not per row.

        Runs are born ``FINISHED`` (their data is complete by construction),
        so the layout stays exactly what ``search_runs`` and the MLflow
        adapter already read.  Returns the new run ids in row order.
        """
        base = os.path.join(self.root, "experiments", experiment_id, "runs")
        os.makedirs(base, exist_ok=True)
        t = _now()
        rids: List[str] = []
        for row in rows:
            rid = uuid.uuid4().hex[:16]
            d = os.path.join(base, rid)
            os.makedirs(os.path.join(d, "artifacts"), exist_ok=True)
            meta = {
                "run_id": rid,
                "run_name": row.get("run_name") or rid,
                "status": "FINISHED",
                "start_time": t,
                "end_time": t,
                "tags": {k: str(v)
                         for k, v in (row.get("tags") or {}).items()},
            }
            with open(os.path.join(d, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2, default=_jsonable)
            params = row.get("params")
            if params:
                with open(os.path.join(d, "params.json"), "w") as f:
                    json.dump({k: _jsonable(v) for k, v in params.items()},
                              f, indent=2, default=_jsonable)
            metrics = row.get("metrics")
            if metrics:
                hist = {k: [[0, float(v)]] for k, v in metrics.items()}
                with open(os.path.join(d, "metrics.json"), "w") as f:
                    json.dump(hist, f, indent=2)
            rids.append(rid)
        # one durability barrier for the whole batch: flush the runs
        # directory so the new entries survive a crash (the per-file
        # contents went through buffered writes above)
        fd = os.open(base, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        return rids

    def get_run(self, experiment_id: str, run_id: str) -> Run:
        if not os.path.isdir(self._run_dir(experiment_id, run_id)):
            raise KeyError(f"run {run_id} not found in experiment {experiment_id}")
        return Run(self, experiment_id, run_id)

    def search_runs(
        self,
        experiment_id: str,
        run_name: Optional[str] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> List[Run]:
        """The reference's ``mlflow.search_runs`` analogue (its
        model_wrapper.py:27-29 builds the inference index from it)."""
        base = os.path.join(self.root, "experiments", experiment_id, "runs")
        if not os.path.isdir(base):
            return []
        out = []
        for rid in sorted(os.listdir(base)):
            run = Run(self, experiment_id, rid)
            meta = run.meta()
            if run_name is not None and meta.get("run_name") != run_name:
                continue
            if tags:
                rt = meta.get("tags", {})
                if any(rt.get(k) != str(v) for k, v in tags.items()):
                    continue
            out.append(run)
        return out
