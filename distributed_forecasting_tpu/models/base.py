"""Model protocol + registry.

Every model family exposes the same two pure functions over a *batch* of
series (the whole point: one compiled program for all 500 fits, replacing the
reference's one-Prophet-per-Spark-group fan-out):

    fit(y, mask, day, config)                 -> params (pytree, leaves lead
                                                 with the series axis S)
    forecast(params, day_all, t_end, config, key)
        -> (yhat, lo, hi) each (S, len(day_all))

``day_all`` covers history + horizon (``make_future_dataframe(...,
include_history=True)`` semantics, reference ``notebooks/prophet/
02_training.py:201-205``); ``t_end`` is the last *training* day so the model
knows where forecast uncertainty starts.

Both functions must be jit-safe with static config, and batch-shaped so the
engine can shard the S axis over a device mesh unchanged.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp
from jax.scipy.special import ndtri

MODEL_REGISTRY: dict = {}


def gaussian_quantiles(forecast_fn: Callable, floor=None) -> Callable:
    """Exact quantile forecaster for families whose predictive is Gaussian
    IN DATA SPACE (``hi = yhat + z·sd`` — true of holt_winters, arima,
    theta, croston; the curve model has its own transform-aware
    implementation in ``prophet_glm``).  The per-step sd is recovered from
    the UPPER bound (never clamped), so a family that floors its lower
    bound (croston's non-negative demand) still recovers the true sd;
    ``floor`` then applies the same clamp to every priced quantile.
    Returns (S, Q, T_all)."""

    def forecast_quantiles(params, day_all, t_end, config,
                           quantiles=(0.1, 0.5, 0.9), key=None):
        if not quantiles or not all(0.0 < q < 1.0 for q in quantiles):
            raise ValueError(
                f"quantiles must lie in (0, 1), got {quantiles!r}"
            )
        yhat, lo, hi = forecast_fn(params, day_all, t_end, config, key)
        z_w = ndtri(0.5 + config.interval_width / 2.0)
        sd = (hi - yhat) / z_w
        qs = jnp.asarray(tuple(quantiles), jnp.float32)
        yq = yhat[:, None, :] + ndtri(qs)[None, :, None] * sd[:, None, :]
        if floor is not None:
            yq = jnp.maximum(yq, floor)
        return yq

    return forecast_quantiles


def history_splice(fitted, future, day_all, day0, h):
    """Assemble the (S, T_all) forecast path over history + future days.

    In-sample days (``h <= 0``) gather the one-step fitted path by day offset
    from ``day0``; future days take ``future``.  Shared by every scan-family
    model (holt_winters, croston, theta) so the day-grid indexing lives in
    one place.
    """
    S, T_fit = fitted.shape
    T_all = day_all.shape[0]
    hist_idx = jnp.clip(
        (day_all.astype(jnp.float32) - day0).astype(jnp.int32), 0, T_fit - 1
    )
    hist = jnp.take_along_axis(
        fitted, jnp.broadcast_to(hist_idx[None, :], (S, T_all)), axis=1
    )
    return jnp.where((h > 0.0)[None, :], future, hist)


class ModelFns(NamedTuple):
    fit: Callable
    forecast: Callable
    config_cls: type
    # whether fit/forecast accept an ``xreg`` keyword (exogenous regressor
    # values; the curve model's Prophet ``add_regressor`` equivalent)
    supports_xreg: bool = False
    # optional probabilistic output: (params, day_all, t_end, config,
    # quantiles, key=None[, xreg=None]) -> (S, Q, T_all) quantile paths
    forecast_quantiles: Callable = None
    # hard floor the family enforces on its lower band/quantiles (croston
    # clamps demand at 0); band post-processing (conformal scaling,
    # engine/calibrate) must re-apply it after widening
    band_floor: float = None
    # optional streaming-update kernel (the serving/ingest path):
    #   update_state(params, aux, y_new, mask_new, valid, day_new, config)
    #       -> (params', aux', preds)
    # continues the family's filter over K appended day-columns in one
    # jitted dispatch.  y_new/mask_new: (S, K); valid: (K,) 1.0 for real
    # appended days, 0.0 for shape-bucket padding (padded columns must
    # leave the carry bit-identical); day_new: (K,) absolute day ordinals;
    # preds: (S, K) one-step-ahead fitted values for the new columns.
    # ``params'.fitted`` is left untouched — the state store owns the
    # fitted buffer and splices ``preds`` in itself.
    update_state: Callable = None
    # init_update_aux(params, y=None, mask=None) -> aux pytree seeding the
    # filter carry pieces that fit() does not persist in params (sse/n for
    # sigma continuation, croston's gap counter, tsb's probability).  With
    # the training (y, mask) the seed is exact; without, a documented
    # approximation (docs/streaming.md).
    init_update_aux: Callable = None


def register_model(name: str, fit: Callable, forecast: Callable, config_cls: type,
                   supports_xreg: bool = False, forecast_quantiles: Callable = None,
                   band_floor: float = None, update_state: Callable = None,
                   init_update_aux: Callable = None):
    MODEL_REGISTRY[name] = ModelFns(fit=fit, forecast=forecast,
                                    config_cls=config_cls,
                                    supports_xreg=supports_xreg,
                                    forecast_quantiles=forecast_quantiles,
                                    band_floor=band_floor,
                                    update_state=update_state,
                                    init_update_aux=init_update_aux)


def get_model(name: str) -> ModelFns:
    if name not in MODEL_REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[name]
