"""Prophet-equivalent curve model: piecewise-linear trend + Fourier seasonality.

This is the native-equivalent obligation of the build (SURVEY.md §2.2): the
reference's per-series compute kernel is ``Prophet.fit`` -> pystan -> Stan C++
L-BFGS MAP (reference ``notebooks/prophet/02_training.py:162-172``,
``requirements.txt:3-4``).  The same MAP problem — hinge-basis trend with a
sparsity prior on slope deltas, weekly+yearly Fourier seasonality, Gaussian
likelihood — is solved here in closed form as a batched penalized
least-squares on the MXU: for S=500 series one einsum builds all Gram
matrices and one batched Cholesky solves them.  No iterative optimizer, no
per-series Python.

Reference model config reproduced (``02_training.py:162-169``):
  interval_width=0.95, growth='linear', daily_seasonality=False,
  weekly_seasonality=True, yearly_seasonality=True,
  seasonality_mode='multiplicative'.

Multiplicative seasonality is fit additively in log space (a GLM with log
link and Gaussian noise), matching Prophet's ``trend * (1 + seasonal)`` to
first order; predictions/intervals are mapped back with exp.

Uncertainty follows Prophet's own trick (no posterior needed): observation
noise from training residuals + *trend* uncertainty by simulating future
changepoints — Laplace-distributed slope deltas at the historical changepoint
rate, with scale equal to the mean |delta| learned on history — then taking
quantiles over a fixed number of sample paths (static shapes, one vmapped
matmul).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import register_model
from distributed_forecasting_tpu.ops.features import (
    curve_design_matrix,
    scaled_time,
    with_regressors,
)
from distributed_forecasting_tpu.ops.solve import (
    fitted_values,
    huber_irls_solve,
    ridge_solve_batch,
    weighted_residual_scale,
    yule_walker_masked,
)

_LOG_EPS = 1e-3


@dataclasses.dataclass(frozen=True)
class CurveModelConfig:
    growth: str = "linear"  # 'linear' | 'flat' | 'logistic'
    # logistic growth: per-series carrying capacity = cap_multiplier * max(y)
    # (Prophet takes an explicit cap column; a data-derived cap covers the
    # retail-demand case without a second input table)
    cap_multiplier: float = 1.1
    # Prophet's explicit saturating bounds: cap_value overrides the
    # data-derived rule with a known shared capacity (Prophet's `cap`
    # column); floor_value is the saturating minimum (Prophet's `floor`) —
    # the trend is linear in logit((y - floor)/(cap - floor)) space, so the
    # forecast saturates at both bounds.  floor_value only applies to
    # logistic growth.
    cap_value: Optional[float] = None
    floor_value: float = 0.0
    n_changepoints: int = 25
    changepoint_range: float = 0.8
    # Prophet's explicit `changepoints`: hinge sites at KNOWN dates (static
    # tuple of epoch-day ints, e.g. via data/holidays-style day math or
    # pd.Timestamp(...).toordinal() - 719163); overrides the uniform
    # n_changepoints/changepoint_range grid when non-empty
    changepoint_days: tuple = ()
    changepoint_prior_scale: float = 0.05
    seasonality_prior_scale: float = 10.0
    weekly_order: int = 3
    yearly_order: int = 10
    # Prophet's add_seasonality: ((name, period_days, fourier_order), ...)
    # static tuples — e.g. (("monthly", 30.5, 5),); YAML lists freeze to
    # tuples through the task conf path.  Shares seasonality_prior_scale
    # unless an entry carries its own 4th element, Prophet's per-seasonality
    # prior_scale: ("monthly", 30.5, 5, 2.0).
    extra_seasonalities: tuple = ()
    seasonality_mode: str = "multiplicative"  # or 'additive'
    # static holiday spec ((name, (epoch_day, ...)), ...) — build with
    # data/holidays.holiday_spec / us_holiday_spec_for_range
    holidays: tuple = ()
    holiday_prior_scale: float = 10.0
    interval_width: float = 0.95
    # 0 = analytic intervals (closed-form variance of the simulated
    # changepoint process — deterministic and compile-cheap, the default);
    # >0 = Prophet-faithful Monte-Carlo quantiles over that many paths.
    uncertainty_samples: int = 0
    # Autoregression on the fit residuals (NeuralProphet's headline
    # addition to the Prophet decomposition: arXiv:2111.15397).  Two-stage:
    # the curve fit is unchanged; an AR(p) is then fit on its in-sample
    # residuals by batched Yule-Walker (closed form, no optimizer) and the
    # forecast adds the AR extrapolation seeded from the last observed
    # residuals — short-horizon accuracy when residuals are autocorrelated,
    # decaying to the plain curve forecast (and its marginal variance) at
    # long leads.  0 = off (the Prophet-parity default).
    ar_order: int = 0
    # Exogenous regressors (Prophet's ``add_regressor``): static column
    # count; values arrive as the ``xreg`` argument to fit/forecast —
    # (T, R) shared across series (promo calendar, weather) or (S, T, R)
    # per-series (each store-item's price).  Like Prophet, future values
    # must be supplied at forecast time.  Regressors enter the fit space
    # additively, i.e. they act multiplicatively on y under
    # seasonality_mode='multiplicative' (Prophet's mode default too).
    n_regressors: int = 0
    regressor_prior_scale: float = 10.0
    # Prophet's standardize='auto': continuous columns are z-scored for
    # conditioning; binary 0/1 columns pass through untouched
    regressor_standardize: bool = True
    regressor_names: tuple = ()  # optional, for logging/plots
    # Outlier-robust fitting: 'huber' replaces the L2 MAP solve with IRLS
    # (ops/solve.huber_irls_solve) — promo spikes / stockouts / glitches
    # stop dragging the trend and inflating sigma; each IRLS round is one
    # more batched weighted-Gram solve.  The residual scale then comes
    # from the robust weights (inlier spread), so bands track typical
    # days, not the spikes.  'l2' is the Prophet-parity default (Stan's
    # MAP is Gaussian-likelihood).
    loss: str = "l2"  # 'l2' | 'huber'
    huber_delta: float = 1.345
    robust_iters: int = 3


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CurveParams:
    """Fitted parameters for a batch of series (leaves lead with S)."""

    beta: jax.Array        # (S, F) coefficients in the design basis
    sigma: jax.Array       # (S,) residual std (in fit space)
    y_scale: jax.Array     # (S,) per-series scale used to normalize y
    cap: jax.Array         # (S,) carrying capacity (logistic growth; else 1)
    t0: jax.Array          # () scalar: first training day (absolute)
    t1: jax.Array          # () scalar: last training day (absolute)
    # regressor standardization learned at fit time — ALWAYS (S, R), even
    # when the fit regressors were a shared calendar (stats broadcast per
    # series), so every param leaf keeps the lead-with-S invariant that
    # serving's gather_params relies on; (0, 0) when n_regressors == 0.
    # Forecast must map future xreg through the SAME affine transform the
    # coefficients were fit in.
    reg_mu: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((0, 0), jnp.float32)
    )
    reg_sd: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.ones((0, 0), jnp.float32)
    )
    # AR-on-residuals state (ar_order > 0; empty otherwise so old artifacts
    # keep loading): Yule-Walker coefficients, the residual window ending
    # at each series' last OBSERVED day (seeds the forecast rollout), and
    # the one-step innovation std — all in normalized fit space.
    ar_phi: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((0, 0), jnp.float32)
    )
    ar_tail: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((0, 0), jnp.float32)
    )
    ar_sigma: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((0,), jnp.float32)
    )
    # absolute day of each series' last observation -- the AR lead index is
    # per-series so a stale series (observations ending G days before the
    # batch end) gets the decayed phi^(G+h) correction and the wider
    # (G+h)-step variance, not a full-strength lead-1 one
    ar_last_day: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((0,), jnp.float32)
    )


def _fit_space(y, mask, mode, cap=None, floor=0.0):
    """Transform observations into the (additive) fitting space.

    multiplicative -> log space; logistic growth -> logit of
    (y - floor)/(cap - floor) (the saturating-growth analogue: a linear
    trend in logit space is a logistic curve in data space, matching
    Prophet's ``growth='logistic'`` with its ``cap``/``floor`` bounds);
    otherwise identity.
    """
    if cap is not None:
        frac = jnp.clip(
            (y - floor) / (cap[:, None] - floor), _LOG_EPS, 1.0 - _LOG_EPS
        )
        return jnp.log(frac / (1.0 - frac)) * mask
    if mode == "multiplicative":
        return jnp.log(jnp.maximum(y, _LOG_EPS)) * mask
    return y * mask


def _feature_masks(layout, own_scale=()):
    """Static 0/1 masks over the feature axis for each prior group.

    ``own_scale``: ((slice, prior_scale), ...) for extra seasonalities
    carrying their own Prophet-style prior_scale — excluded from the shared
    seasonal mask and returned as (mask, scale) pairs.
    """
    F = layout["n_features"]
    import numpy as _np

    cp = _np.zeros(F, _np.float32)
    cp[layout["changepoints"]] = 1.0
    seas = _np.zeros(F, _np.float32)
    seas[layout["weekly"]] = 1.0
    seas[layout["yearly"]] = 1.0
    # custom seasonalities share the seasonality prior scale (Prophet's
    # add_seasonality default prior_scale=10.0 matches it) unless an entry
    # sets its own
    seas[layout["extra_seas"]] = 1.0
    own = []
    for sl, ps in own_scale:
        m = _np.zeros(F, _np.float32)
        m[sl] = 1.0
        seas[sl] = 0.0
        own.append((jnp.asarray(m), float(ps)))
    fixed = _np.zeros(F, _np.float32)
    fixed[layout["intercept"]] = 1.0
    slope = _np.zeros(F, _np.float32)
    slope[layout["slope"]] = 1.0
    hol = _np.zeros(F, _np.float32)
    if "holidays" in layout:
        hol[layout["holidays"]] = 1.0
    reg = _np.zeros(F, _np.float32)
    if "regressors" in layout:
        reg[layout["regressors"]] = 1.0
    return (jnp.asarray(cp), jnp.asarray(seas), jnp.asarray(fixed),
            jnp.asarray(slope), jnp.asarray(hol), jnp.asarray(reg), own)


def _prior_precision(layout, cfg: CurveModelConfig, cp_scale=None, seas_scale=None,
                     hol_scale=None):
    """Per-feature ridge precision: flat prior on intercept/slope, Laplace->
    ridge surrogate 1/scale^2 on changepoint deltas and seasonality.

    ``cp_scale`` / ``seas_scale`` / ``hol_scale`` may be traced scalars or
    (S,)/(S,1) arrays — the hyperparameter-search path (engine/hyper.py)
    sweeps them WITHOUT recompiling, the analogue of the reference AutoML's
    per-series hyperopt over changepoint/seasonality/holiday prior scales
    (``notebooks/automl/22-09-26...py:111-123``).  Result broadcasts to
    (F,) or (S, F).
    """
    cp_scale = cfg.changepoint_prior_scale if cp_scale is None else cp_scale
    seas_scale = cfg.seasonality_prior_scale if seas_scale is None else seas_scale
    hol_scale = cfg.holiday_prior_scale if hol_scale is None else hol_scale
    cp_scale = jnp.asarray(cp_scale)[..., None]  # (...,1) broadcasts over F
    seas_scale = jnp.asarray(seas_scale)[..., None]
    hol_scale = jnp.asarray(hol_scale)[..., None]
    own_scale = tuple(
        (layout[f"seas_{name}"], ps)
        for name, _p, _o, ps in _extra_entries(cfg)
        if ps is not None
    )
    cp_m, seas_m, fixed_m, slope_m, hol_m, reg_m, own = _feature_masks(
        layout, own_scale
    )
    # flat growth = no trend at all: clamp the slope AND the changepoint
    # hinges (which would otherwise reintroduce a piecewise trend)
    slope_prec = 1e8 if cfg.growth == "flat" else 1e-8
    if cfg.growth == "flat":
        cp_scale = jnp.full_like(cp_scale, 1e-4)
    lam = (
        cp_m * (1.0 / cp_scale**2)
        + seas_m * (1.0 / seas_scale**2)
        + fixed_m * 1e-8
        + slope_m * slope_prec
        + hol_m * (1.0 / hol_scale**2)
        + reg_m * (1.0 / cfg.regressor_prior_scale**2)
    )
    # Prophet per-seasonality prior scales: fixed (static) precisions for
    # entries that carry their own scale, outside the swept shared scale
    for m, ps in own:
        lam = lam + m * (1.0 / ps**2)
    return lam


_RESERVED_COMPONENTS = frozenset({
    # built-in decompose components
    "trend", "weekly", "yearly", "holidays", "regressors",
    # component_frame skeleton columns a custom name must not clobber
    "ds", "store", "item", "y", "yhat", "yhat_lower", "yhat_upper",
})


def _extra_entries(cfg: CurveModelConfig):
    """Validate and normalize extra_seasonalities to
    (name, period, order, prior_scale_or_None) 4-tuples."""
    seen = set()
    out = []
    for entry in cfg.extra_seasonalities:
        if len(entry) == 3:
            name, period, order = entry
            ps = None
        elif len(entry) == 4:
            name, period, order, ps = entry
            # YAML null = "use the shared scale", same as the 3-tuple form
            if ps is not None and not float(ps) > 0:
                raise ValueError(
                    f"extra seasonality {name!r} prior_scale must be > 0, "
                    f"got {ps}"
                )
        else:
            raise ValueError(
                f"extra seasonality entries are (name, period, order[, "
                f"prior_scale]), got {entry!r}"
            )
        if str(name) in _RESERVED_COMPONENTS:
            raise ValueError(
                f"extra seasonality name {name!r} collides with a built-in "
                f"component; rename it"
            )
        if str(name) in seen:
            # a duplicate would fit both blocks but silently overwrite the
            # layout slice, dropping the first block from decomposition
            raise ValueError(
                f"duplicate extra seasonality name {name!r}"
            )
        seen.add(str(name))
        if not (float(period) > 0 and int(order) > 0):
            raise ValueError(
                f"extra seasonality {name!r} needs period > 0 and "
                f"order >= 1, got period={period}, order={order}"
            )
        out.append((
            str(name), float(period), int(order),
            None if ps is None else float(ps),
        ))
    return tuple(out)


def _n_cp(cfg: CurveModelConfig) -> int:
    """Effective hinge count: explicit changepoint_days override the grid."""
    return len(cfg.changepoint_days) or cfg.n_changepoints


def _cp_range(cfg: CurveModelConfig) -> float:
    """Fraction of history the hinge sites span — the uniform grid covers
    changepoint_range; explicit dates are treated as covering the whole
    history for the future-changepoint rate."""
    return 1.0 if cfg.changepoint_days else cfg.changepoint_range


def _design(day, t0, t1, cfg: CurveModelConfig):
    entries = _extra_entries(cfg)
    return curve_design_matrix(
        day,
        t0,
        t1,
        n_changepoints=cfg.n_changepoints,
        weekly_order=cfg.weekly_order,
        yearly_order=cfg.yearly_order,
        changepoint_range=cfg.changepoint_range,
        holidays=cfg.holidays,
        extra_seasonalities=tuple((n, p, o) for n, p, o, _ in entries),
        changepoint_days=cfg.changepoint_days,
    )


def _standardize_xreg(xreg, mask, config: CurveModelConfig):
    """Standardize regressor columns for conditioning; returns (xs, mu, sd).

    Per-series (S, T, R) regressors standardize under the observation mask
    (padded days carry arbitrary fill); shared (T, R) regressors over the
    whole grid.  A near-constant column (e.g. a promo flag never active in
    history) keeps sd=1 instead of exploding to 1/eps.

    Binary 0/1 columns are left untransformed — Prophet's
    ``standardize='auto'`` rule — so the effective prior scale on indicator
    covariates (promo flags) matches reference behavior instead of being
    rescaled by the flag's rarity.  The check is traced (all observed values
    in {0, 1}), so it costs one reduction, not a recompile per column set.
    """
    if not config.regressor_standardize:
        R = xreg.shape[-1]
        return xreg, jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32)
    if xreg.ndim == 3:
        w = mask[:, :, None]
        # Prophet's rule needs BOTH values observed: an all-ones flag is NOT
        # binary-exempt — centering it (mu=1) zeroes the column so the ridge
        # prior pins its coefficient, instead of leaving a ones column
        # collinear with the intercept
        obs = w > 0
        is01 = (
            jnp.all((xreg == 0) | (xreg == 1) | ~obs, axis=1)
            & jnp.any((xreg == 0) & obs, axis=1)
            & jnp.any((xreg == 1) & obs, axis=1)
        )  # (S, R)
        n = jnp.maximum(w.sum(axis=1), 1.0)  # (S, 1->R broadcast)
        mu = (xreg * w).sum(axis=1) / n  # (S, R)
        var = (((xreg - mu[:, None, :]) ** 2) * w).sum(axis=1) / n
        sd_raw = jnp.sqrt(var)
        sd = jnp.where(sd_raw > 1e-6, sd_raw, 1.0)
        mu = jnp.where(is01, 0.0, mu)
        sd = jnp.where(is01, 1.0, sd)
        return (xreg - mu[:, None, :]) / sd[:, None, :], mu, sd
    is01 = (
        jnp.all((xreg == 0) | (xreg == 1), axis=0)
        & jnp.any(xreg == 0, axis=0)
        & jnp.any(xreg == 1, axis=0)
    )  # (R,)
    mu = jnp.where(is01, 0.0, xreg.mean(axis=0))  # (R,)
    sd_raw = xreg.std(axis=0)
    sd = jnp.where(is01 | (sd_raw <= 1e-6), 1.0, sd_raw)
    return (xreg - mu) / sd, mu, sd


def _check_xreg(xreg, config: CurveModelConfig, what: str):
    if config.n_regressors == 0:
        if xreg is not None:
            raise ValueError(
                "xreg passed but config.n_regressors == 0 — set "
                "CurveModelConfig(n_regressors=R) so the design and priors "
                "include the regressor columns"
            )
        return False
    if xreg is None:
        raise ValueError(
            f"config.n_regressors={config.n_regressors} but no xreg values "
            f"were passed to {what} (like Prophet, regressor values must be "
            f"supplied for fitting AND for the forecast window)"
        )
    if xreg.shape[-1] != config.n_regressors:
        raise ValueError(
            f"xreg has {xreg.shape[-1]} columns, config.n_regressors="
            f"{config.n_regressors}"
        )
    return True


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: CurveModelConfig, prior_scales=None,
        xreg=None) -> CurveParams:
    """Fit all series at once.  y, mask: (S, T); day: (T,) absolute days.

    ``prior_scales``: optional (changepoint_scale, seasonality_scale) or
    (changepoint_scale, seasonality_scale, holiday_scale) overrides — traced
    scalars or per-series (S,) arrays (hyper-search path); ``None`` uses the
    static config values.

    ``xreg``: exogenous regressor values over the SAME day grid — (T, R)
    shared or (S, T, R) per-series; required iff config.n_regressors > 0.
    """
    t0 = day[0].astype(jnp.float32)
    t1 = day[-1].astype(jnp.float32)
    if config.growth == "logistic":
        if config.cap_value is not None:
            if config.cap_value <= config.floor_value:
                raise ValueError(
                    f"cap_value ({config.cap_value}) must exceed "
                    f"floor_value ({config.floor_value})"
                )
            cap = jnp.full((y.shape[0],), float(config.cap_value))
        else:
            if config.floor_value != 0.0:
                # the data-derived rule assumes the saturation range starts
                # at 0; a floor above a small series' derived cap would
                # silently invert the logit.  Prophet likewise only defines
                # `floor` alongside an explicit `cap`.
                raise ValueError(
                    "floor_value requires an explicit cap_value (the "
                    "cap_multiplier rule derives capacity from 0)"
                )
            cap = config.cap_multiplier * jnp.maximum(
                jnp.max(y * mask, axis=1), _LOG_EPS
            )
        z = _fit_space(y, mask, config.seasonality_mode, cap=cap,
                       floor=float(config.floor_value))
        y_scale = jnp.ones((y.shape[0],))
    else:
        cap = jnp.ones((y.shape[0],))
        z = _fit_space(y, mask, config.seasonality_mode)
        # normalize per series for conditioning (Prophet divides by max |y|)
        if config.seasonality_mode == "multiplicative":
            y_scale = jnp.ones((y.shape[0],))
        else:
            y_scale = jnp.maximum(jnp.max(jnp.abs(z) * mask, axis=1), 1.0)
    zn = z / y_scale[:, None]
    X, layout = _design(day, t0, t1, config)
    if _check_xreg(xreg, config, "fit"):
        xs, reg_mu, reg_sd = _standardize_xreg(
            jnp.asarray(xreg, jnp.float32), mask, config
        )
        X, layout = with_regressors(X, layout, xs)
        if reg_mu.ndim == 1:  # shared calendar: broadcast stats per series
            S = y.shape[0]
            reg_mu = jnp.broadcast_to(reg_mu[None], (S, reg_mu.shape[0]))
            reg_sd = jnp.broadcast_to(reg_sd[None], (S, reg_sd.shape[0]))
    else:
        reg_mu = jnp.zeros((0, 0), jnp.float32)
        reg_sd = jnp.ones((0, 0), jnp.float32)
    if prior_scales is None:
        cp_s = seas_s = hol_s = None
    elif len(prior_scales) == 2:
        (cp_s, seas_s), hol_s = prior_scales, None
    else:
        cp_s, seas_s, hol_s = prior_scales
    lam = _prior_precision(layout, config, cp_s, seas_s, hol_s)
    resid_clip = None
    if config.loss == "huber":
        from distributed_forecasting_tpu.ops.solve import masked_mad_scale

        beta, w_rob = huber_irls_solve(
            X, zn, mask, lam, delta=config.huber_delta,
            iters=config.robust_iters,
        )
        # sigma = MAD scale of the final residuals: fully bounded in
        # outlier magnitude (Huber-WEIGHTED squares still grow as
        # delta*s*|r|, so one extreme glitch would widen every band) and
        # Gaussian-consistent on clean data — the inlier spread, which is
        # exactly what the bands should price
        r_fin = (zn - fitted_values(X, beta)) * mask
        sigma = masked_mad_scale(r_fin, mask)
        # downstream consumers of the residuals (the AR stage) must see
        # the same robustness: winsorize at delta * sigma so a spike on
        # the last observed days cannot seed the AR tail
        cl = (config.huber_delta * sigma)[:, None]
        resid_clip = jnp.clip(r_fin, -cl, cl)
    elif config.loss == "l2":
        beta = ridge_solve_batch(X, zn, mask, lam)
        sigma = weighted_residual_scale(X, zn, mask, beta)
    else:
        raise ValueError(
            f"unknown CurveModelConfig.loss {config.loss!r}; 'l2' or 'huber'"
        )
    ar_kwargs = {}
    if config.ar_order > 0:
        if resid_clip is not None:
            resid = resid_clip
        else:
            resid = (zn - fitted_values(X, beta)) * mask
        phi, tail, s_inn, last = _fit_ar_residuals(
            resid, mask, config.ar_order
        )
        ar_kwargs = dict(
            ar_phi=phi, ar_tail=tail, ar_sigma=s_inn,
            ar_last_day=day[last].astype(jnp.float32),
        )
    return CurveParams(beta=beta, sigma=sigma, y_scale=y_scale, cap=cap,
                       t0=t0, t1=t1, reg_mu=reg_mu, reg_sd=reg_sd,
                       **ar_kwargs)


_FUTURE_CP_GRID = 25  # static count of candidate future changepoint sites


def _trend_deviation_samples(params: CurveParams, t_all, t_end_scaled, cfg, key):
    """Simulated future trend deviations, Prophet-style.  Returns
    (S, n_samples, T_all) deviations, zero at/before the forecast start.

    Prophet samples a possible slope change at every future day; identically
    distributed (to first order) and far cheaper to compile is a static grid
    of L candidate changepoint sites spread over the forecast window, each
    active with probability matching the historical changepoint *rate* and
    Laplace magnitude matching the historical mean |delta| — the randomness
    tensors are (S, N, L) with L=25 instead of (S, N, T_all)."""
    S = params.beta.shape[0]
    N = cfg.uncertainty_samples
    L = _FUTURE_CP_GRID
    deltas_hist = params.beta[:, 2 : 2 + _n_cp(cfg)]  # (S, K)
    lam_scale = jnp.mean(jnp.abs(deltas_hist), axis=1)  # (S,)
    t_max = t_all[-1]
    span = jnp.maximum(t_max - t_end_scaled, 0.0)
    # grid of L future sites in (t_end, t_max]
    sites = t_end_scaled + (jnp.arange(L, dtype=jnp.float32) + 0.5) / L * span
    # expected changepoints in the window = K * span / changepoint_range;
    # spread over L sites
    p_cp = jnp.clip(
        _n_cp(cfg) * span / _cp_range(cfg) / L, 0.0, 1.0
    )
    k_bern, k_lap = jax.random.split(key)
    occur = jax.random.bernoulli(k_bern, p_cp, shape=(S, N, L)).astype(jnp.float32)
    mag = jax.random.laplace(k_lap, shape=(S, N, L)) * lam_scale[:, None, None]
    delta_samp = occur * mag  # (S, N, L) slope change at each site
    # deviation(t_j) = sum_l delta_l * max(0, t_j - site_l)
    lag = jnp.maximum(0.0, t_all[None, :] - sites[:, None])  # (L, T_all)
    dev = jnp.einsum("snl,lj->snj", delta_samp, lag, optimize=True)
    return dev


def _trend_deviation_variance(params: CurveParams, t_all, t_end_scaled, cfg):
    """Closed-form variance of the simulated changepoint process above:
    each site l flips on with prob p and Laplace(0, b) magnitude, so
    Var[dev(t)] = 2 b^2 p * sum_l max(0, t - s_l)^2.  Returns (S, T_all)."""
    L = _FUTURE_CP_GRID
    deltas_hist = params.beta[:, 2 : 2 + _n_cp(cfg)]
    lam_scale = jnp.mean(jnp.abs(deltas_hist), axis=1)  # (S,) Laplace b
    t_max = t_all[-1]
    span = jnp.maximum(t_max - t_end_scaled, 0.0)
    sites = t_end_scaled + (jnp.arange(L, dtype=jnp.float32) + 0.5) / L * span
    p_cp = jnp.clip(_n_cp(cfg) * span / _cp_range(cfg) / L, 0.0, 1.0)
    lag2 = jnp.sum(jnp.maximum(0.0, t_all[None, :] - sites[:, None]) ** 2, axis=0)
    return 2.0 * lam_scale[:, None] ** 2 * p_cp * lag2[None, :]


def _regressor_contrib(params: CurveParams, xreg, F0: int):
    """Fit-space regressor contribution (unscaled by y_scale), (S, T_all).

    Affine identity: ``beta.(x - mu)/sd = (beta/sd).x - sum(beta.mu/sd)``,
    so the standardized (S, T_all, R) intermediate never materializes — a
    shared calendar stays (T_all, R) through the einsum even when the
    standardization stats are per-series.
    """
    xreg = jnp.asarray(xreg, jnp.float32)
    beta_reg = params.beta[:, F0:]  # (S, R)
    w = beta_reg / params.reg_sd  # (S, R)
    offset = jnp.sum(w * params.reg_mu, axis=-1)[:, None]  # (S, 1)
    return (
        jnp.einsum("sr,str->st", w, xreg, optimize=True)
        if xreg.ndim == 3
        else jnp.einsum("sr,tr->st", w, xreg, optimize=True)
    ) - offset


# AR extrapolation/variance tables are precomputed for this many leads and
# gathered by clipped lead index — beyond it the mean has decayed to ~0 and
# the variance has saturated to the marginal residual variance, so clipping
# reproduces the plain curve forecast exactly where AR no longer matters.
# (A full-T_all sequential scan here would cost ~20 ms/batch of pure serial
# depth on the hot engine path — see the same note in models/arima.py.)
_AR_TABLE_LEN = 64


def _fit_ar_residuals(resid, mask, p: int):
    """Batched Yule-Walker AR(p) on masked residuals.

    resid, mask: (S, T) with resid already zeroed off-mask.  Returns
    (phi (S, p), tail (S, p), sigma_inn (S,)):

    * ``phi`` from the biased (divisor n) sample autocovariances — the PSD
      choice, so the solution is stationary;
    * ``tail``: the residual window ending at each series' LAST OBSERVED
      day (dynamic per-series slice — under a CV cutoff mask the grid's
      final positions are masked and would seed zeros);
    * ``sigma_inn``: std of the one-step innovations
      ``e_t = r_t - sum_k phi_k r_{t-k}`` over fully-observed lag windows.
    """
    S, T = resid.shape
    phi, c = yule_walker_masked(
        resid, mask, p, per_lag_norm=False, jitter_rel=1e-6, jitter_abs=1e-12
    )

    # residual window ending at the last observed index (newest last)
    last = jnp.argmax(
        jnp.arange(T, dtype=jnp.float32)[None, :] * mask
        + mask,  # all-masked series resolve to index 0
        axis=1,
    )
    start = jnp.clip(last - (p - 1), 0, T - p)

    def take_window(r_row, s0):
        return jax.lax.dynamic_slice(r_row, (s0,), (p,))

    tail = jax.vmap(take_window)(resid, start)  # (S, p) newest last

    # one-step innovations over fully-observed windows
    lags = jnp.stack(
        [resid[:, p - k : T - k] for k in range(1, p + 1)], axis=2
    )  # (S, T-p, p) lag k at [..., k-1]
    lag_mask = jnp.prod(
        jnp.stack([mask[:, p - k : T - k] for k in range(0, p + 1)], axis=2),
        axis=2,
    )  # (S, T-p) — target and every lag observed
    e = (resid[:, p:] - jnp.einsum("stp,sp->st", lags, phi)) * lag_mask
    ne = jnp.maximum(jnp.sum(lag_mask, axis=1), 1.0)
    sigma_inn = jnp.sqrt(jnp.sum(e**2, axis=1) / ne)
    # no valid windows -> fall back to the marginal residual std
    sigma_marg = jnp.sqrt(jnp.maximum(c[:, 0], 1e-12))
    sigma_inn = jnp.where(jnp.sum(lag_mask, axis=1) > 0, sigma_inn, sigma_marg)
    return phi, tail, sigma_inn, last


def _ar_tables(params: CurveParams, p: int):
    """(mean_table (K+1, S), var_table (K+1, S)) for leads 0..K.

    Row h holds the AR(p) h-step-ahead residual prediction from the stored
    tail window, and its predictive variance ``sigma_inn^2 * sum psi_j^2``
    (psi = MA(inf) weights of the fitted AR).  Row 0 is zero mean /
    marginal-free variance anchor (unused: in-history leads clip to 0 and
    take the marginal sigma instead).
    """
    phi, tail, s_inn = params.ar_phi, params.ar_tail, params.ar_sigma
    K = _AR_TABLE_LEN

    def step(carry, _):
        w, psi_w, var_acc = carry
        # next residual prediction: newest lag is w[:, -1]
        r_next = jnp.einsum("sp,sp->s", w, phi[:, ::-1])
        w = jnp.concatenate([w[:, 1:], r_next[:, None]], axis=1)
        # h-step predictive variance uses psi_0..psi_{h-1}: emit the sum
        # BEFORE folding in psi_h (lead 1 = psi_0^2 alone)
        out_var = var_acc
        psi_next = jnp.einsum("sp,sp->s", psi_w, phi[:, ::-1])
        psi_w = jnp.concatenate([psi_w[:, 1:], psi_next[:, None]], axis=1)
        var_acc = var_acc + psi_next**2
        return (w, psi_w, var_acc), (r_next, out_var)

    S = phi.shape[0]
    psi0 = jnp.concatenate(
        [jnp.zeros((S, p - 1)), jnp.ones((S, 1))], axis=1
    )  # psi_0 = 1 impulse
    var0 = jnp.ones((S,))  # sum psi_0^2
    (_, _, _), (means, var_sums) = jax.lax.scan(
        step, (tail, psi0, var0), None, length=K
    )
    # lead h=1..K: mean = means[h-1]; var = sigma_inn^2 * var_sums[h-1]
    zero = jnp.zeros((1, S))
    mean_table = jnp.concatenate([zero, means], axis=0)  # (K+1, S)
    var_table = jnp.concatenate(
        [jnp.ones((1, S)), var_sums], axis=0
    ) * (s_inn[None, :] ** 2)
    return mean_table, var_table


def _ar_correction(params: CurveParams, day_all, t_end, p: int):
    """(mean (S, T_all), var (S, T_all), future_mask (S, T_all)).

    Lead index is PER SERIES from each series' last observed day
    (``ar_last_day``) — a stale series whose observations end G days
    before the batch end gets the decayed ``phi^(G+h)`` correction and the
    wider (G+h)-step variance, not a full-strength lead-1 one.  The
    correction is gated to days strictly past ``t_end`` (the forecast
    start: the batch end, or a CV cutoff), clipped into the precomputed
    tables where the mean has decayed and the variance saturated.  Values
    are in normalized fit space (multiply the mean by ``y_scale``).
    """
    mean_t, var_t = _ar_tables(params, p)  # (K+1, S) each
    dayf = day_all.astype(jnp.float32)
    h_raw = jnp.round(dayf[None, :] - params.ar_last_day[:, None]).astype(
        jnp.int32
    )  # (S, T_all)
    h_idx = jnp.clip(h_raw, 0, _AR_TABLE_LEN)
    within = h_raw <= _AR_TABLE_LEN
    fut = (dayf[None, :] > t_end) & (h_raw > 0)
    # beyond the table the mean is ZEROED, not frozen at its lead-K value:
    # for a near-unit-root phi the table end still carries a material
    # offset, and freezing it would contradict the decay-to-plain-forecast
    # contract; the variance falls back to the marginal residual variance
    mean = jnp.where(
        fut & within, jnp.take_along_axis(mean_t.T, h_idx, axis=1), 0.0
    )
    var = jnp.where(
        within,
        jnp.take_along_axis(var_t.T, h_idx, axis=1),
        params.sigma[:, None] ** 2,
    )
    return mean, var, fut


def _predictive(params: CurveParams, day_all, t_end, config, key, xreg):
    """Fit-space predictive distribution over ``day_all``.

    Returns ``(zhat, sd, paths)``: point path (S, T_all) plus either the
    analytic predictive sd (S, T_all) with ``paths=None`` (default), or
    Monte-Carlo sample paths (S, N, T_all) with ``sd=None`` when
    ``config.uncertainty_samples > 0``.  Shared by ``forecast`` (central
    interval) and ``forecast_quantiles`` (arbitrary quantile grid).
    """
    X, layout = _design(day_all, params.t0, params.t1, config)
    # base design stays SHARED (T_all, F0) even with per-series regressors:
    # the regressor contribution is a rank-R inner product added on top, so
    # the (S, T_all, F) per-series design the fit needs for its Gram never
    # materializes here (at serving scale that tensor would be tens of GB)
    F0 = layout["n_features"]
    zhat = (params.beta[:, :F0] @ X.T) * params.y_scale[:, None]  # (S, T_all)
    if _check_xreg(xreg, config, "forecast"):
        zhat = zhat + _regressor_contrib(params, xreg, F0) * params.y_scale[:, None]
    t_all = scaled_time(day_all, params.t0, params.t1)
    t_end_scaled = (t_end - params.t0) / jnp.maximum(params.t1 - params.t0, 1.0)

    var_obs = params.sigma[:, None] ** 2  # marginal residual variance
    if config.ar_order > 0:
        ar_mean, ar_var, fut = _ar_correction(
            params, day_all, t_end, config.ar_order
        )
        zhat = zhat + ar_mean * params.y_scale[:, None]
        var_obs = jnp.where(fut, ar_var, var_obs)

    if config.uncertainty_samples > 0:
        dev = _trend_deviation_samples(params, t_all, t_end_scaled, config, key)
        noise = (
            jax.random.normal(jax.random.fold_in(key, 1), shape=dev.shape)
            * (jnp.sqrt(var_obs) * params.y_scale[:, None])[:, None, :]
        )
        paths = zhat[:, None, :] + dev * params.y_scale[:, None, None] + noise
        return zhat, None, paths
    var_dev = _trend_deviation_variance(params, t_all, t_end_scaled, config)
    sd = jnp.sqrt(var_dev + var_obs) * params.y_scale[:, None]
    return zhat, sd, None


def _to_data_space(v, params: CurveParams, config):
    """Map fit-space values back to data space.  Monotone transforms, so
    quantiles in fit space ARE quantiles in data space.  Broadcasts over any
    trailing axes (v leads with S)."""
    if config.growth == "logistic":
        cap = params.cap.reshape((-1,) + (1,) * (v.ndim - 1))
        floor = float(config.floor_value)
        return floor + (cap - floor) * jax.nn.sigmoid(v)
    if config.seasonality_mode == "multiplicative":
        return jnp.exp(v)
    return v


@partial(jax.jit, static_argnames=("config",))
def forecast(
    params: CurveParams,
    day_all,
    t_end,
    config: CurveModelConfig,
    key=None,
    xreg=None,
):
    """Predict over ``day_all`` (history+future), intervals included.

    Mirrors ``make_future_dataframe(periods=90, freq='d',
    include_history=True)`` -> ``model.predict`` (reference
    ``02_training.py:201-205``).  Returns (yhat, lo, hi): (S, T_all).

    ``xreg``: regressor values over ``day_all`` — (T_all, R) or
    (S, T_all, R); required iff config.n_regressors > 0 (future covariate
    values must be known, exactly as with Prophet's ``add_regressor``).
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    zhat, sd, paths = _predictive(params, day_all, t_end, config, key, xreg)
    if paths is not None:
        alpha = (1.0 - config.interval_width) / 2.0
        qs = jnp.quantile(paths, jnp.asarray([alpha, 1.0 - alpha]), axis=1)
        lo, hi = qs[0], qs[1]
    else:
        z = ndtri(0.5 + config.interval_width / 2.0)
        lo = zhat - z * sd
        hi = zhat + z * sd
    return (
        _to_data_space(zhat, params, config),
        _to_data_space(lo, params, config),
        _to_data_space(hi, params, config),
    )


@partial(jax.jit, static_argnames=("config", "quantiles"))
def forecast_quantiles(
    params: CurveParams,
    day_all,
    t_end,
    config: CurveModelConfig,
    quantiles: tuple = (0.1, 0.5, 0.9),
    key=None,
    xreg=None,
):
    """Arbitrary forecast quantiles (M5-style probabilistic output).

    ``quantiles``: static tuple of levels in (0, 1).  Returns
    (S, Q, T_all), non-decreasing along Q.  The analytic path prices every
    quantile from the same closed-form predictive sd (one ndtri per level
    — virtually free); the Monte-Carlo path (``uncertainty_samples > 0``)
    takes empirical quantiles over the sampled trend+noise paths.  The
    data-space transforms (exp / logistic) are monotone, so fit-space
    quantiles map through exactly.
    """
    if not quantiles or not all(0.0 < q < 1.0 for q in quantiles):
        raise ValueError(f"quantiles must lie in (0, 1), got {quantiles!r}")
    if key is None:
        key = jax.random.PRNGKey(0)
    zhat, sd, paths = _predictive(params, day_all, t_end, config, key, xreg)
    qs = jnp.asarray(quantiles, jnp.float32)
    if paths is not None:
        zq = jnp.moveaxis(jnp.quantile(paths, qs, axis=1), 0, 1)  # (S, Q, T)
    else:
        zq = zhat[:, None, :] + ndtri(qs)[None, :, None] * sd[:, None, :]
    return _to_data_space(zq, params, config)


@partial(jax.jit, static_argnames=("config",))
def decompose(params: CurveParams, day_all, config: CurveModelConfig,
              xreg=None, t_end=None):
    """Per-component contributions over ``day_all`` — the tabular analogue
    of Prophet's component columns (trend/weekly/yearly/holidays, plus
    regressors here).  Returns a dict name -> (S, T_all) in FIT SPACE,
    scaled so the components sum to the fit-space point path: under
    additive seasonality they sum to yhat directly; under multiplicative
    (log-space) mode ``exp(component)`` is that component's multiplicative
    factor on the forecast.

    ``xreg`` is OPTIONAL even for a regressor-fit model: the trend and
    seasonal panels never need covariate values, so omitting it just
    omits the ``regressors`` component (components then sum to the path
    minus the regressor effect).

    ``t_end``: forecast-start day, required to include the ``ar``
    component when ``config.ar_order > 0`` (the AR correction is a
    forecast-time term anchored at the forecast start, not a design
    column); omitting it omits that component the same way omitting
    ``xreg`` omits the regressor one.
    """
    X, layout = _design(day_all, params.t0, params.t1, config)
    ys = params.y_scale[:, None]
    comps = {}
    tr = slice(0, 2 + _n_cp(config))
    comps["trend"] = (params.beta[:, tr] @ X[:, tr].T) * ys
    extra_names = tuple(
        str(e[0]) for e in config.extra_seasonalities
    )
    for name, key in (
        [(n, n) for n in ("weekly", "yearly", "holidays")]
        + [(n, f"seas_{n}") for n in extra_names]
    ):
        sl = layout.get(key)
        if sl is not None and (sl.stop - sl.start) > 0:
            comps[name] = (params.beta[:, sl] @ X[:, sl].T) * ys
    if xreg is not None:
        if config.n_regressors == 0:
            raise ValueError(
                "xreg passed but config.n_regressors == 0"
            )
        xreg = jnp.asarray(xreg, jnp.float32)
        if xreg.shape[-1] != config.n_regressors:
            raise ValueError(
                f"xreg has {xreg.shape[-1]} columns, config.n_regressors="
                f"{config.n_regressors}"
            )
        if xreg.shape[-2] != day_all.shape[0]:
            raise ValueError(
                f"xreg time axis is {xreg.shape[-2]}, expected "
                f"len(day_all) = {day_all.shape[0]}"
            )
        comps["regressors"] = (
            _regressor_contrib(params, xreg, layout["n_features"]) * ys
        )
    if config.ar_order > 0 and t_end is not None:
        ar_mean, _, _ = _ar_correction(params, day_all, t_end,
                                       config.ar_order)
        comps["ar"] = ar_mean * ys
    return comps


def component_frame(batch, params: CurveParams, config: CurveModelConfig,
                    horizon: int = 0, xreg=None):
    """Long component table ``[ds, *keys, trend, weekly, yearly, ...]`` over
    history + ``horizon`` days — what Prophet's ``predict`` output carries in
    its component columns.  Values are fit-space contributions (see
    :func:`decompose`)."""
    import numpy as np
    import pandas as pd

    from distributed_forecasting_tpu.engine.fit import (
        day_grid,
        long_frame_skeleton,
    )

    day_all = day_grid(batch.day, horizon)
    comps = decompose(params, day_all, config, xreg=xreg,
                      t_end=batch.day[-1].astype(jnp.float32))
    frame = long_frame_skeleton(batch.keys, batch.key_names, day_all,
                                freq=batch.freq)
    for name, vals in comps.items():
        frame[name] = np.asarray(vals).reshape(-1)
    return pd.DataFrame(frame)


def extract_params(params: CurveParams, config: CurveModelConfig) -> dict:
    """Loggable scalar params per series — the analogue of the reference's
    ``extract_params`` pulling Prophet's SIMPLE_ATTRIBUTES
    (``02_training.py:146-147``)."""
    return {
        "growth": config.growth,
        "n_changepoints": _n_cp(config),
        "explicit_changepoints": bool(config.changepoint_days),
        "changepoint_range": config.changepoint_range,
        "changepoint_prior_scale": config.changepoint_prior_scale,
        "seasonality_prior_scale": config.seasonality_prior_scale,
        "seasonality_mode": config.seasonality_mode,
        "interval_width": config.interval_width,
        "weekly_order": config.weekly_order,
        "yearly_order": config.yearly_order,
        "extra_seasonalities": ",".join(
            f"{n}:{p}:{o}" + (f":{ps}" if ps is not None else "")
            for n, p, o, ps in _extra_entries(config)
        ) or "none",
        "uncertainty_samples": config.uncertainty_samples,
        "n_holidays": len(config.holidays),
        "holiday_prior_scale": config.holiday_prior_scale,
        "n_regressors": config.n_regressors,
        "regressor_prior_scale": config.regressor_prior_scale,
        "ar_order": config.ar_order,
    }


@dataclasses.dataclass(frozen=True)
class CurveModelConfigAR(CurveModelConfig):
    """Curve model with AR-on-residuals ON by default — registered as the
    ``prophet_ar`` family so auto-selection (`engine/select.py`) can race
    the plain and AR-augmented curve per series:
    ``families=("prophet", "prophet_ar", ...)``."""

    ar_order: int = 1


register_model("prophet_ar", fit, forecast, CurveModelConfigAR,
               supports_xreg=True, forecast_quantiles=forecast_quantiles)
register_model("prophet", fit, forecast, CurveModelConfig, supports_xreg=True,
               forecast_quantiles=forecast_quantiles)
register_model("curve", fit, forecast, CurveModelConfig, supports_xreg=True,
               forecast_quantiles=forecast_quantiles)
