"""Batched ARIMA(p,d,q) via a state-space Kalman filter in ``lax.scan``.

BASELINE config #3: "500 series, batched ARIMA(p,d,q) state-space Kalman
filter (vmap)".  The reference has no ARIMA itself — it is in the driver
target set as the state-space member of the model zoo; the native-kernel
analogy still holds: where Prophet's fits run Stan's C++ L-BFGS per series
(reference ``notebooks/prophet/02_training.py:172``), here estimation is
batched linear algebra.

Two fit methods (``ArimaConfig.method``):
  * ``'hr'`` (default): closed-form Hannan-Rissanen — Yule-Walker long-AR,
    innovation extraction, one ridge regression; three MXU-shaped solves
    with zero optimizer serial depth (500x1826 fits: 0.28s vs 30.8s for
    'mle' on CPU), then ONE Kalman pass for sigma2/states/fitted path.
  * ``'mle'``: exact Gaussian likelihood evaluated by the Kalman recursion
    and maximized with a fixed-iteration optax Adam loop — tighter
    estimates, serial depth fit_steps x T.

Implementation notes:
  * Harvey representation of ARMA(p, q): state dim r = max(p, q+1),
    transition T has phi in the first column and an identity shift block,
    R = (1, theta_1..theta_q, 0..), Z = e_1, no separate observation noise.
  * Stationarity/invertibility enforced by the tanh/Durbin-Levinson
    reparameterization (Monahan 1984) of partial autocorrelations — the
    optimizer runs unconstrained.
  * d in {0, 1}: first-difference the masked series, forecast in the
    differenced space, integrate back with cumsum from the last observed
    level.
  * Missing values: the filter propagates without the measurement update via
    ``jnp.where`` — exactly how state-space models handle gaps.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import gaussian_quantiles, register_model
from distributed_forecasting_tpu.ops.solve import solve_dense, yule_walker_masked

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class ArimaConfig:
    p: int = 2
    d: int = 1
    q: int = 1
    # Seasonal (SARMA) terms: AR/MA lags at multiples of ``m`` — the subset
    # form phi_1..phi_p plus Phi_1 B^m..Phi_P B^{Pm} (additive, not the
    # multiplicative Box-Jenkins product; estimated for free by the HR
    # regression as extra lag features).  Requires method='hr'.
    P: int = 0
    Q: int = 0
    m: int = 7  # seasonal period (daily data: weekly)
    interval_width: float = 0.95
    # 'hr' (default): closed-form Hannan-Rissanen — long-AR Yule-Walker +
    # two batched ridge solves, all MXU matmuls, no optimizer loop.  'mle':
    # fixed-iteration Adam on the exact Kalman likelihood (tighter estimates,
    # ~2 orders of magnitude more serial depth: fit_steps x T sequential
    # scan steps — measured 30.8s vs <1s at 500x1826 on CPU).
    method: str = "hr"  # 'hr' | 'mle'
    # long-AR order for the HR innovation estimate
    hr_ar_order: int = 20
    fit_steps: int = 200
    learning_rate: float = 0.05
    # Gaussian prior on the unconstrained (atanh-PACF) parameters: keeps MAP
    # solutions off the |pacf|->1 stationarity boundary, where predict-only
    # propagation decays so slowly that integrated d=1 forecasts can wander
    # thousands of sigma before settling (observed under vmapped CV fits)
    prior_scale: float = 1.0
    # Final filtering pass: 'scan' (default) = sequential lax.scan Kalman
    # filter; 'pscan' = associative-scan parallel filter (ops/pkalman.py) —
    # O(log T) parallel depth instead of T sequential steps, results match
    # to float tolerance (tests/unit/test_pkalman.py).  The default follows
    # the measurement (docs/benchmarks.md): at 500 x 1826 on TPU v5e with
    # the slope protocol, 'scan' runs the full fit in ~62 ms/batch vs
    # ~1140 ms for 'pscan' — 500 series already fill the chip, so trading
    # sequential depth for O(T log T) 3x3-matrix composition work loses
    # ~18x.  'pscan' remains the few-series x very-long-T option.  The MLE
    # path's likelihood loop keeps the sequential filter regardless.
    kalman: str = "scan"  # 'scan' | 'pscan'


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ArimaParams:
    phi: jax.Array        # (S, p) AR coefficients
    theta: jax.Array      # (S, q) MA coefficients
    sigma2: jax.Array     # (S,) innovation variance (differenced space)
    mean: jax.Array       # (S,) mean of the differenced series
    a_last: jax.Array     # (S, r) final filtered state
    P_last: jax.Array     # (S, r, r) final state covariance
    level_end: jax.Array  # (S,) level at the fit-grid end: last observed y,
                          # or the carried-forward predicted level if the
                          # grid ends in an unobserved stretch (d=1)
    var_end: jax.Array    # (S,) accumulated level variance at the grid end
                          # (0 if the last grid day was observed)
    fitted: jax.Array     # (S, T) one-step fitted values on the ORIGINAL grid
    fitted_var: jax.Array  # (S, T) predictive variance of `fitted` (widens
                           # over unobserved stretches, e.g. CV eval windows)
    day0: jax.Array       # () first training day
    t_fit_end: jax.Array  # () last training day


def _pacf_stack(r: jnp.ndarray) -> jnp.ndarray:
    """Durbin-Levinson: PACF sequence (k,) in (-1,1) -> AR coefficients.
    k is static and tiny, so a Python loop unrolls fine under jit."""
    k = r.shape[0]
    coef = jnp.zeros_like(r)
    for j in range(k):
        prev = coef[:j]
        new = prev - r[j] * prev[::-1]
        coef = coef.at[:j].set(new).at[j].set(r[j])
    return coef


def _pacf_to_coef(u: jnp.ndarray) -> jnp.ndarray:
    """Monahan map: unconstrained (k,) -> stationary AR coefficients via
    tanh -> PACF -> Durbin-Levinson."""
    return _pacf_stack(jnp.tanh(u))


def _coef_to_pacf(c: jnp.ndarray) -> jnp.ndarray:
    """Inverse Durbin-Levinson: AR coefficients (k,) -> PACF sequence.

    The reverse recursion divides by (1 - pac_j^2); clamped so a numerically
    non-stationary input degrades instead of producing inf/nan.
    """
    k = c.shape[0]
    pac = jnp.zeros_like(c)
    cur = c
    for j in range(k - 1, -1, -1):
        pj = cur[j]
        pac = pac.at[j].set(pj)
        if j > 0:
            prev = cur[:j]
            denom = jnp.maximum(1.0 - pj**2, 1e-6)
            cur = (prev + pj * prev[::-1]) / denom
    return pac


def _stabilize(c: jnp.ndarray, limit: float = 0.97) -> jnp.ndarray:
    """Project coefficients to the stationary/invertible region by clipping
    their PACF representation — identity for interior points, a gentle
    shrink for boundary/exterior ones (unlike naive |coef|-sum scaling,
    which would distort legitimate near-unit-root AR fits)."""
    if c.shape[0] == 0:
        return c
    return _pacf_stack(jnp.clip(_coef_to_pacf(c), -limit, limit))


def _build_ssm(phi, theta, r):
    """Transition T (r,r), disturbance loading R (r,) for Harvey's ARMA form."""
    p, q = phi.shape[0], theta.shape[0]
    T = jnp.zeros((r, r))
    T = T.at[:p, 0].set(phi)
    T = T.at[:-1, 1:].set(jnp.eye(r - 1))
    Rv = jnp.zeros((r,)).at[0].set(1.0)
    if q:
        Rv = Rv.at[1 : 1 + q].set(theta)
    return T, Rv


def _init_cov(T, RRt, n_iter=30):
    """Stationary covariance by fixed-point iteration of the Lyapunov
    equation P = T P T' + RR' (converges geometrically for stationary T).
    float32 matmuls: 30 chained products at the TPU's bfloat16 default
    would hand every downstream filter a drifted P0."""
    def body(P, _):
        return T @ P @ T.T + RRt, None

    with jax.default_matmul_precision("float32"):
        P, _ = jax.lax.scan(body, RRt, None, length=n_iter)
    return P


def _kalman_loglik(z, mask, phi, theta, r):
    """Filter one differenced series; unit innovation variance (sigma2 is
    concentrated out).  Returns (ssq, ldet, n, preds, Fs, a_T, P_T).

    Matmuls run at float32 precision: the TPU MXU bfloat16 default drifts
    the covariance recursion over ~1.8k steps, and the parallel-scan
    variant (``ops/pkalman``) holds the same precision so the two filters
    agree on hardware (integration tier, round 3).  FLOPs at r <= ~10 are
    negligible either way.  Excluded from the ops/precision.py bf16 gate:
    the loglik feeds gradient-free optimization whose convergence test is
    tighter than bf16 resolution."""
    with jax.default_matmul_precision("float32"):
        return _kalman_loglik_impl(z, mask, phi, theta, r)


def _kalman_loglik_impl(z, mask, phi, theta, r):
    T_mat, Rv = _build_ssm(phi, theta, r)
    RRt = jnp.outer(Rv, Rv)
    P0 = _init_cov(T_mat, RRt)
    # data-derived zeros keep the scan carry's varying type consistent under
    # shard_map (see holt_winters._filter)
    zero = jnp.sum(z) * 0.0
    a0 = jnp.zeros((r,)) + zero

    def step(carry, inp):
        a, P, ssq, ldet, n = carry
        zt, mt = inp
        pred = a[0]
        F = jnp.maximum(P[0, 0], _EPS)
        v = zt - pred
        K = (T_mat @ P[:, 0]) / F
        a_obs = T_mat @ a + K * v
        P_obs = T_mat @ P @ T_mat.T + RRt - jnp.outer(K, K) * F
        a_pred = T_mat @ a
        P_pred = T_mat @ P @ T_mat.T + RRt
        a_new = jnp.where(mt > 0, a_obs, a_pred)
        P_new = jnp.where(mt > 0, P_obs, P_pred)
        ssq = ssq + jnp.where(mt > 0, v**2 / F, 0.0)
        ldet = ldet + jnp.where(mt > 0, jnp.log(F), 0.0)
        return (a_new, P_new, ssq, ldet, n + mt), (pred, F)

    (a_T, P_T, ssq, ldet, n), (preds, Fs) = jax.lax.scan(
        step, (a0, P0, zero, zero, zero), (z, mask)
    )
    return ssq, ldet, n, preds, Fs, a_T, P_T


def _lag(x, k: int):
    """Time shift: out[:, t] = x[:, t-k], zero-filled at the front."""
    if k == 0:
        return x
    return jnp.pad(x, ((0, 0), (k, 0)))[:, : x.shape[1]]


def _lag_sets(config: ArimaConfig):
    """AR / MA lag sets incl. seasonal terms, deduplicated and sorted, plus
    the effective (dense) polynomial orders they scatter into."""
    if (config.P > 0 or config.Q > 0) and config.m < 1:
        # m=0 would make the seasonal term a lag-0 regressor (the target
        # regressing on itself) and scatter its coefficient to index -1 —
        # a silently corrupt fit rather than an error
        raise ValueError(
            f"seasonal orders P={config.P}/Q={config.Q} require a seasonal "
            f"period m >= 1, got m={config.m}"
        )
    ar = sorted(
        set(range(1, config.p + 1))
        | {config.m * i for i in range(1, config.P + 1)}
    )
    ma = sorted(
        set(range(1, config.q + 1))
        | {config.m * j for j in range(1, config.Q + 1)}
    )
    p_eff = ar[-1] if ar else 0
    q_eff = ma[-1] if ma else 0
    return ar, ma, p_eff, q_eff


def _effective_r(config: ArimaConfig) -> int:
    _, _, p_eff, q_eff = _lag_sets(config)
    return max(p_eff, q_eff + 1, 1)


def _hr_regression(z, m, ar_lags, ma_lags, K: int, ridge: float = 1e-4):
    """The Hannan-Rissanen regression core, exposed as sufficient statistics.

    z/m: centered differenced series + validity mask, (S, T) — S being any
    batch axis (whole series, or flattened series x windows for the
    DARIMA split-and-combine path, engine/windowed.py).  Returns

      coef  (S, F): regression coefficients over the lag-set feature basis
                    ``ar_lags + ma_lags`` (RAW — no PACF projection);
      gram  (S, F, F): the ridged normal matrix X'X — the observed
                    information (up to sigma2), which is exactly the
                    inverse-covariance weight the DARIMA WLS combine needs
                    (arXiv 2007.09577 eq. 10: Sigma_k^{-1} ∝ X_k'X_k);
      n_valid (S,): rows with every lag feature observed;
      sigma2  (S,): residual variance of the regression — the per-window
                    noise scale that divides the gram into a precision.
    """
    S, T = z.shape
    zm = z * m
    # masked series variance: also scales the ridge of the MA regression
    g0 = jnp.maximum(
        jnp.sum(zm * zm, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0),
        _EPS,
    )
    a, _rho = yule_walker_masked(
        z, m, K, per_lag_norm=True, jitter_abs=ridge, eps=_EPS
    )  # (S, K)

    e = zm
    evalid = m
    for i in range(1, K + 1):
        e = e - a[:, i - 1 : i] * _lag(zm, i)
        evalid = evalid * _lag(m, i)
    e = e * evalid

    F = len(ar_lags) + len(ma_lags)
    if F == 0:
        zero_s = jnp.zeros((S,))
        return (jnp.zeros((S, 0)), jnp.zeros((S, 0, 0)), zero_s + 1.0,
                jnp.maximum(g0, _EPS))
    feats = [_lag(zm, i) for i in ar_lags] + [_lag(e, j) for j in ma_lags]
    valid = m
    for i in ar_lags:
        valid = valid * _lag(m, i)
    for j in ma_lags:
        valid = valid * _lag(evalid, j)
    X = jnp.stack(feats, axis=2) * valid[..., None]  # (S, T, F)
    zv = zm * valid
    n_valid = jnp.maximum(jnp.sum(valid, axis=1), 1.0)
    G = jnp.einsum("stf,stg->sfg", X, X, optimize=True)
    G = G + (ridge * g0 * n_valid)[:, None, None] * jnp.eye(F)[None]
    b = jnp.einsum("stf,st->sf", X, zv, optimize=True)
    coef = solve_dense(G, b)
    resid = zv - jnp.einsum("stf,sf->st", X, coef, optimize=True) * valid
    sigma2 = jnp.maximum(
        jnp.sum(resid * resid, axis=1) / n_valid, _EPS)
    return coef, G, n_valid, sigma2


def coef_to_poly(coef, ar_lags, ma_lags, p_eff: int, q_eff: int):
    """Scatter lag-set regression coefficients (S, F) into dense stabilized
    (phi (S, p_eff), theta (S, q_eff)) polynomials — the shared tail of the
    HR fit, reused verbatim by the windowed WLS-combine path so combined
    coefficients land in the exact same stationary/invertible region."""
    S = coef.shape[0]
    nar = len(ar_lags)
    phi = jnp.zeros((S, p_eff))
    for col, lag in enumerate(ar_lags):
        phi = phi.at[:, lag - 1].set(coef[:, col])
    theta = jnp.zeros((S, q_eff))
    for col, lag in enumerate(ma_lags):
        theta = theta.at[:, lag - 1].set(coef[:, nar + col])
    # PACF-clip projection (identity for interior points, sparsity included)
    if p_eff:
        phi = jax.vmap(_stabilize)(phi)
    if q_eff:
        theta = jax.vmap(_stabilize)(theta)
    return phi, theta


def _hannan_rissanen(z, m, ar_lags, ma_lags, p_eff: int, q_eff: int, K: int,
                     ridge: float = 1e-4):
    """Closed-form batched (S)ARMA estimation (Hannan-Rissanen).

    The TPU-first fit: where the 'mle' path runs fit_steps sequential Adam
    iterations of a T-step Kalman scan (serial depth fit_steps x T), this is
    three batched linear-algebra steps, all MXU-shaped:

      1. long-AR(K) by Yule-Walker on masked pairwise autocorrelations —
         one (S, K, K) Toeplitz solve;
      2. innovations e_t = z_t - sum_i a_i z_{t-i} from K static lag shifts;
      3. regression of z_t on the AR lag set + innovation lag set — one
         (S, F, F) ridge solve.  Seasonal (SARMA) terms are just more lags
         in the sets (``_lag_sets``), at zero extra structure;

    followed by a PACF-clip projection into the stationary/invertible
    region.  Returns dense polynomials (phi (S, p_eff), theta (S, q_eff))
    with the non-lag positions zero.
    """
    S = z.shape[0]
    F = len(ar_lags) + len(ma_lags)
    if F == 0:
        return jnp.zeros((S, 0)), jnp.zeros((S, 0))
    coef, _G, _n, _s2 = _hr_regression(z, m, ar_lags, ma_lags, K, ridge)
    return coef_to_poly(coef, ar_lags, ma_lags, p_eff, q_eff)


def _difference(y, mask, d):
    if d == 0:
        return y, mask
    z = y[:, 1:] - y[:, :-1]
    m = mask[:, 1:] * mask[:, :-1]
    z = jnp.pad(z * m, ((0, 0), (1, 0)))
    m = jnp.pad(m, ((0, 0), (1, 0)))
    return z, m


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: ArimaConfig) -> ArimaParams:
    p, d, q = config.p, config.d, config.q
    ar_lags, ma_lags, p_eff, q_eff = _lag_sets(config)
    r = _effective_r(config)
    z, zmask = _difference(y, mask, d)
    n_obs = jnp.maximum(zmask.sum(axis=1), 1.0)
    mean = (z * zmask).sum(axis=1) / n_obs
    zc = (z - mean[:, None]) * zmask

    if config.method == "hr":
        K = max(config.hr_ar_order, p_eff + q_eff + config.m)
        phi, theta = _hannan_rissanen(
            zc, zmask, ar_lags, ma_lags, p_eff, q_eff, K
        )
    elif config.method == "mle":
        if config.P or config.Q:
            raise ValueError(
                "seasonal (P, Q) terms require method='hr' — the MLE path's "
                "PACF parameterization is dense in the lag order"
            )
        def nll_one(u, zs, ms):
            phi = _pacf_to_coef(u[:p]) if p else jnp.zeros((0,))
            theta = _pacf_to_coef(u[p : p + q]) if q else jnp.zeros((0,))
            ssq, ldet, n, *_ = _kalman_loglik(zs, ms, phi, theta, r)
            n = jnp.maximum(n, 1.0)
            # concentrated Gaussian NLL + MAP prior (see ArimaConfig.prior_scale)
            prior = 0.5 * jnp.sum((u / config.prior_scale) ** 2)
            return 0.5 * n * jnp.log(jnp.maximum(ssq / n, _EPS)) + 0.5 * ldet + prior

        u0 = jnp.zeros((y.shape[0], p + q))
        opt = optax.adam(config.learning_rate)

        def fit_one(u, zs, ms):
            state = opt.init(u)
            grad_fn = jax.value_and_grad(nll_one)

            def step_fn(carry, _):
                u, state = carry
                val, g = grad_fn(u, zs, ms)
                g = jnp.where(jnp.isfinite(g), g, 0.0)
                updates, state = opt.update(g, state)
                return (optax.apply_updates(u, updates), state), val

            (u, _), _ = jax.lax.scan(step_fn, (u, state), None, length=config.fit_steps)
            return u

        u = jax.vmap(fit_one)(u0, zc, zmask)
        phi = jax.vmap(lambda uu: _pacf_to_coef(uu[:p]) if p else jnp.zeros((0,)))(u)
        theta = jax.vmap(lambda uu: _pacf_to_coef(uu[p : p + q]) if q else jnp.zeros((0,)))(u)
    else:
        raise ValueError(f"unknown ARIMA fit method {config.method!r}; 'hr' or 'mle'")

    return _finalize(y, mask, day, config, phi, theta, mean, zc, zmask)


def _finalize(y, mask, day, config: ArimaConfig, phi, theta, mean, zc, zmask):
    """Post-estimation tail of ``fit``: one Kalman pass for sigma2 / final
    states / one-step fitted path, then d=1 integration.  Shared by the
    whole-series fit above and the windowed path (engine/windowed.py), which
    runs it over the TAIL window only with externally-combined phi/theta."""
    d = config.d
    r = _effective_r(config)
    if config.kalman == "pscan":
        from distributed_forecasting_tpu.ops.pkalman import parallel_kalman_filter

        def filt(zs, ms, ph, th):
            T_mat, Rv = _build_ssm(ph, th, r)
            RRt = jnp.outer(Rv, Rv)
            return parallel_kalman_filter(zs, ms, T_mat, RRt, _init_cov(T_mat, RRt))
    elif config.kalman == "scan":
        filt = lambda zs, ms, ph, th: _kalman_loglik(zs, ms, ph, th, r)
    else:
        raise ValueError(
            f"unknown ArimaConfig.kalman {config.kalman!r}; 'scan' or 'pscan'"
        )

    def final_one(zs, ms, ph, th):
        ssq, ldet, n, preds, Fs, a_T, P_T = filt(zs, ms, ph, th)
        sigma2 = ssq / jnp.maximum(n, 1.0)
        return sigma2, preds, Fs, a_T, P_T

    sigma2, zpreds, Fs, a_T, P_T = jax.vmap(final_one)(zc, zmask, phi, theta)

    # fitted values on the original scale: undiff one-step preds.  Integration
    # must NOT read the actual y over unobserved stretches (mask==0: data
    # gaps, and CV eval windows where it would leak the answer) — carry the
    # fitted level forward instead, accumulating variance random-walk style.
    zhat = zpreds + mean[:, None]
    if d == 1:
        def integrate_one(ys, ms, zh, Fv, s2):
            def step(carry, inp):
                lvl, var = carry
                yt, mt, zt, ft = inp
                mean_t = lvl + zt
                var_t = var + ft * s2
                lvl_new = jnp.where(mt > 0, yt, mean_t)
                var_new = jnp.where(mt > 0, 0.0 * var_t, var_t)
                return (lvl_new, var_new), (mean_t, var_t)

            zero = jnp.sum(ys) * 0.0
            # seed the level from the FIRST OBSERVED value, not ys[0]: a
            # leading padded stretch (mask==0) would otherwise anchor the
            # fitted path at the padding zero until the first real day
            y_first = ys[jnp.argmax(ms)]
            (lvl_T, var_T), (means, vars_) = jax.lax.scan(
                step, (y_first, zero), (ys, ms, zh, Fv)
            )
            return means, vars_, lvl_T, var_T

        fitted, fitted_var, level_end, var_end = jax.vmap(integrate_one)(
            y, mask, zhat, Fs, sigma2
        )
    else:
        fitted = zhat
        fitted_var = Fs * sigma2[:, None]
        level_end = jnp.zeros_like(sigma2)
        var_end = jnp.zeros_like(sigma2)
    return ArimaParams(
        phi=phi, theta=theta, sigma2=sigma2, mean=mean,
        a_last=a_T, P_last=P_T, level_end=level_end, var_end=var_end,
        fitted=fitted, fitted_var=fitted_var,
        day0=day[0].astype(jnp.float32),
        t_fit_end=day[-1].astype(jnp.float32),
    )


@partial(jax.jit, static_argnames=("config",))
def window_stats(y, mask, config: ArimaConfig):
    """Per-window HR sufficient statistics for the DARIMA split-and-combine
    path (arXiv 2007.09577).  y/mask (B, W) are RAW windows — B is the
    flattened series x windows axis — differencing happens inside, exactly
    as in ``fit``.  Returns a dict of

      coef (B, F), gram (B, F, F), n_valid (B,), sigma2 (B,): the HR
        regression's sufficient statistics (see ``_hr_regression``);
      mean (B,), n_obs (B,): per-window differenced-space mean + count, so
        the combine can reconstruct the precision-weighted global mean.

    Every array is O(F^2) per window — the (B, W) data stays on device and
    only these small statistics flow into the combine solve.
    """
    if config.method != "hr":
        raise ValueError(
            "windowed fitting requires ArimaConfig.method='hr' — the MLE "
            "path has no closed-form sufficient statistics to combine"
        )
    ar_lags, ma_lags, p_eff, q_eff = _lag_sets(config)
    z, zmask = _difference(y, mask, config.d)
    n_obs = jnp.maximum(zmask.sum(axis=1), 1.0)
    mean = (z * zmask).sum(axis=1) / n_obs
    zc = (z - mean[:, None]) * zmask
    K = max(config.hr_ar_order, p_eff + q_eff + config.m)
    coef, gram, n_valid, sigma2 = _hr_regression(zc, zmask, ar_lags, ma_lags, K)
    return {
        "coef": coef, "gram": gram, "n_valid": n_valid, "sigma2": sigma2,
        "mean": mean, "n_obs": n_obs,
    }


@partial(jax.jit, static_argnames=("config",))
def params_from_estimates(y, mask, day, config: ArimaConfig, phi, theta, mean):
    """Build full ``ArimaParams`` from externally-estimated coefficients by
    running only the post-estimation Kalman/integration tail over (y, mask,
    day).  The windowed path calls this on the TAIL window with the
    WLS-combined phi/theta/mean: the resulting params are anchored at the
    tail (day0 = tail start), so ``forecast`` routes through the existing
    predictor unchanged and never scans the full T axis."""
    z, zmask = _difference(y, mask, config.d)
    zc = (z - mean[:, None]) * zmask
    return _finalize(y, mask, day, config, phi, theta, mean, zc, zmask)


@partial(jax.jit, static_argnames=("config", "_r"))
def _forecast_impl(params: ArimaParams, day_all, config: ArimaConfig, _r: int):
    p, d, q = config.p, config.d, config.q
    S = params.sigma2.shape[0]
    T_all = day_all.shape[0]
    dayf = day_all.astype(jnp.float32)
    h = dayf - params.t_fit_end
    # Forecast-path length (static).  CONTRACT: day_all is a contiguous
    # daily grid, and any grid LONGER than the fit grid must start at day0
    # (i.e. cover history+future — every in-repo caller does: the engine
    # uses day_grid and the serving predictor always forecasts the full grid
    # and trims, serving/predictor.py).  Under that contract the max lead is
    # T_all - T_fit for long grids and at most T_all for short (future-only)
    # ones.  Scanning the full T_all for a history+future grid (the hot
    # engine path) would spend ~20x the steps on leads the gather below
    # clips away — at 500x1826 that was ~20 ms of pure serial scan depth
    # per batch.
    T_fit = params.fitted.shape[1]
    H = T_all - T_fit + 1 if T_all > T_fit else T_all

    def fc_one(ph, th, a0, P0, s2):
        T_mat, Rv = _build_ssm(ph, th, _r)
        RRt = jnp.outer(Rv, Rv)

        def step(carry, _):
            a, P = carry
            a2, P2 = T_mat @ a, T_mat @ P @ T_mat.T + RRt
            return (a2, P2), (a2[0], P2[0, 0])

        # float32: H chained covariance products (see _init_cov)
        with jax.default_matmul_precision("float32"):
            _, (zf, vf) = jax.lax.scan(step, (a0, P0), None, length=H)
        return zf, vf * s2

    zf, vf = jax.vmap(fc_one)(
        params.phi, params.theta, params.a_last, params.P_last, params.sigma2
    )  # (S, H) forecast of centered differenced series + variances
    zf = zf + params.mean[:, None]
    if d == 1:
        # integrate from the carried level/variance at the fit-grid end so
        # the future path continues the fitted path without a jump when the
        # grid ends in an unobserved stretch
        path = params.level_end[:, None] + jnp.cumsum(zf, axis=1)
        var = params.var_end[:, None] + jnp.cumsum(vf, axis=1)
    else:
        path, var = zf, vf

    hidx = jnp.clip(h.astype(jnp.int32) - 1, 0, H - 1)
    gath = lambda M: jnp.take_along_axis(
        M, jnp.broadcast_to(hidx[None, :], (S, T_all)), axis=1
    )
    fut_mean, fut_var = gath(path), gath(var)

    fit_idx = jnp.clip((dayf - params.day0).astype(jnp.int32), 0, T_fit - 1)
    gath_fit = lambda M: jnp.take_along_axis(
        M, jnp.broadcast_to(fit_idx[None, :], (S, T_all)), axis=1
    )
    hist = gath_fit(params.fitted)
    hist_var = gath_fit(params.fitted_var)
    is_future = (h > 0.0)[None, :]
    yhat = jnp.where(is_future, fut_mean, hist)
    sd = jnp.sqrt(jnp.where(is_future, fut_var, hist_var))
    z = ndtri(0.5 + config.interval_width / 2.0)
    return yhat, yhat - z * sd, yhat + z * sd


def forecast(params: ArimaParams, day_all, t_end, config: ArimaConfig, key=None):
    return _forecast_impl(params, day_all, config, _effective_r(config))


register_model("arima", fit, forecast, ArimaConfig,
               forecast_quantiles=gaussian_quantiles(forecast))
