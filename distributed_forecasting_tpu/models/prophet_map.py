"""Prophet MAP oracle — the reference model's EXACT objective, in-repo.

The reference's production model is the ``prophet`` package with this config
(reference ``notebooks/prophet/02_training.py:162-186``): linear growth,
weekly+yearly seasonality, multiplicative mode, 95% intervals, MAP fit via
Stan's L-BFGS (``cmdstan optimize``).  The prophet package cannot be
installed in the zero-egress TPU image (BASELINE.md records the parity
claim as unverified against the real package), so this module implements
the same generative model from its published specification (Taylor &
Letham, "Forecasting at scale", 2017; the Stan program shipped in
prophet) and fits it the same way — L-BFGS on the penalized joint density,
no Jacobian adjustment, matching ``cmdstan optimize``'s default:

  trend      g(t) = (k + A(t) delta) * t + (m + A(t) gamma),
             gamma_j = -s_j delta_j   (continuity at changepoints);
             25 changepoints uniform over the first 80% of history
  seasonal   X(t) beta, Fourier features: yearly period 365.25 order 10,
             weekly period 7 order 3 (t in absolute days, prophet's
             ``fourier_series``)
  model      y/scale ~ Normal(g(t) * (1 + X(t) beta), sigma)   [mult. mode]
  priors     delta ~ Laplace(0, 0.05); beta ~ Normal(0, 10);
             sigma ~ HalfNormal(0.5); k, m flat
  scaling    scale = max|y| (linear growth); t scaled to [0, 1] over the
             fit window

This is an ORACLE for accuracy measurement (scripts/prophet_parity.py
--oracle), not a production path: it fits one series at a time with scipy
L-BFGS-B over a float64 numpy objective with analytic gradients, exactly
because that is what Stan does (f64 L-BFGS) — and deliberately WITHOUT
touching the framework's JAX compute path, so the production
``models/prophet_glm`` batched estimator is measured against a fully
independent implementation.  It is also a DIFFERENT estimator (L1
changepoint posterior vs closed-form ridge), so the CV-MAPE delta
between them measures model-quality parity, not self-agreement.
"""

from __future__ import annotations

from dataclasses import dataclass
import numpy as np


@dataclass(frozen=True)
class ProphetMAPConfig:
    """Defaults == the prophet package's defaults under the reference's
    training config (multiplicative weekly+yearly, linear growth)."""

    n_changepoints: int = 25
    changepoint_range: float = 0.8
    changepoint_prior_scale: float = 0.05   # tau: Laplace scale on delta
    seasonality_prior_scale: float = 10.0   # sigma: Normal scale on beta
    yearly_order: int = 10
    weekly_order: int = 3
    sigma_prior_scale: float = 0.5          # HalfNormal scale on sigma_obs
    maxiter: int = 2000                     # cmdstan optimize default


@dataclass
class ProphetMAPParams:
    k: float
    m: float
    delta: np.ndarray       # (S,)
    beta: np.ndarray        # (F,)
    sigma: float
    t_change: np.ndarray    # (S,) changepoints in scaled time
    t0_days: float          # absolute day of the fit window's first point
    t_span_days: float      # fit window length in days (scaled-time unit)
    y_scale: float


def _fourier(t_days: np.ndarray, period: float, order: int) -> np.ndarray:
    """prophet's ``fourier_series``: t in absolute days; (T, 2*order)
    columns [sin(2*pi*1*t/P), cos(2*pi*1*t/P), sin(2*pi*2*t/P), ...]."""
    cols = []
    for n in range(1, order + 1):
        ang = 2.0 * np.pi * n * t_days / period
        cols.append(np.sin(ang))
        cols.append(np.cos(ang))
    return np.stack(cols, axis=1).astype(np.float64)


def _design(t_days: np.ndarray, cfg: ProphetMAPConfig) -> np.ndarray:
    return np.concatenate(
        [
            _fourier(t_days, 365.25, cfg.yearly_order),
            _fourier(t_days, 7.0, cfg.weekly_order),
        ],
        axis=1,
    )


def _changepoints(t_scaled: np.ndarray, cfg: ProphetMAPConfig) -> np.ndarray:
    """prophet's ``set_changepoints``: evenly spaced over the first
    ``changepoint_range`` of HISTORY ROWS, first point excluded."""
    T = t_scaled.shape[0]
    hist = int(np.floor(T * cfg.changepoint_range))
    n = min(cfg.n_changepoints, max(hist - 1, 1))
    idx = np.linspace(0, hist - 1, n + 1).round().astype(int)[1:]
    return t_scaled[idx].astype(np.float64)


def _objective_fn(t, A, A_s, X, y_s, tau: float, beta_sd: float,
                  sigma_sd: float):
    """Penalized joint density + analytic gradient, float64 numpy.

    Trend is (k + A delta) t + (m - A_s delta): A is the changepoint
    indicator matrix and A_s = A * t_change carries the continuity
    offsets gamma_j = -s_j delta_j.  Stan optimizes the same density in
    float64 L-BFGS; an earlier float32-JAX variant of this objective
    left the seasonal amplitudes ~25% short at L-BFGS-B's default
    tolerances.
    """
    T = t.shape[0]
    S = A.shape[1]
    F = X.shape[1]
    At_As = A * t[:, None] - A_s  # d(trend)/d(delta), (T, S)

    def f(theta):
        # errstate: a wild line-search step can underflow sigma to 0 (1/0
        # divide) or overflow mu; the non-finite guard below handles those
        # steps correctly, so the transient RuntimeWarnings are pure noise
        with np.errstate(divide="ignore", over="ignore", invalid="ignore"):
            k, m = theta[0], theta[1]
            delta = theta[2 : 2 + S]
            beta = theta[2 + S : 2 + S + F]
            log_sigma = theta[-1]
            sigma = np.exp(log_sigma)
            g = (k + A @ delta) * t + (m - A_s @ delta)
            season = 1.0 + X @ beta
            mu = g * season
            err = y_s - mu
            inv_s2 = 1.0 / sigma**2
            val = (
                0.5 * inv_s2 * float(err @ err)
                + T * log_sigma
                + float(np.sum(np.abs(delta))) / tau
                + 0.5 * float(beta @ beta) / beta_sd**2
                + 0.5 * sigma**2 / sigma_sd**2
            )
            if not np.isfinite(val):
                # a wild line-search step (sigma underflow / mu overflow):
                # return a huge finite value with a zero gradient so L-BFGS-B
                # backtracks instead of propagating NaNs into its history
                return 1e15, np.zeros_like(theta)
            dmu = -err * inv_s2          # dL/dmu, (T,)
            ds = dmu * season            # dL/d(trend)
            dg = dmu * g                 # dL/d(season term X beta)
            grad = np.empty_like(theta)
            grad[0] = float(ds @ t)
            grad[1] = float(np.sum(ds))
            grad[2 : 2 + S] = At_As.T @ ds + np.sign(delta) / tau
            grad[2 + S : 2 + S + F] = X.T @ dg + beta / beta_sd**2
            grad[-1] = -inv_s2 * float(err @ err) + T + sigma**2 / sigma_sd**2
            return val, grad

    return f


def fit_map(
    day: np.ndarray, y: np.ndarray, cfg: ProphetMAPConfig = ProphetMAPConfig()
) -> ProphetMAPParams:
    """MAP fit of one series.  ``day``: absolute integer day numbers
    (monotone, gaps allowed); ``y``: observations, same length."""
    from scipy.optimize import minimize

    day = np.asarray(day, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    t0, t1 = float(day[0]), float(day[-1])
    span = max(t1 - t0, 1.0)
    t = (day - t0) / span
    y_scale = max(float(np.max(np.abs(y))), 1e-10)
    y_s = y / y_scale

    t_change = _changepoints(t, cfg)
    S = t_change.shape[0]
    A = (t[:, None] >= t_change[None, :]).astype(np.float64)       # (T, S)
    A_s = A * t_change[None, :]                                    # (T, S)
    X = _design(day, cfg)                                          # (T, F)
    F = X.shape[1]

    # prophet's linear_growth_init
    k0 = (y_s[-1] - y_s[0]) / max(float(t[-1] - t[0]), 1e-10)
    m0 = y_s[0] - k0 * t[0]
    theta0 = np.zeros(2 + S + F + 1, dtype=np.float64)
    theta0[0], theta0[1] = k0, m0
    theta0[-1] = 0.0  # log sigma = 0 -> sigma = 1, prophet's init

    f = _objective_fn(t, A, A_s, X, y_s, cfg.changepoint_prior_scale,
                      cfg.seasonality_prior_scale, cfg.sigma_prior_scale)
    res = minimize(f, theta0, jac=True, method="L-BFGS-B",
                   options={"maxiter": cfg.maxiter, "maxcor": 20})
    th = res.x
    return ProphetMAPParams(
        k=float(th[0]), m=float(th[1]),
        delta=th[2 : 2 + S].copy(), beta=th[2 + S : 2 + S + F].copy(),
        sigma=float(np.exp(th[-1])), t_change=t_change,
        t0_days=t0, t_span_days=span, y_scale=y_scale,
    )


def predict(params: ProphetMAPParams, day: np.ndarray,
            cfg: ProphetMAPConfig = ProphetMAPConfig()) -> np.ndarray:
    """Point forecast (yhat) on absolute day numbers — in-sample or
    future; the trend extrapolates the last fitted segment, exactly
    prophet's deterministic ``predict`` path."""
    day = np.asarray(day, dtype=np.float64)
    t = (day - params.t0_days) / params.t_span_days
    A = (t[:, None] >= params.t_change[None, :]).astype(np.float64)
    slope = params.k + A @ params.delta
    offset = params.m - A @ (params.t_change * params.delta)
    g = slope * t + offset
    X = _design(day, cfg)
    yhat_s = g * (1.0 + X @ params.beta)
    return (yhat_s * params.y_scale).astype(np.float64)


def cv_cutoff_days(day: np.ndarray, initial: int = 730, period: int = 360,
                   horizon: int = 90) -> np.ndarray:
    """prophet.diagnostics.generate_cutoffs on integer days: last cutoff =
    max(day) - horizon, stepping back by ``period`` while the training
    window keeps >= ``initial`` days."""
    day = np.asarray(day, dtype=np.float64)
    cutoffs = []
    c = float(day.max()) - horizon
    while c - float(day.min()) >= initial:
        cutoffs.append(c)
        c -= period
    if not cutoffs:
        raise ValueError(
            f"series too short for CV: span {day.max() - day.min():.0f}d "
            f"< initial {initial}d + horizon {horizon}d"
        )
    return np.asarray(sorted(cutoffs))


def cv_mape(day: np.ndarray, y: np.ndarray,
            cfg: ProphetMAPConfig = ProphetMAPConfig(),
            initial: int = 730, period: int = 360,
            horizon: int = 90) -> float:
    """Rolling-origin CV MAPE, the reference's protocol
    (``notebooks/prophet/02_training.py:179-186``): fit on data through
    each cutoff, forecast ``horizon`` days, mean |y-yhat|/|y| over all
    horizon points with y != 0 pooled across cutoffs."""
    day = np.asarray(day, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    apes = []
    for c in cv_cutoff_days(day, initial, period, horizon):
        tr = day <= c
        te = (day > c) & (day <= c + horizon)
        if not te.any():
            continue
        params = fit_map(day[tr], y[tr], cfg)
        yhat = predict(params, day[te], cfg)
        yy = y[te]
        nz = np.abs(yy) > 1e-9
        apes.append(np.abs(yy[nz] - yhat[nz]) / np.abs(yy[nz]))
    return float(np.concatenate(apes).mean())
