from distributed_forecasting_tpu.models.base import MODEL_REGISTRY, register_model
from distributed_forecasting_tpu.models import (  # noqa: F401 (registration)
    arima,
    arnet,
    croston,
    holt_winters,
    prophet_glm,
    theta,
)
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig
from distributed_forecasting_tpu.models.holt_winters import HoltWintersConfig
from distributed_forecasting_tpu.models.arima import ArimaConfig
from distributed_forecasting_tpu.models.croston import CrostonConfig
from distributed_forecasting_tpu.models.theta import ThetaConfig
from distributed_forecasting_tpu.models.arnet import ArnetConfig

__all__ = [
    "MODEL_REGISTRY",
    "register_model",
    "CurveModelConfig",
    "HoltWintersConfig",
    "ArimaConfig",
    "CrostonConfig",
    "ThetaConfig",
    "ArnetConfig",
]
