from distributed_forecasting_tpu.models.base import MODEL_REGISTRY, register_model
from distributed_forecasting_tpu.models import prophet_glm, holt_winters, arima  # noqa: F401 (registration)
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig
from distributed_forecasting_tpu.models.holt_winters import HoltWintersConfig
from distributed_forecasting_tpu.models.arima import ArimaConfig

__all__ = [
    "MODEL_REGISTRY",
    "register_model",
    "CurveModelConfig",
    "HoltWintersConfig",
    "ArimaConfig",
]
