"""Batched Holt-Winters seasonal exponential smoothing.

BASELINE config #2: "500 store x item series, batched Holt-Winters (vmap,
single TPU core)".  The per-series recursion is a ``lax.scan`` over time; the
smoothing-parameter fit is a *vectorized grid search* — every (alpha, beta,
gamma) candidate is just one more vmapped axis, so fitting 500 series x ~100
candidates is a single compiled program.  This replaces the reference's
per-series Stan fits (``notebooks/prophet/02_training.py:172``) with a solver
whose inner loop is sequential in time but embarrassingly parallel over
series x candidates — the axes TPUs shard.

Missing observations (mask==0) take the "predict-only" branch of the
recursion via ``jnp.where`` — no dynamic control flow under jit.

Fit is two-pass to keep memory flat: pass 1 scores every candidate by masked
one-step-ahead MSE (scalar carry only); pass 2 re-runs the winning candidate
collecting the fitted path for include-history output.

Forecast intervals use the standard HW(A,A) variance recursion
(Hyndman-Koehler class-1 formula) on the one-step residual scale.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import ClassVar

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import (
    gaussian_quantiles,
    history_splice,
    register_model,
)

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class HoltWintersConfig:
    season_length: int = 7
    seasonality_mode: str = "additive"  # 'additive' | 'multiplicative'
    interval_width: float = 0.95
    # grid-search resolution (static — candidate count derives from these)
    n_alpha: int = 6
    n_beta: int = 4
    n_gamma: int = 4
    # Damped trend (Gardner-McKenzie; ETS(A,Ad,A)/(A,Ad,M)): the trend is
    # multiplied by phi < 1 each step, so long-horizon forecasts flatten to
    # level + phi/(1-phi) * trend instead of extrapolating a straight line
    # off a 5-year grid.  When enabled, phi joins the candidate grid as one
    # more vmapped axis (n_phi values in [0.80, 0.98]); when disabled the
    # recursion runs with phi = 1 exactly and the grid is unchanged.
    damped: bool = False
    n_phi: int = 3
    # time-dimension solver: 'scan' = sequential lax.scan (serial depth T);
    # 'pscan' = associative parallel prefix over affine maps (O(log T) depth,
    # additive mode only) — the long-series regime where the scan's serial
    # chain, not the series axis, bounds wall time.  See docs/parallelism.md
    # for the measured crossover.  'pallas' = fused TPU scoring kernel for
    # the candidate grid (ops/fused_scan.hw_score; additive only) with the
    # winner refit on the sequential scan.  'auto' picks per trace from
    # (backend, S, T, grid lanes) via ops/fused_scan.select_filter — a
    # pinned 'pscan' conf pessimizes the CPU fallback ~50-100x (BENCH_r05),
    # so prefer 'auto' unless benchmarking a specific solver.
    filter: str = "scan"  # 'scan' | 'pscan' | 'pallas' | 'auto'


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HWParams:
    alpha: jax.Array   # (S,)
    beta: jax.Array    # (S,)
    gamma: jax.Array   # (S,)
    phi: jax.Array     # (S,) trend damping; 1.0 when config.damped=False
    level: jax.Array   # (S,) final level
    trend: jax.Array   # (S,) final trend
    season: jax.Array  # (S, m) final seasonal states (slot = row index mod m)
    sigma: jax.Array   # (S,) one-step residual std
    fitted: jax.Array  # (S, T) one-step-ahead fitted values on the train grid
    day0: jax.Array    # () first training day (absolute)
    t_fit_end: jax.Array  # () last training day (absolute)

    # serving artifacts saved before the damped-trend feature have no phi
    # field; phi=1 is exactly the recursion they were fit with
    # (serving/predictor.load_params_npz consults this registry)
    _LEGACY_DEFAULTS: ClassVar[dict] = {
        "phi": lambda fields: jnp.ones_like(fields["alpha"])
    }


def _damp_sum(phi, h):
    """sum_{j=1..h} phi^j, continuous in h; equals h at phi == 1 (the
    geometric form is 0/0 there, so the undamped case takes the exact
    branch via where, keeping the pre-damping forecast path bit-identical)."""
    near1 = jnp.abs(1.0 - phi) < 1e-6
    phi_safe = jnp.where(near1, 0.5, phi)
    geo = phi_safe * (1.0 - phi_safe**h) / (1.0 - phi_safe)
    return jnp.where(near1, h, geo)


def _init_state(y, mask, m, mode):
    """Initial level/trend/season from the first two seasonal cycles."""
    y0, m0 = y[:m], mask[:m]
    l0 = (y0 * m0).sum() / jnp.maximum(m0.sum(), 1.0)
    y1, m1 = y[m : 2 * m], mask[m : 2 * m]
    l1 = (y1 * m1).sum() / jnp.maximum(m1.sum(), 1.0)
    b0 = (l1 - l0) / m
    if mode == "multiplicative":
        s0 = jnp.where(m0 > 0, y0 / jnp.maximum(l0, _EPS), 1.0)
    else:
        s0 = jnp.where(m0 > 0, y0 - l0, 0.0)
    return l0, b0, s0


def _hw_step(l, b, s, yt, mt, it, alpha, beta, gamma, phi, mode):
    """One Holt-Winters recursion step: (l, b, s) -> (l', b', s', pred).

    Shared verbatim by the fit-time filter (``_filter``) and the streaming
    ``update_state`` kernel so the incremental path is the *same float
    expression sequence* as a refit — the exactness contract of
    docs/streaming.md rests on this function having exactly one body.
    Masked steps (mt == 0) take the predict-only branch, which still
    advances the level by phi*b (HW's masked step is NOT state-preserving).
    """
    si = s[it]
    pb = phi * b
    if mode == "multiplicative":
        pred = (l + pb) * si
        l_obs = alpha * yt / jnp.maximum(si, _EPS) + (1 - alpha) * (l + pb)
        s_obs = gamma * yt / jnp.maximum(l_obs, _EPS) + (1 - gamma) * si
    else:
        pred = l + pb + si
        l_obs = alpha * (yt - si) + (1 - alpha) * (l + pb)
        s_obs = gamma * (yt - l_obs) + (1 - gamma) * si
    b_obs = beta * (l_obs - l) + (1 - beta) * pb
    l_new = jnp.where(mt > 0, l_obs, l + pb)
    b_new = jnp.where(mt > 0, b_obs, pb)
    s_new = s.at[it].set(jnp.where(mt > 0, s_obs, si))
    return l_new, b_new, s_new, pred


def _filter(y, mask, alpha, beta, gamma, m, mode, phi=1.0):
    """One-step-ahead filter for one series & one candidate.

    Returns (final_state, mse, preds) where preds is the (T,) one-step
    prediction path.  ``phi`` damps the trend (Gardner-McKenzie): every
    appearance of the prior trend is phi*b, including the pure-prediction
    advance on masked steps; phi=1.0 is exactly the classic recursion.
    """
    l0, b0, s0 = _init_state(y, mask, m, mode)
    T = y.shape[0]
    idx = jnp.arange(T) % m

    def step(carry, inp):
        l, b, s, sse, n = carry
        yt, mt, it = inp
        l_new, b_new, s_new, pred = _hw_step(
            l, b, s, yt, mt, it, alpha, beta, gamma, phi, mode
        )
        err = (yt - pred) * mt
        return (l_new, b_new, s_new, sse + err**2, n + mt), pred

    # derive the zero from data so the carry's device-varying type matches
    # under shard_map (literal 0.0 would be replicated -> VMA mismatch)
    zero = jnp.sum(y) * 0.0
    (l, b, s, sse, n), preds = jax.lax.scan(
        step, (l0, b0, s0, zero, zero), (y, mask, idx)
    )
    mse = sse / jnp.maximum(n, 1.0)
    return (l, b, s), mse, preds


def _affine_elems(y, mask, alpha, beta, gamma, m, phi=1.0):
    """The additive HW update as per-step affine maps x_t = A_t x_{t-1} + c_t
    over the state x = [l, b, s_0..s_{m-1}] — shared by the on-chip parallel
    prefix (:func:`parallel_filter`) and the cross-chip time-sharded variant
    (:func:`parallel_filter_time_sharded`).  Returns (A (T,d,d), c (T,d),
    x0 (d,), e (T,m) one-hot slots)."""
    T = y.shape[0]
    d = m + 2
    idx = jnp.arange(T) % m
    eye_m = jnp.eye(m)
    e = eye_m[idx]  # (T, m) one-hot seasonal slot per step

    # observed-update matrix rows (affine in previous state; f = phi):
    #   l' = (1-a) l + (1-a)f b - a s_i             + a y
    #   b' = -ab l + f(b(1-a)+(1-b)) b - ab s_i     + ab y
    #   s_i' = -g(1-a) l - g(1-a)f b + (ga+1-g)s_i  + g(1-a) y ; s_j'=s_j
    row_l = jnp.concatenate(
        [jnp.full((T, 1), 1 - alpha), jnp.full((T, 1), (1 - alpha) * phi),
         -alpha * e],
        axis=1,
    )
    bb = (beta * (1 - alpha) + (1 - beta)) * phi
    row_b = jnp.concatenate(
        [jnp.full((T, 1), -alpha * beta), jnp.full((T, 1), bb),
         -alpha * beta * e],
        axis=1,
    )
    # seasonal block: identity + slot-row replacement
    s_rows = (
        jnp.broadcast_to(eye_m[None], (T, m, m))
        + e[:, :, None]
        * (
            (gamma * alpha + 1 - gamma - 1.0) * e[:, None, :]  # diag slot adj
        )
    )
    s_lb = e[:, :, None] * jnp.stack(
        [jnp.full((T,), -gamma * (1 - alpha)),
         jnp.full((T,), -gamma * (1 - alpha) * phi)], axis=-1
    )[:, None, :]  # (T, m, 2) only slot row gets l/b terms
    A_obs = jnp.concatenate(
        [
            row_l[:, None, :],
            row_b[:, None, :],
            jnp.concatenate([s_lb, s_rows], axis=2),
        ],
        axis=1,
    )  # (T, d, d)
    c_obs = jnp.concatenate(
        [
            (alpha * y)[:, None],
            (alpha * beta * y)[:, None],
            e * (gamma * (1 - alpha) * y)[:, None],
        ],
        axis=1,
    )  # (T, d)

    A_pred = jnp.zeros((d, d)).at[0, 0].set(1.0).at[0, 1].set(phi)
    A_pred = A_pred.at[1, 1].set(phi)
    A_pred = A_pred.at[2:, 2:].set(eye_m)
    mt = mask[:, None, None]
    A = jnp.where(mt > 0, A_obs, A_pred[None])
    c = jnp.where(mask[:, None] > 0, c_obs, 0.0)

    l0, b0, s0 = _init_state(y, mask, m, "additive")
    x0 = jnp.concatenate([jnp.stack([l0, b0]), s0])
    return A, c, x0, e


def _filter_outputs(states, x0, e, y, mask, phi):
    """(final_state_tuple, mse, preds) from the scanned state trajectory —
    the shared tail of both parallel filters, matching ``_filter``."""
    prev = jnp.concatenate([x0[None], states[:-1]], axis=0)  # state before t
    preds = prev[:, 0] + phi * prev[:, 1] + jnp.sum(prev[:, 2:] * e, axis=1)
    err = (y - preds) * mask
    n = jnp.maximum(jnp.sum(mask), 1.0)
    mse = jnp.sum(err**2) / n
    xT = states[-1]
    return (xT[0], xT[1], xT[2:]), mse, preds


def parallel_filter(y, mask, alpha, beta, gamma, m, phi=1.0):
    """Additive HW filter via parallel prefix over time (O(log T) depth).

    The sequential ``_filter`` is a lax.scan — fine at T~2k, but serial depth
    T dominates for very long series.  The additive update is affine in the
    state x = [l, b, s_0..s_{m-1}]:  x_t = A_t x_{t-1} + c_t, with A_t
    depending only on (observed_t, slot_t) — so the whole trajectory is an
    associative scan over affine maps (ops/pscan.py), the time-dimension
    parallelism story of this framework (SURVEY.md §5).

    Returns (final_state_tuple, mse, preds) matching ``_filter`` semantics
    (mode='additive', same ``phi`` damping — the prior-trend coefficients
    of the affine maps each carry the phi factor).
    """
    from distributed_forecasting_tpu.ops.pscan import affine_scan

    A, c, x0, e = _affine_elems(y, mask, alpha, beta, gamma, m, phi)
    states = affine_scan(A, c, x0)  # (T, d) after each step
    return _filter_outputs(states, x0, e, y, mask, phi)


def parallel_filter_time_sharded(y, mask, alpha, beta, gamma, m, mesh,
                                 axis_name="series", phi=1.0):
    """:func:`parallel_filter` with the TIME axis sharded across a device
    mesh — the model-level entry to cross-chip sequence parallelism
    (ops/pscan.affine_scan_time_sharded): one very long series' filter pass
    can span every chip, T growing with the mesh.  Same return contract as
    ``_filter``/``parallel_filter``.

    T must be a multiple of the mesh size.  To extend a shorter series,
    pad at the OPS level with identity maps (A=eye, c=0 —
    ``affine_scan_time_sharded``'s recipe); masked (mask=0) steps are NOT
    state-preserving here — the prediction map still advances the level by
    ``phi * trend`` each step, so a mask-0 tail drifts the returned final
    state.

    The whole pass (affine-element build + two-phase scan) runs under one
    ``jit`` with the (T, d, d) element tensors sharding-constrained to the
    mesh axis, so GSPMD lays them out sharded from the start — the
    elements are never materialized whole on one device, keeping the
    memory claim (T beyond one chip's HBM) real.  The jitted closure is
    cached per ``(mesh, axis_name, m)``, so callers looping over many
    series of the same shape hit the trace cache instead of recompiling.
    Equivalence vs the sequential filter is tested on the 8-device virtual
    mesh (tests/unit/test_pscan.py)."""
    return _time_sharded_run(mesh, axis_name, m)(
        y, mask, alpha, beta, gamma, phi
    )


@lru_cache(maxsize=32)
def _time_sharded_run(mesh, axis_name: str, m: int):
    """Jitted time-sharded filter body, one per (mesh, axis_name, m)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from distributed_forecasting_tpu.ops.pscan import affine_scan_time_sharded

    shard = NamedSharding(mesh, P(axis_name))

    @jax.jit
    def run(y, mask, alpha, beta, gamma, phi):
        A, c, x0, e = _affine_elems(y, mask, alpha, beta, gamma, m, phi)
        A = jax.lax.with_sharding_constraint(A, shard)
        c = jax.lax.with_sharding_constraint(c, shard)
        states = affine_scan_time_sharded(A, c, x0, mesh,
                                          axis_name=axis_name)
        return _filter_outputs(states, x0, e, y, mask, phi)

    return run


def _candidate_grid(cfg: HoltWintersConfig):
    a = jnp.linspace(0.05, 0.95, cfg.n_alpha)
    b = jnp.linspace(0.01, 0.4, cfg.n_beta)
    g = jnp.linspace(0.05, 0.6, cfg.n_gamma)
    # phi = 1 exactly when undamped (one grid value, no candidate growth)
    p = jnp.linspace(0.80, 0.98, cfg.n_phi) if cfg.damped else jnp.ones((1,))
    A, B, G, P = jnp.meshgrid(a, b, g, p, indexing="ij")
    return A.ravel(), B.ravel(), G.ravel(), P.ravel()  # (C,) each


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: HoltWintersConfig) -> HWParams:
    """Grid-search fit of all series at once.  y, mask: (S, T); day: (T,)."""
    m = config.season_length
    mode = config.seasonality_mode
    A, B, G, P = _candidate_grid(config)

    which = config.filter
    if which == "auto":
        # Resolved at trace time from the actual backend + problem shape
        # (batch S, length T, grid-candidate lanes) — a conf that says
        # 'pscan' pessimizes the CPU fallback ~50-100x (BENCH_r05), and
        # multiplicative seasonality has no affine form (pscan) and no
        # fused scoring kernel (pallas), so it always scans.
        from distributed_forecasting_tpu.ops.fused_scan import select_filter

        which = select_filter(
            jax.default_backend(), int(y.shape[0]), int(y.shape[1]),
            lanes=int(A.shape[0]),
        ) if mode == "additive" else "scan"

    if which == "pallas":
        # Fused Pallas kernel scores the candidate grid; the WINNER is
        # refit with the sequential scan below, so the returned state/
        # sigma/fitted path remain the bitwise ``_hw_step`` products the
        # streaming contract pins — only the argmin ranking runs fused.
        if mode != "additive":
            raise ValueError(
                "filter='pallas' supports additive seasonality only"
            )
        from distributed_forecasting_tpu.ops.fused_scan import hw_score

        msec = hw_score(y, mask, A, B, G, P, m)  # (S, C)
        best = jnp.argmin(msec, axis=1)  # (S,)
        a, b, g, p = A[best], B[best], G[best], P[best]

        def winner(ys, ms, aa, bb, gg, pp):
            (l, tr, s), mse, preds = _filter(ys, ms, aa, bb, gg, m, mode, pp)
            return l, tr, s, jnp.sqrt(mse), preds

        l, t, s, sig, fitted = jax.vmap(winner)(y, mask, a, b, g, p)
        return HWParams(
            alpha=a, beta=b, gamma=g, phi=p, level=l, trend=t, season=s,
            sigma=sig, fitted=fitted,
            day0=day[0].astype(jnp.float32),
            t_fit_end=day[-1].astype(jnp.float32),
        )

    if which == "pscan":
        if mode != "additive":
            raise ValueError(
                "filter='pscan' supports additive seasonality only "
                "(the multiplicative update is not affine in the state)"
            )
        filt = lambda ys, ms, a, b, g, p: parallel_filter(ys, ms, a, b, g, m, p)
    elif which == "scan":
        filt = lambda ys, ms, a, b, g, p: _filter(ys, ms, a, b, g, m, mode, p)
    else:
        raise ValueError(
            f"unknown filter {config.filter!r}; "
            f"'scan', 'pscan', 'pallas', or 'auto'"
        )

    # Config-gated mixed precision (ops/precision.py): bf16 accumulation is
    # tolerable ONLY in the scoring pass — the argmin is its sole consumer
    # and the winner below is refit in float32, so the bitwise streaming
    # contract never sees a bf16 value.  OFF by default; outputs are only
    # baseline-identical when the gate is off.
    from distributed_forecasting_tpu.ops.precision import scoring_dtype

    sd = scoring_dtype()

    def per_series(ys, ms):
        def score(a, b, g, p):
            if sd is not None:
                _, mse, _ = filt(ys.astype(sd), ms.astype(sd), a.astype(sd),
                                 b.astype(sd), g.astype(sd), p.astype(sd))
                return mse.astype(jnp.float32)
            _, mse, _ = filt(ys, ms, a, b, g, p)
            return mse

        msec = jax.vmap(score)(A, B, G, P)  # (C,)
        best = jnp.argmin(msec)
        a, b, g, p = A[best], B[best], G[best], P[best]
        (l, bb, s), mse, preds = filt(ys, ms, a, b, g, p)
        return a, b, g, p, l, bb, s, jnp.sqrt(mse), preds

    a, b, g, p, l, t, s, sig, fitted = jax.vmap(per_series)(y, mask)
    return HWParams(
        alpha=a, beta=b, gamma=g, phi=p, level=l, trend=t, season=s, sigma=sig,
        fitted=fitted,
        day0=day[0].astype(jnp.float32),
        t_fit_end=day[-1].astype(jnp.float32),
    )


@partial(jax.jit, static_argnames=("config",))
def forecast(params: HWParams, day_all, t_end, config: HoltWintersConfig, key=None):
    """(yhat, lo, hi) over history+future days.

    In-sample days (day <= t_fit_end) return the filter's one-step fitted
    path; future days extrapolate level + h*trend (+/x season).
    """
    m = config.season_length
    S = params.level.shape[0]
    T_all = day_all.shape[0]
    dayf = day_all.astype(jnp.float32)
    # Splice origin is the fit grid's end: the masked-scan branch advances
    # the state (l += b) each unobserved step, so the fitted path through a
    # CV eval window is already the honest h-step extrapolation — the final
    # (level, trend) state belongs to t_fit_end, not the caller's cutoff.
    h = dayf - params.t_fit_end  # steps past the fit grid; <= 0 in history
    # Intervals widen from t_end (the caller's last observed day, e.g. a CV
    # cutoff): uncertainty starts where observations stop.
    h_unc = dayf - t_end.astype(jnp.float32)

    # future seasonal slot: training rows were indexed 0..T-1 => slot of day d
    # is (d - day0) mod m
    sidx = jnp.mod((dayf - params.day0).astype(jnp.int32), m)
    s_at = params.season[:, :][jnp.arange(S)[:, None], sidx[None, :].repeat(S, 0)]
    # h-step trend multiplier: sum_{j=1..h} phi^j = phi(1-phi^h)/(1-phi),
    # which is exactly h when phi = 1 (the undamped case)
    hpos = jnp.maximum(h, 0.0)[None, :]
    base = params.level[:, None] + params.trend[:, None] * _damp_sum(
        params.phi[:, None], hpos
    )
    if config.seasonality_mode == "multiplicative":
        fut = base * s_at
    else:
        fut = base + s_at

    # in-sample: gather fitted by day offset
    yhat = history_splice(params.fitted, fut, day_all, params.day0, h)

    # class-1 variance: var(h) = sigma^2 (1 + sum_{j=1}^{h-1} c_j^2); the
    # damped form replaces j*beta with beta * sum_{i<=j} phi^i (Hyndman et
    # al., class-1 ETS(A,Ad,A)), reducing to j*beta at phi = 1
    j = jnp.arange(1, T_all + 1, dtype=jnp.float32)
    cj = (
        params.alpha[:, None]
        * (1.0 + params.beta[:, None] * _damp_sum(params.phi[:, None], j[None, :]))
        + params.gamma[:, None] * (jnp.mod(j[None, :], float(m)) == 0)
    )
    cum = jnp.concatenate(
        [jnp.zeros((S, 1)), jnp.cumsum(cj**2, axis=1)[:, :-1]], axis=1
    )
    hclip = jnp.clip(h_unc.astype(jnp.int32) - 1, 0, T_all - 1)
    var_mult = 1.0 + jnp.take_along_axis(
        cum, jnp.broadcast_to(hclip[None, :], (S, T_all)), axis=1
    )
    var_mult = jnp.where((h_unc > 0.0)[None, :], var_mult, 1.0)
    sd = params.sigma[:, None] * jnp.sqrt(var_mult)
    z = ndtri(0.5 + config.interval_width / 2.0)
    return yhat, yhat - z * sd, yhat + z * sd


@partial(jax.jit, static_argnames=("config",))
def update_state(params: HWParams, aux, y_new, mask_new, valid, day_new,
                 config: HoltWintersConfig):
    """Continue the HW filter over K appended day-columns in one dispatch.

    y_new/mask_new: (S, K); valid: (K,) 1.0 for real appended days, 0.0 for
    shape-bucket padding; day_new: (K,) absolute day ordinals (contiguous
    from t_fit_end+1 in the streaming path, but only the seasonal-slot and
    t_fit_end arithmetic depend on them).  Each valid step runs
    :func:`_hw_step` — the byte-identical expression sequence the fit
    filter scans — so level/trend/season after k updates equal a refit of
    the extended series bit-for-bit (given the same winning candidate;
    tests/unit/test_state_update.py pins a 1-candidate grid to prove it).
    Padding columns gate the whole carry through ``where(valid, ...)``,
    leaving it bit-identical — HW's masked branch still advances the level,
    so padding must skip the step entirely rather than masquerade as
    mask==0.  ``sigma`` continues from aux's (sse, n_obs) running moments;
    ``fitted`` is left untouched (the state store owns that buffer).
    """
    m = config.season_length
    mode = config.seasonality_mode
    dayf = day_new.astype(jnp.float32)
    # training rows are indexed (day - day0), so the slot of appended day d
    # is (d - day0) mod m — same formula forecast() uses for future days
    slots = jnp.mod((dayf - params.day0).astype(jnp.int32), m)  # (K,)

    def per_series(l, b, s, al, be, ga, ph, ys, ms, sse, n):
        def step(carry, inp):
            l, b, s, sse, n = carry
            yt, mt, it, vt = inp
            l2, b2, s2, pred = _hw_step(l, b, s, yt, mt, it, al, be, ga,
                                        ph, mode)
            l3 = jnp.where(vt > 0, l2, l)
            b3 = jnp.where(vt > 0, b2, b)
            s3 = jnp.where(vt > 0, s2, s)
            err = (yt - pred) * mt * vt
            return (l3, b3, s3, sse + err**2, n + mt * vt), pred

        (l, b, s, sse, n), preds = jax.lax.scan(
            step, (l, b, s, sse, n), (ys, ms, slots, valid)
        )
        return l, b, s, sse, n, preds

    l, b, s, sse, n, preds = jax.vmap(per_series)(
        params.level, params.trend, params.season, params.alpha, params.beta,
        params.gamma, params.phi, y_new, mask_new, aux["sse"], aux["n_obs"]
    )
    sigma = jnp.sqrt(sse / jnp.maximum(n, 1.0))
    t2 = jnp.maximum(
        params.t_fit_end,
        jnp.max(jnp.where(valid > 0, dayf, params.t_fit_end)),
    )
    params2 = dataclasses.replace(
        params, level=l, trend=b, season=s, sigma=sigma, t_fit_end=t2
    )
    return params2, {"sse": sse, "n_obs": n}, preds


def init_update_aux(params: HWParams, y=None, mask=None):
    """Seed the streaming carry pieces fit() does not persist.

    With the training mask, n_obs is exact; sse is recovered as
    sigma^2 * max(n, 1) — the sqrt/square round-trip is the only seeding
    error, so sigma after updates matches a refit within float tolerance
    while the filter state stays bitwise.  Without history, n_obs falls
    back to the grid length (exact only for fully-observed series).
    """
    if mask is not None:
        n = jnp.sum(jnp.asarray(mask, jnp.float32), axis=1)
    else:
        n = jnp.full_like(params.sigma, float(params.fitted.shape[1]))
    sse = params.sigma**2 * jnp.maximum(n, 1.0)
    return {"sse": sse, "n_obs": n}


register_model("holt_winters", fit, forecast, HoltWintersConfig,
               forecast_quantiles=gaussian_quantiles(forecast),
               update_state=update_state, init_update_aux=init_update_aux)
