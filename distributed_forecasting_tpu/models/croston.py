"""Batched Croston / SBA / TSB intermittent-demand forecasting.

Beyond-parity model family: at store x item granularity much retail demand
is *intermittent* (mostly zero days with occasional demands), where
curve/HW/ARIMA models systematically under- or over-shoot.  Croston's method
smooths demand sizes and inter-demand intervals separately with SES and
forecasts their ratio; the SBA variant applies the (1 - alpha/2) bias
correction.  The TSB variant (Teunter-Syntetos-Babai 2011, public method)
instead smooths the demand *probability* every observed period — so a run
of zero-demand days decays the forecast toward zero, handling product
obsolescence, where Croston/SBA freeze at the last demand rate forever.
The recursion is a ``lax.scan`` with a (size-level, interval-level,
gap-counter) carry — (size-level, probability) for TSB — vmapped over
series; same batched architecture as every other family here (one compiled
program for all series, reference fan-out analogy as in
models/holt_winters.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import (
    gaussian_quantiles,
    history_splice,
    register_model,
)

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class CrostonConfig:
    alpha: float = 0.1          # SES smoothing for sizes and intervals
    variant: str = "sba"        # 'croston' | 'sba' | 'tsb'
    # TSB only: smoothing rate for the demand-probability EWMA (updated
    # every observed period, unlike sizes/intervals which update only at
    # demand points — this is what lets the forecast decay to zero over a
    # dead tail)
    beta: float = 0.1
    interval_width: float = 0.95


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CrostonParams:
    z_level: jax.Array   # (S,) smoothed demand size
    # (S,) smoothed inter-demand interval; for the TSB variant this holds
    # the INVERSE smoothed demand probability (1/b >= 1), so the shared
    # forecast rate z/p equals TSB's z*b with an unchanged param pytree
    p_level: jax.Array
    sigma: jax.Array     # (S,) one-step residual std (demand-rate space)
    fitted: jax.Array    # (S, T) one-step-ahead fitted rates
    day0: jax.Array
    t_fit_end: jax.Array


def _rate(z, p, alpha, variant):
    rate = z / jnp.maximum(p, 1.0)
    if variant == "sba":
        rate = rate * (1.0 - alpha / 2.0)
    return rate


def _croston_step(z, p, q, yt, mt, alpha, variant):
    """One Croston/SBA step: (z, p, q) -> (z', p', q', pred).  Shared
    verbatim by fit's scan and the streaming ``update_state`` kernel (one
    body — the docs/streaming.md exactness contract).  mt == 0 steps are
    state-preserving: q_new = q + 0 and demand is False."""
    pred = _rate(z, p, alpha, variant)
    demand = (yt > _EPS) & (mt > 0)
    q_new = q + mt  # observed periods since last demand
    z_upd = alpha * yt + (1 - alpha) * z
    p_upd = alpha * q_new + (1 - alpha) * p
    z2 = jnp.where(demand, z_upd, z)
    p2 = jnp.where(demand, p_upd, p)
    q2 = jnp.where(demand, 0.0, q_new)
    return z2, p2, q2, pred


def _tsb_step(z, b, yt, mt, alpha, beta):
    """One TSB step: (z, b) -> (z', b', pred); same sharing discipline as
    :func:`_croston_step`.  The probability b updates every observed
    period; the size z only at demand points."""
    pred = z * b
    demand = (yt > _EPS) & (mt > 0)
    ind = jnp.where(demand, 1.0, 0.0)
    # probability updates EVERY observed period; size only at
    # demand points — the asymmetry that makes dead tails decay
    b2 = jnp.where(mt > 0, beta * ind + (1 - beta) * b, b)
    z2 = jnp.where(demand, alpha * yt + (1 - alpha) * z, z)
    return z2, b2, pred


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: CrostonConfig) -> CrostonParams:
    if config.variant not in ("croston", "sba", "tsb"):
        raise ValueError(
            f"unknown CrostonConfig.variant {config.variant!r}; "
            f"'croston', 'sba', or 'tsb'"
        )
    a = config.alpha

    def per_series(ys, ms):
        nz = (ys > _EPS) & (ms > 0)
        n_demands = jnp.maximum(jnp.sum(nz), 1.0)
        z0 = jnp.sum(jnp.where(nz, ys, 0.0)) / n_demands
        n_obs = jnp.maximum(jnp.sum(ms), 1.0)
        zero = jnp.sum(ys) * 0.0  # varying-type-safe zero (see holt_winters)

        if config.variant == "tsb":
            bta = config.beta
            b0 = n_demands / n_obs

            def step(carry, inp):
                z, b, sse, n = carry
                yt, mt = inp
                z2, b2, pred = _tsb_step(z, b, yt, mt, a, bta)
                err = (yt - pred) * mt
                return (z2, b2, sse + err**2, n + mt), pred

            (z, b, sse, n), preds = jax.lax.scan(
                step, (z0, b0, zero, zero), (ys, ms)
            )
            p = 1.0 / jnp.maximum(b, _EPS)
        else:
            p0 = n_obs / n_demands

            def step(carry, inp):
                z, p, q, sse, n = carry
                yt, mt = inp
                z2, p2, q2, pred = _croston_step(z, p, q, yt, mt, a,
                                                 config.variant)
                err = (yt - pred) * mt
                return (z2, p2, q2, sse + err**2, n + mt), pred

            (z, p, _q, sse, n), preds = jax.lax.scan(
                step, (z0, p0, zero, zero, zero), (ys, ms)
            )
        sigma = jnp.sqrt(sse / jnp.maximum(n, 1.0))
        return z, p, sigma, preds

    z, p, sigma, fitted = jax.vmap(per_series)(y, mask)
    return CrostonParams(
        z_level=z, p_level=p, sigma=sigma, fitted=fitted,
        day0=day[0].astype(jnp.float32),
        t_fit_end=day[-1].astype(jnp.float32),
    )


@partial(jax.jit, static_argnames=("config",))
def forecast(params: CrostonParams, day_all, t_end, config: CrostonConfig,
             key=None):
    dayf = day_all.astype(jnp.float32)
    # splice origin = fit-grid end; the frozen rate makes the fitted path in
    # a masked eval window equal the flat future forecast anyway
    h = dayf - params.t_fit_end
    rate = _rate(params.z_level, params.p_level, config.alpha, config.variant)

    fut = jnp.broadcast_to(rate[:, None], (rate.shape[0], day_all.shape[0]))
    yhat = history_splice(params.fitted, fut, day_all, params.day0, h)
    z = ndtri(0.5 + config.interval_width / 2.0)
    sd = params.sigma[:, None]
    lo = jnp.maximum(yhat - z * sd, 0.0)  # demand is non-negative
    hi = yhat + z * sd
    return yhat, lo, hi


@partial(jax.jit, static_argnames=("config",))
def update_state(params: CrostonParams, aux, y_new, mask_new, valid, day_new,
                 config: CrostonConfig):
    """Continue the Croston/SBA/TSB filter over K appended day-columns.

    Both variants' masked steps are state-preserving, so shape-bucket
    padding rides in as ``mask * valid == 0`` (bitwise the original mask
    where valid == 1).  The carries fit() does not persist live in aux:
    ``q`` (Croston/SBA observed-periods-since-demand) and ``b`` (TSB
    demand probability — params stores only 1/b, so aux keeps the exact
    value across dispatches; only the initial seeding pays the reciprocal
    round-trip, see ``init_update_aux``).  aux keeps BOTH keys regardless
    of variant, passing the unused one through, so the aux pytree
    structure — and with it the AOT cache fingerprint — is identical on
    every dispatch.
    """
    if config.variant not in ("croston", "sba", "tsb"):
        raise ValueError(
            f"unknown CrostonConfig.variant {config.variant!r}; "
            f"'croston', 'sba', or 'tsb'"
        )
    a = config.alpha
    dayf = day_new.astype(jnp.float32)
    m_eff = mask_new * valid[None, :]

    if config.variant == "tsb":
        bta = config.beta

        def per_series(z, b, ys, ms, sse, n):
            def step(carry, inp):
                z, b, sse, n = carry
                yt, mt = inp
                z2, b2, pred = _tsb_step(z, b, yt, mt, a, bta)
                err = (yt - pred) * mt
                return (z2, b2, sse + err**2, n + mt), pred

            (z, b, sse, n), preds = jax.lax.scan(
                step, (z, b, sse, n), (ys, ms)
            )
            return z, b, sse, n, preds

        z, b, sse, n, preds = jax.vmap(per_series)(
            params.z_level, aux["b"], y_new, m_eff, aux["sse"], aux["n_obs"]
        )
        p = 1.0 / jnp.maximum(b, _EPS)
        q2 = aux["q"]
    else:

        def per_series(z, p, q, ys, ms, sse, n):
            def step(carry, inp):
                z, p, q, sse, n = carry
                yt, mt = inp
                z2, p2, q2, pred = _croston_step(z, p, q, yt, mt, a,
                                                 config.variant)
                err = (yt - pred) * mt
                return (z2, p2, q2, sse + err**2, n + mt), pred

            (z, p, q, sse, n), preds = jax.lax.scan(
                step, (z, p, q, sse, n), (ys, ms)
            )
            return z, p, q, sse, n, preds

        z, p, q2, sse, n, preds = jax.vmap(per_series)(
            params.z_level, params.p_level, aux["q"], y_new, m_eff,
            aux["sse"], aux["n_obs"]
        )
        b = aux["b"]
    sigma = jnp.sqrt(sse / jnp.maximum(n, 1.0))
    t2 = jnp.maximum(
        params.t_fit_end,
        jnp.max(jnp.where(valid > 0, dayf, params.t_fit_end)),
    )
    params2 = dataclasses.replace(
        params, z_level=z, p_level=p, sigma=sigma, t_fit_end=t2
    )
    return params2, {"sse": sse, "n_obs": n, "q": q2, "b": b}, preds


def init_update_aux(params: CrostonParams, y=None, mask=None):
    """Seed the non-persisted carries from training history.

    With (y, mask): ``q`` is the exact observed-period count after the last
    demand (0/1 sums — exact in float32); without, q = 0 (assume a demand
    closed the training window — documented approximation).  ``b`` is
    recovered as 1/max(p_level, eps): exact for Croston/SBA (unused) and a
    ~2-ulp reciprocal round-trip for TSB, after which aux carries b
    exactly.  (sse, n_obs) as in the other families.
    """
    if mask is not None:
        maskf = jnp.asarray(mask, jnp.float32)
        n = jnp.sum(maskf, axis=1)
    else:
        maskf = None
        n = jnp.full_like(params.sigma, float(params.fitted.shape[1]))
    sse = params.sigma**2 * jnp.maximum(n, 1.0)
    b = 1.0 / jnp.maximum(params.p_level, _EPS)
    if y is not None and maskf is not None:
        yf = jnp.asarray(y, jnp.float32)
        nz = ((yf > _EPS) & (maskf > 0)).astype(jnp.float32)
        # positions strictly after the last demand contribute their mask;
        # reversed-cumsum == 0 marks exactly those trailing positions
        trailing = (jnp.cumsum(nz[:, ::-1], axis=1) == 0).astype(jnp.float32)
        q = jnp.sum(maskf[:, ::-1] * trailing, axis=1)
    else:
        q = jnp.zeros_like(params.sigma)
    return {"sse": sse, "n_obs": n, "q": q, "b": b}


register_model("croston", fit, forecast, CrostonConfig,
               forecast_quantiles=gaussian_quantiles(forecast, floor=0.0),
               band_floor=0.0,
               update_state=update_state, init_update_aux=init_update_aux)
