"""Batched AR-Net: linear autoregression + future-regressor head, fit by
minibatch gradient descent over ALL series at once (NeuralProphet's AR-Net
core, arXiv 2111.15397, without the hidden layers).

The model per series s, in per-series standardized space ``z``:

    z_t ~ w_s · [z_{t-1} .. z_{t-L}] + beta_s · x_t + b_s

``x_t`` are regressors KNOWN over history + horizon (exactly the
``(T+horizon, R)`` holiday tensors autoprep emits), standardized with
stats frozen at fit time.  Unlike every other family here there is no
closed form — fitting is the batched gradient loop in
``engine/gradfit.py``: one jitted optimizer step advances all S series
over ``(S, B, L)`` minibatch tensors (sum-of-per-series losses, so series
never couple and shape-bucket padding rows are exact no-ops).

Two fit paths, one numeric core:

* :func:`fit` (registered) trains fully in-trace via
  ``gradfit.train_scan`` — jit/vmap-safe with static config, so the family
  rides ``fit_forecast``, vmapped CV cutoffs, the TrainingPipeline and the
  serving predictor like the other families;
* the eager engine path (``gradfit.gradfit_fit_forecast``, armed by the
  ``engine.gradfit`` conf block) trains with host-assembled prefetched
  minibatches + donated AOT steps, then calls :func:`params_from_weights`
  + :func:`forecast` — the same post-training code as this module.

Forecasting rolls the AR recursion forward from the fit-grid-end lag
buffer (honest recursive multi-step: predictions feed back as lag
inputs).  Interval growth uses the AR(1) proxy ``a = sum(w)`` (the lag
polynomial's total persistence): h-step variance ``sigma^2 ·
(1 - a^{2h}) / (1 - a^2)``, the exact AR(1) forward-variance recursion —
cheap, monotone, and collapsing to the 1-step sigma in-sample.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import (
    history_splice,
    register_model,
)

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class ArnetConfig:
    lags: int = 28
    n_regressors: int = 0
    loss: str = "huber"            # "huber" | "mse"
    huber_delta: float = 1.0
    optimizer: str = "adam"        # "adam" | "sgd" | "momentum"
    learning_rate: float = 0.05
    epochs: int = 30
    batch_size: int = 64
    seed: int = 0
    interval_width: float = 0.95

    def __post_init__(self):
        if self.lags < 1:
            raise ValueError(f"lags must be >= 1, got {self.lags}")
        if self.epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {self.epochs}")
        if self.batch_size < 1:
            raise ValueError(
                f"batch_size must be >= 1, got {self.batch_size}")
        if not 0.0 < self.interval_width < 1.0:
            raise ValueError(
                f"interval_width must lie in (0, 1), got "
                f"{self.interval_width}")


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ArnetParams:
    w: jax.Array         # (S, L) AR lag weights (lag 1 first), z-space
    beta: jax.Array      # (S, R) regressor weights, standardized space
    b: jax.Array         # (S,) bias, z-space
    mu: jax.Array        # (S,) per-series target mean (standardization)
    sd: jax.Array        # (S,) per-series target std (standardization)
    xmu: jax.Array       # (S, R) regressor means — identical rows; kept
    xsd: jax.Array       # (S, R) S-leading so the serving param gather
    #                      slices them like every other leaf
    sigma: jax.Array     # (S,) one-step residual std, data space
    buf_end: jax.Array   # (S, L) z-space lag buffer at the fit-grid end
    fitted: jax.Array    # (S, T) one-step fitted path, data space
    day0: jax.Array
    t_fit_end: jax.Array


def _check_xreg(xreg, config: ArnetConfig, what: str) -> bool:
    if config.n_regressors == 0:
        if xreg is not None:
            raise ValueError(
                "xreg passed but config.n_regressors == 0 — set "
                f"ArnetConfig(n_regressors={xreg.shape[-1]}) ({what})")
        return False
    if xreg is None:
        raise ValueError(
            f"config.n_regressors={config.n_regressors} but no xreg "
            f"values passed to {what}")
    if xreg.shape[-1] != config.n_regressors:
        raise ValueError(
            f"xreg has {xreg.shape[-1]} columns, config.n_regressors="
            f"{config.n_regressors} ({what})")
    return True


def prep_training(y, mask, config: ArnetConfig, xreg=None):
    """Standardized training tensors:
    ``(z, mu, sd, xz, valid, xmu, xsd)``.

    z: (S, T) per-series standardized targets, masked positions zeroed;
    xz: regressors standardized with GLOBAL per-column stats, same layout
    as the input ((T, R) shared / (S, T, R) per-series; (T, 0) when the
    family runs without regressors); valid: (S, T) teacher-forcing weight
    — 1 only where the target AND all ``lags`` lag positions are observed.

    Every reduction is masked, so a fully-padded bucket row yields
    ``z = 0, valid = 0`` and the stats of real rows are untouched —
    training S series inside a padded bucket matches training them alone.
    """
    y = jnp.asarray(y, jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    T = y.shape[1]
    n = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    mu = jnp.sum(y * mask, axis=1) / n
    var = jnp.sum(((y - mu[:, None]) ** 2) * mask, axis=1) / n
    sd = jnp.sqrt(var)
    sd = jnp.where(sd > _EPS, sd, 1.0)
    z = jnp.where(mask > 0, (y - mu[:, None]) / sd[:, None], 0.0)

    # valid_t = mask_t * prod_{i=1..L} mask_{t-i}: unrolled shift product
    # (L static and small — no (S, T, L) window materialization)
    valid = mask
    for i in range(1, config.lags + 1):
        valid = valid * jnp.pad(mask, ((0, 0), (i, 0)))[:, :T]

    if _check_xreg(xreg, config, "fit"):
        xreg = jnp.asarray(xreg, jnp.float32)
        if xreg.ndim == 3:
            # per-series values: mask-weighted global stats so padded
            # bucket rows (mask == 0) cannot shift them
            w = mask[:, :, None]
            cnt = jnp.maximum(jnp.sum(w), 1.0)
            xmu = jnp.sum(xreg * w, axis=(0, 1)) / cnt          # (R,)
            xvar = jnp.sum(((xreg - xmu) ** 2) * w, axis=(0, 1)) / cnt
        else:
            # shared calendar: plain time stats (identical for every
            # series, so bucket padding is irrelevant by construction)
            xmu = jnp.mean(xreg, axis=0)                        # (R,)
            xvar = jnp.mean((xreg - xmu) ** 2, axis=0)
        xsd = jnp.sqrt(xvar)
        xsd = jnp.where(xsd > _EPS, xsd, 1.0)
        xz = (xreg - xmu) / xsd
    else:
        xmu = jnp.zeros((0,), jnp.float32)
        xsd = jnp.ones((0,), jnp.float32)
        xz = jnp.zeros((T, 0), jnp.float32)
    return z, mu, sd, xz, valid, xmu, xsd


def _fitted_scan(z, mask, xc, w):
    """One-step-ahead fitted path in z-space with an honest recursive lag
    buffer: observed positions enter the buffer as-is, masked positions
    (gaps, CV eval windows) enter as their own prediction — the same
    closed-loop dynamics the future rollout uses, so a forecast spliced at
    the grid end continues the carry seamlessly.

    Returns (preds (S, T), buf_end (S, L))."""
    S, L = w.shape

    def step(buf, inp):
        z_t, m_t, xc_t = inp
        pred = jnp.sum(buf * w, axis=1) + xc_t
        v = jnp.where(m_t > 0, z_t, pred)
        return jnp.concatenate([v[:, None], buf[:, :-1]], axis=1), pred

    buf_end, preds = jax.lax.scan(
        step, jnp.zeros((S, L), z.dtype), (z.T, mask.T, xc.T))
    return preds.T, buf_end


def _xreg_contrib(xreg_grid, params: ArnetParams):
    """(S, T_grid) regressor contribution from RAW values: fold the frozen
    standardization into the weights (``beta·(x-mu)/sd = (beta/sd)·x -
    beta·mu/sd``) instead of materializing an (S, T, R) standardized
    tensor for a shared calendar."""
    xreg_grid = jnp.asarray(xreg_grid, jnp.float32)
    beta_eff = params.beta / params.xsd                         # (S, R)
    offset = jnp.sum(params.beta * params.xmu / params.xsd, axis=1)
    if xreg_grid.ndim == 3:
        contrib = jnp.einsum("str,sr->st", xreg_grid, beta_eff)
    else:
        contrib = jnp.einsum("tr,sr->st", xreg_grid, beta_eff)
    return contrib - offset[:, None]


@partial(jax.jit, static_argnames=("config",))
def params_from_weights(y, mask, day, config: ArnetConfig, w, beta, b,
                        xreg=None) -> ArnetParams:
    """Finalize trained weights into the family's params pytree: fitted
    path, residual sigma, grid-end lag buffer, frozen standardization.
    Shared verbatim by the in-trace :func:`fit` and the eager gradfit
    engine path (``gradfit_finalize:arnet``) — one post-training body, so
    the two trainers differ only in who ran the optimizer loop."""
    z, mu, sd, xz, _valid, xmu_g, xsd_g = prep_training(
        y, mask, config, xreg=xreg)
    S = y.shape[0]
    xc = jnp.broadcast_to(b[:, None], z.shape)
    if xz.shape[-1]:
        if xz.ndim == 2:
            xc = xc + jnp.einsum("tr,sr->st", xz, beta)
        else:
            xc = xc + jnp.einsum("str,sr->st", xz, beta)
    preds, buf_end = _fitted_scan(z, jnp.asarray(mask, jnp.float32), xc, w)
    fitted = mu[:, None] + sd[:, None] * preds
    m = jnp.asarray(mask, jnp.float32)
    resid = (jnp.asarray(y, jnp.float32) - fitted) * m
    sigma = jnp.sqrt(
        jnp.sum(resid * resid, axis=1)
        / jnp.maximum(jnp.sum(m, axis=1), 1.0))
    R = config.n_regressors
    return ArnetParams(
        w=w, beta=beta, b=b, mu=mu, sd=sd,
        xmu=jnp.broadcast_to(xmu_g[None, :], (S, R)),
        xsd=jnp.broadcast_to(xsd_g[None, :], (S, R)),
        sigma=sigma, buf_end=buf_end, fitted=fitted,
        day0=day[0].astype(jnp.float32),
        t_fit_end=day[-1].astype(jnp.float32),
    )


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: ArnetConfig, xreg=None) -> ArnetParams:
    """In-trace batched gradient fit (``gradfit.train_scan``) — jit- and
    vmap-safe with static config, so CV cutoffs vmap over it unchanged.
    Determinism comes from ``config.seed`` (no key argument in the family
    protocol): two fits on identical inputs are bitwise identical."""
    from distributed_forecasting_tpu.engine import gradfit

    z, _mu, _sd, xz, valid, _xmu, _xsd = prep_training(
        y, mask, config, xreg=xreg)
    wp, _losses = gradfit.train_scan(z, xz, valid, config)
    return params_from_weights(y, mask, day, config,
                               wp["w"], wp["beta"], wp["b"], xreg=xreg)


@partial(jax.jit, static_argnames=("config",))
def forecast(params: ArnetParams, day_all, t_end, config: ArnetConfig,
             key=None, xreg=None):
    """Recursive multi-step rollout from the fit-grid-end lag buffer.

    ``xreg`` (when the family runs with regressors) covers the FULL
    history + horizon grid — future steps read their regressor row through
    the frozen standardization (folded into the weights, see
    :func:`_xreg_contrib`).
    """
    if config.n_regressors and xreg is None:
        raise ValueError(
            f"config.n_regressors={config.n_regressors} but no xreg "
            f"values passed to forecast")
    S, L = params.w.shape
    T_fit = params.fitted.shape[1]
    T_all = day_all.shape[0]
    H = T_all - T_fit + 1 if T_all > T_fit else T_all

    dayf = day_all.astype(jnp.float32)
    h = dayf - params.t_fit_end
    h_unc = dayf - t_end.astype(jnp.float32)

    if config.n_regressors:
        xc_all = params.b[:, None] + _xreg_contrib(xreg, params)  # (S, T_all)
    else:
        xc_all = jnp.broadcast_to(params.b[:, None], (S, T_all))
    # future step j (1-based h = j+1) sits at grid position T_fit + j
    pos = jnp.clip(T_fit + jnp.arange(H), 0, T_all - 1)
    xc_fut = xc_all[:, pos]                                       # (S, H)

    def step(buf, xc_t):
        pred = jnp.sum(buf * params.w, axis=1) + xc_t
        return jnp.concatenate([pred[:, None], buf[:, :-1]], axis=1), pred

    _, fut_z = jax.lax.scan(step, params.buf_end, xc_fut.T)
    fut = params.mu[:, None] + params.sd[:, None] * fut_z.T       # (S, H)

    hidx = jnp.clip(h.astype(jnp.int32) - 1, 0, H - 1)
    fut_g = jnp.take_along_axis(
        fut, jnp.broadcast_to(hidx[None, :], (S, T_all)), axis=1)
    yhat = history_splice(params.fitted, fut_g, day_all, params.day0, h)

    # AR(1) persistence proxy for band growth: a = sum of lag weights,
    # clipped inside the unit circle so the geometric series is finite
    a2 = jnp.clip(jnp.sum(params.w, axis=1), -0.98, 0.98) ** 2    # (S,)
    steps = jnp.maximum(h_unc, 1.0)[None, :]
    growth = (1.0 - a2[:, None] ** steps) / (1.0 - a2[:, None])
    sd_path = params.sigma[:, None] * jnp.sqrt(growth)
    z_w = ndtri(0.5 + config.interval_width / 2.0)
    return yhat, yhat - z_w * sd_path, yhat + z_w * sd_path


def forecast_quantiles(params: ArnetParams, day_all, t_end,
                       config: ArnetConfig, quantiles=(0.1, 0.5, 0.9),
                       key=None, xreg=None):
    """Gaussian quantile paths WITH xreg passthrough — the generic
    ``gaussian_quantiles`` wrapper doesn't forward regressor values, and
    arnet's point path needs them."""
    if not quantiles or not all(0.0 < q < 1.0 for q in quantiles):
        raise ValueError(f"quantiles must lie in (0, 1), got {quantiles!r}")
    yhat, _lo, hi = forecast(params, day_all, t_end, config, key,
                             xreg=xreg)
    z_w = ndtri(0.5 + config.interval_width / 2.0)
    sd = (hi - yhat) / z_w
    qs = jnp.asarray(tuple(quantiles), jnp.float32)
    return yhat[:, None, :] + ndtri(qs)[None, :, None] * sd[:, None, :]


register_model("arnet", fit, forecast, ArnetConfig, supports_xreg=True,
               forecast_quantiles=forecast_quantiles)
