"""Batched Theta-method forecasting (Assimakopoulos & Nikolopoulos 2000).

Beyond-parity model family: the Theta method won the M3 competition and is
the standard "strong classical baseline" for retail demand.  Hyndman &
Billah (2003) showed the classic two-line variant is SES with an added drift
of half the linear-trend slope — which is exactly how it is computed here:

    1. multiplicative weekly deseasonalization (index per day-of-week slot),
    2. OLS linear trend ``a + b.t`` on the seasonally-adjusted series
       (the theta=0 line),
    3. SES on the theta=2 line ``Z = 2.y_sa - (a + b.t)`` with a per-series
       grid-optimized smoothing constant,
    4. forecast = mean of the flat SES forecast of Z and the extrapolated
       trend line, reseasonalized.

Everything is masked + fixed-shape: the deseasonalization and regression are
weighted reductions, the SES recursion is a ``lax.scan`` whose level only
updates where ``mask>0``, and the alpha grid is one more vmapped axis — the
same one-compiled-program-for-all-series architecture that replaces the
reference's per-(store,item) Prophet fan-out (reference
``notebooks/prophet/02_training.py:282-307``).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from distributed_forecasting_tpu.models.base import (
    gaussian_quantiles,
    history_splice,
    register_model,
)

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class ThetaConfig:
    theta: float = 2.0
    season_length: int = 7
    deseasonalize: bool = True
    alphas: tuple = (0.02, 0.05, 0.1, 0.2, 0.3, 0.5, 0.8)
    interval_width: float = 0.95


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ThetaParams:
    intercept: jax.Array   # (S,) trend intercept (seasonally-adjusted space)
    slope: jax.Array       # (S,) trend slope per day
    level: jax.Array       # (S,) final SES level of the theta line
    alpha: jax.Array       # (S,) selected smoothing constant
    seas: jax.Array        # (S, m) multiplicative seasonal indices
    sigma: jax.Array       # (S,) one-step residual std (original space)
    fitted: jax.Array      # (S, T) one-step-ahead fitted values (original space)
    day0: jax.Array
    t_fit_end: jax.Array


def _seasonal_indices(y, mask, dow, m):
    """Masked multiplicative index per seasonal slot, normalized to mean 1."""
    onehot = jax.nn.one_hot(dow, m, dtype=y.dtype)          # (T, m)
    w = mask[:, :, None] * onehot[None, :, :]               # (S, T, m)
    slot_sum = jnp.sum(w * y[:, :, None], axis=1)           # (S, m)
    slot_cnt = jnp.maximum(jnp.sum(w, axis=1), 1.0)
    slot_mean = slot_sum / slot_cnt
    overall = jnp.sum(y * mask, axis=1) / jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    idx = slot_mean / jnp.maximum(overall[:, None], _EPS)
    idx = jnp.where(idx > _EPS, idx, 1.0)
    # renormalize so indices average to 1 over slots
    return idx / jnp.maximum(jnp.mean(idx, axis=1, keepdims=True), _EPS)


def _ses_step(level, zt, mt, alpha):
    """One masked SES step: (level) -> (level', pred).  Shared verbatim by
    the fit-time path (``_ses_path``) and the streaming ``update_state``
    kernel — one body, so the incremental filter is the same float
    expression sequence as a refit continuation (docs/streaming.md).
    Masked steps are state-preserving (pred = frozen level)."""
    pred = level
    new = alpha * zt + (1 - alpha) * level
    return jnp.where(mt > 0, new, level), pred


def _ses_path(z, mask, alpha):
    """Masked SES: returns (one-step preds, final level).

    Level initialized to the mean of the first 7 observed values and updated
    only where ``mask > 0``.
    """
    head = jnp.where(jnp.cumsum(mask) <= 7, mask, 0.0)
    l0 = jnp.sum(jnp.where(mask > 0, z, 0.0) * head) / \
        jnp.maximum(jnp.sum(head), 1.0)

    def step(level, inp):
        zt, mt = inp
        return _ses_step(level, zt, mt, alpha)

    level, preds = jax.lax.scan(step, l0, (z, mask))
    return preds, level


@partial(jax.jit, static_argnames=("config",))
def fit(y, mask, day, config: ThetaConfig) -> ThetaParams:
    m = config.season_length
    dow = jnp.mod(day, m).astype(jnp.int32)                 # (T,)
    if config.deseasonalize:
        seas = _seasonal_indices(y, mask, dow, m)           # (S, m)
    else:
        seas = jnp.ones((y.shape[0], m), dtype=y.dtype)
    si = seas[:, dow]                                       # (S, T)
    y_sa = y / jnp.maximum(si, _EPS)

    # weighted OLS trend on the seasonally-adjusted series (theta=0 line)
    t = (day - day[0]).astype(y.dtype)                      # (T,)
    w = mask
    sw = jnp.maximum(jnp.sum(w, axis=1), 1.0)
    tm = jnp.sum(w * t[None, :], axis=1) / sw
    ym = jnp.sum(w * y_sa, axis=1) / sw
    tc = t[None, :] - tm[:, None]
    cov = jnp.sum(w * tc * (y_sa - ym[:, None]), axis=1)
    var = jnp.maximum(jnp.sum(w * tc * tc, axis=1), _EPS)
    slope = cov / var
    intercept = ym - slope * tm

    trend = intercept[:, None] + slope[:, None] * t[None, :]  # (S, T)
    th = config.theta
    zline = th * y_sa + (1.0 - th) * trend

    # per-series alpha grid: run SES for each candidate, pick masked-SSE
    # argmin.  Inverting Z = th*y_sa + (1-th)*trend gives
    # E[y_sa] = (1/th)*Z + (1-1/th)*trend — the classic 0.5/0.5 mean of the
    # two theta lines only at the default th=2.
    alphas = jnp.asarray(config.alphas, dtype=y.dtype)
    w_ses = 1.0 / th  # line-combination weight (distinct from the OLS mask w)

    def per_series(zs, ms, tr, sis, ys):
        def one_alpha(a):
            # score on (sse, level) only; the winner's fitted path is
            # recomputed once below rather than materialized per candidate
            preds, level = _ses_path(zs, ms, a)
            fitted = (w_ses * preds + (1.0 - w_ses) * tr) * sis
            err = (ys - fitted) * ms
            return jnp.sum(err * err), level
        sses, levels = jax.vmap(one_alpha)(alphas)
        k = jnp.argmin(sses)
        best_alpha = alphas[k]
        preds, _ = _ses_path(zs, ms, best_alpha)
        fitted = (w_ses * preds + (1.0 - w_ses) * tr) * sis
        n = jnp.maximum(jnp.sum(ms), 1.0)
        sigma = jnp.sqrt(sses[k] / n)
        return best_alpha, levels[k], fitted, sigma

    alpha, level, fitted, sigma = jax.vmap(per_series)(zline, mask, trend, si, y)
    return ThetaParams(
        intercept=intercept, slope=slope, level=level, alpha=alpha,
        seas=seas, sigma=sigma, fitted=fitted,
        day0=day[0].astype(jnp.float32),
        t_fit_end=day[-1].astype(jnp.float32),
    )


@partial(jax.jit, static_argnames=("config",))
def forecast(params: ThetaParams, day_all, t_end, config: ThetaConfig, key=None):
    m = config.season_length
    dayf = day_all.astype(jnp.float32)
    # Splice origin = fit-grid end (the frozen SES level inside a masked CV
    # eval window makes the fitted path equal the future formula there);
    # intervals widen from t_end, where observations actually stop.
    h = dayf - params.t_fit_end                             # >0 past the grid
    h_unc = dayf - t_end.astype(jnp.float32)
    t = (dayf - params.day0)

    trend = params.intercept[:, None] + params.slope[:, None] * t[None, :]
    # flat SES forecast of the theta line combined with the trend line at the
    # same 1/th weight as in fit
    w_ses = 1.0 / config.theta
    fut_sa = w_ses * params.level[:, None] + (1.0 - w_ses) * trend
    dow = jnp.mod(day_all, m).astype(jnp.int32)
    si = params.seas[:, dow]
    fut = fut_sa * si

    yhat = history_splice(params.fitted, fut, day_all, params.day0, h)

    # SES h-step variance: sigma^2 (1 + (h-1) alpha^2); history uses 1-step
    steps = jnp.maximum(h_unc, 1.0)[None, :]
    sd = params.sigma[:, None] * jnp.sqrt(
        1.0 + (steps - 1.0) * (params.alpha[:, None] ** 2)
    )
    z = ndtri(0.5 + config.interval_width / 2.0)
    return yhat, yhat - z * sd, yhat + z * sd


@partial(jax.jit, static_argnames=("config",))
def update_state(params: ThetaParams, aux, y_new, mask_new, valid, day_new,
                 config: ThetaConfig):
    """Continue the theta SES filter over K appended day-columns.

    The decomposition fit() estimated — seasonal indices, OLS trend,
    selected alpha — is FROZEN (re-estimating it is exactly what the refit
    scheduler is for); only the SES level and the (sse, n) running moments
    evolve.  Each valid step runs :func:`_ses_step`, the byte-identical
    expression the fit filter scans, so the level after k updates equals
    continuing that filter over the extended series bit-for-bit
    (tests/unit/test_state_update.py).  The SES masked step is
    state-preserving, so shape-bucket padding columns simply ride in as
    ``mask * valid == 0`` steps — with valid == 1 that product is bitwise
    the original mask.
    """
    m = config.season_length
    dayf = day_new.astype(jnp.float32)
    dow = jnp.mod(day_new, m).astype(jnp.int32)          # absolute-day slot
    t = dayf - params.day0                                # (K,)
    si = params.seas[:, dow]                              # (S, K)
    y_sa = y_new / jnp.maximum(si, _EPS)
    trend = params.intercept[:, None] + params.slope[:, None] * t[None, :]
    th = config.theta
    zline = th * y_sa + (1.0 - th) * trend
    w_ses = 1.0 / th
    m_eff = mask_new * valid[None, :]

    def per_series(level, al, zs, ms, tr, sis, ys, sse, n):
        def step(carry, inp):
            level, sse, n = carry
            zt, mt, trt, sit, yt = inp
            level2, pred = _ses_step(level, zt, mt, al)
            fitted = (w_ses * pred + (1.0 - w_ses) * trt) * sit
            err = (yt - fitted) * mt
            return (level2, sse + err * err, n + mt), fitted

        (level, sse, n), fitted = jax.lax.scan(
            step, (level, sse, n), (zs, ms, tr, sis, ys)
        )
        return level, sse, n, fitted

    level, sse, n, preds = jax.vmap(per_series)(
        params.level, params.alpha, zline, m_eff, trend, si, y_new,
        aux["sse"], aux["n_obs"]
    )
    sigma = jnp.sqrt(sse / jnp.maximum(n, 1.0))
    t2 = jnp.maximum(
        params.t_fit_end,
        jnp.max(jnp.where(valid > 0, dayf, params.t_fit_end)),
    )
    params2 = dataclasses.replace(
        params, level=level, sigma=sigma, t_fit_end=t2
    )
    return params2, {"sse": sse, "n_obs": n}, preds


def init_update_aux(params: ThetaParams, y=None, mask=None):
    """Seed (sse, n_obs) for sigma continuation; see the holt_winters
    counterpart for the sqrt/square round-trip caveat."""
    if mask is not None:
        n = jnp.sum(jnp.asarray(mask, jnp.float32), axis=1)
    else:
        n = jnp.full_like(params.sigma, float(params.fitted.shape[1]))
    sse = params.sigma**2 * jnp.maximum(n, 1.0)
    return {"sse": sse, "n_obs": n}


register_model("theta", fit, forecast, ThetaConfig,
               forecast_quantiles=gaussian_quantiles(forecast),
               update_state=update_state, init_update_aux=init_update_aux)
