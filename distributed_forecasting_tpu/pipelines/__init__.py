from distributed_forecasting_tpu.pipelines.catalog import CatalogPipeline
from distributed_forecasting_tpu.pipelines.training import TrainingPipeline

__all__ = ["CatalogPipeline", "TrainingPipeline"]
