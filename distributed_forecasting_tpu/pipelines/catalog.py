"""Catalog bootstrap pipeline — the Unity-Catalog DDL equivalent.

Reference: ``forecasting/pipelines/catalog.py:3-22`` runs ``CREATE CATALOG IF
NOT EXISTS``, ``GRANT CREATE, USAGE ... TO account users``, ``USE CATALOG``,
``CREATE SCHEMA IF NOT EXISTS`` with defaults ``hackathon.sales``.  Same
bootstrap against the framework's dataset catalog.
"""

from __future__ import annotations

from distributed_forecasting_tpu.data.catalog import DatasetCatalog

DEFAULT_CATALOG = "hackathon"
DEFAULT_SCHEMA = "sales"
DEFAULT_GRANTS = ["CREATE", "USAGE"]


class CatalogPipeline:
    def __init__(
        self,
        catalog: DatasetCatalog,
        catalog_name: str = DEFAULT_CATALOG,
        schema_name: str = DEFAULT_SCHEMA,
    ):
        self.catalog = catalog
        self.catalog_name = catalog_name or DEFAULT_CATALOG
        self.schema_name = schema_name or DEFAULT_SCHEMA

    def initialize_catalog(self) -> None:
        self.catalog.create_catalog(self.catalog_name, grants=DEFAULT_GRANTS)
        self.catalog.create_schema(self.catalog_name, self.schema_name)
