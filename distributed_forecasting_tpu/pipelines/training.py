"""Training pipelines, librarized.

The reference never moved training out of its notebook — its
``forecasting/pipelines/training.py`` is an empty file (SURVEY.md §2.3-7).
This module librarizes both notebook training paths:

  * :meth:`TrainingPipeline.fine_grained` — the headline 500-series
    per-(store,item) workload (reference ``notebooks/prophet/
    02_training.py:260-328``): history -> batched fit -> rolling-origin CV ->
    tracked run(s) -> forecast table -> serving artifact.
  * :meth:`TrainingPipeline.allocated` — the traditional baseline
    (``02_training.py:119-256``): aggregate per item across stores, fit
    item-level models, allocate store forecasts by each store's historical
    share of the item's sales (the window-function ratio join at
    ``02_training.py:237-247``).

Tracking layout: ONE batched run per fit carrying aggregate metrics, the
model config, the per-series metric table (parquet artifact) and the
serving artifact — collapsing the reference's 500 tracking-server round
trips (SURVEY.md §3.1 hot loop (b)).  Optionally, per-series drill-down runs
named ``run_item_{item}_store_{store}`` for naming parity with the
reference's run tree (``02_training.py:160-161``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data import DatasetCatalog, tensorize
from distributed_forecasting_tpu.engine import (
    CVConfig,
    cross_validate,
    fit_forecast,
    forecast_frame,
)
from distributed_forecasting_tpu.models.base import get_model
from distributed_forecasting_tpu.serving import BatchForecaster
from distributed_forecasting_tpu.tracking import FileTracker
from distributed_forecasting_tpu.utils import get_logger
from distributed_forecasting_tpu.utils.config import freeze

_METRICS = ("mse", "rmse", "mae", "mape", "smape", "mdape", "coverage",
            "mase")

# per-series drill-down runs: warn above this count (O(S) host loop)
_PER_SERIES_RUNS_WARN = 2000


def _comparability_params(batch, cv):
    """Promotion-gate comparability stamp: the CV protocol and data span
    behind this run's ``val_*`` metrics.  ``tasks/promote.py`` compares
    these between candidate and champion runs — scores measured on
    different history windows or CV configs are not strictly comparable
    (the data, not the model, may explain a difference), and the gate
    warns (or refuses) when they differ.

    ``cv``: the CVConfig actually used (not the raw conf, which could
    drift from what ran); None when CV was skipped."""
    dates = batch.dates()
    return {
        "cv_protocol": (f"{cv.initial}/{cv.period}/{cv.horizon}"
                        if cv is not None else "none"),
        "data_span": (f"{dates[0].date()}..{dates[-1].date()}"
                      f":{getattr(batch, 'freq', 'D')}"),
    }


def _config_from_conf(model: str, model_conf: Optional[Dict[str, Any]]):
    fns = get_model(model)
    # YAML sequences arrive as lists; configs are static jit args and must be
    # hashable (e.g. ThetaConfig.alphas, CurveModelConfig tuples)
    return fns.config_cls(
        **{k: freeze(v) for k, v in (model_conf or {}).items()}
    )


_CALENDAR_DAILY_FAMILIES = frozenset({"prophet", "curve", "prophet_ar"})


def _check_cadence(freq: str, model: str, model_conf, regressors=None,
                   tuning=None):
    """Non-daily grids work for every cadence-agnostic family (HW, arima,
    theta, croston — they see a contiguous step grid), but the curve
    model's weekly/yearly Fourier, holiday calendars, daily regressor
    grids, and the tuned path are CALENDAR-DAILY constructs; a clear
    error here beats silently fitting a 7-step "weekly" seasonality on
    weekly-cadence data."""
    if freq == "D":
        return
    fams = set()
    if model in ("auto", "blend"):
        from distributed_forecasting_tpu.engine.select import DEFAULT_FAMILIES

        fams = set((model_conf or {}).get("families", DEFAULT_FAMILIES))
    bad = ({model} | fams) & _CALENDAR_DAILY_FAMILIES
    if bad or (tuning and tuning.get("enabled")):
        raise ValueError(
            f"training.freq={freq!r}: the curve model's seasonalities and "
            f"the tuned path are calendar-daily; use the cadence-agnostic "
            f"families (holt_winters/arima/theta/croston) or freq: D"
            + (f" (conf names {sorted(bad)})" if bad else "")
        )
    if regressors:
        raise ValueError(
            f"training.freq={freq!r}: conf-driven regressors resolve on a "
            f"daily calendar grid; use freq: D"
        )
    if isinstance((model_conf or {}).get("holidays"), (str, dict)):
        raise ValueError(
            f"training.freq={freq!r}: holiday calendars are daily; "
            f"use freq: D"
        )


def _resolve_model_conf(
    model: str,
    model_conf: Optional[Dict[str, Any]],
    batch,
    horizon: int,
    cv_conf: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """The ONE conf-translation chain — named holidays, season_length:
    auto, arima order: auto — applied identically on every path that
    builds a model config from task conf (plain, allocated, auto/blend
    per-family), so an 'order' key can never reach a config constructor
    as an unexpected kwarg on one path while working on another."""
    out = _resolve_season_conf(
        _resolve_holidays_conf(model_conf, batch, horizon), batch
    )
    # trigger on ANY order* key, not just "order": resolve_order_conf owns
    # the clear rejection of order_candidates/order_metric without "order"
    # (gating on "order" alone would let the stray keys fall through to
    # ArimaConfig as an opaque unexpected-keyword TypeError)
    if model == "arima" and any(
        k in (out or {}) for k in ("order", "order_candidates", "order_metric")
    ):
        from distributed_forecasting_tpu.engine.order import resolve_order_conf

        out = resolve_order_conf(out, batch, cv_conf)
    return out


def _resolve_season_conf(
    model_conf: Optional[Dict[str, Any]], batch
) -> Optional[Dict[str, Any]]:
    """Translate ``season_length: auto`` into the batch's detected dominant
    period (``engine/season``) — config fields are static jit args, so the
    detection runs once here on the host and the config carries a plain
    int.  Any other value passes through untouched."""
    if not model_conf or model_conf.get("season_length") != "auto":
        return model_conf
    from distributed_forecasting_tpu.engine.season import detect_season_length

    out = dict(model_conf)
    # the no-detectable-period fallback must match the grid cadence: 7 is
    # the daily-domain default; a weekly/monthly grid falls back to its
    # natural yearly period instead of a meaningless 7-week/7-month cycle
    default = {"D": 7, "W": 52, "M": 12}.get(batch.freq, 7)
    out["season_length"] = detect_season_length(batch, default=default)
    return out


def _resolve_holidays_conf(
    model_conf: Optional[Dict[str, Any]], batch, horizon: int
) -> Optional[Dict[str, Any]]:
    """Translate a NAMED holiday calendar in a task conf into the static
    epoch-day spec the curve model carries.

    The reference's AutoML trainer turns on holidays by name alone —
    ``country_name="US"`` (``notebooks/automl/22-09-26-06:54-Prophet-*.py:118``)
    — so a task YAML here accepts the same ergonomics::

        model_conf:
          holidays: US                 # or the expanded form:
          holidays:
            calendar: US
            lower_window: 1            # widen each occurrence like Prophet
            upper_window: 1
            custom:                    # extra events, Prophet-dict style
              promo: ["2017-11-24", "2017-12-26"]

    The calendar is materialized over the batch's date range extended by the
    horizon (``data/holidays.us_federal_holidays``), so forecast-window
    occurrences get indicator columns too.  An explicit epoch-day spec
    (list/tuple of (name, days) pairs) passes through untouched.
    """
    if not model_conf or not isinstance(model_conf.get("holidays"), (str, dict)):
        return model_conf
    from distributed_forecasting_tpu.data import holidays as H

    spec = model_conf["holidays"]
    if isinstance(spec, str):
        spec = {"calendar": spec}
    lower = int(spec.get("lower_window", 0))
    upper = int(spec.get("upper_window", 0))
    epoch = pd.Timestamp("1970-01-01")
    start = epoch + pd.Timedelta(days=int(batch.day[0]))
    end = epoch + pd.Timedelta(days=int(batch.day[-1]) + horizon)
    name = spec.get("calendar")
    custom = spec.get("custom") or {}
    if not name and not custom:
        raise ValueError(
            "holidays conf resolved to an empty calendar: give 'calendar: "
            "US', a 'custom' dates dict, or both"
        )
    out = dict(model_conf)
    # the shared resolver validates the calendar name AND rejects custom
    # names that collide with base holidays (a tenant's "christmas" promo
    # silently replacing the federal date was exactly the ambiguity the
    # old dict-update merge allowed)
    out["holidays"] = H.holiday_spec_for_range(
        start, end, calendar=(name or "none"), custom=custom,
        lower_window=lower, upper_window=upper)
    return out


def _load_regressors(catalog, regressors: Dict[str, Any], batch, horizon: int,
                     config):
    """Conf-driven covariate loading shared by the plain and tuned training
    paths: read the catalog table, tensorize onto the batch grid extended by
    ``horizon``, stamp column count/names into the config.  Returns
    ``(xreg, config)``."""
    import dataclasses

    from distributed_forecasting_tpu.data.tensorize import tensorize_regressors

    cols = list(regressors["columns"])
    reg_df = catalog.read_table(regressors["table"])
    xreg = tensorize_regressors(
        reg_df, batch, cols, horizon=horizon,
        per_series=bool(regressors.get("per_series", False)),
    )
    config = dataclasses.replace(
        config, n_regressors=len(cols), regressor_names=tuple(cols)
    )
    return xreg, config


class TrainingPipeline:
    def __init__(self, catalog: DatasetCatalog, tracker: FileTracker):
        self.catalog = catalog
        self.tracker = tracker
        self.logger = get_logger("TrainingPipeline")

    # -------------------------------------------------------------- pipeline
    def _run_stages(self, name: str, prep, dispatch, complete, executor):
        """Route one experiment's prep/dispatch/complete stages.

        With a caller-owned executor (``run_many``), submit and return the
        :class:`~distributed_forecasting_tpu.engine.executor.ExperimentHandle`
        — the caller flushes and collects.  Standalone, run through a local
        executor under the process-wide ``pipeline:`` config and block for
        the result, so the public API stays synchronous.
        """
        from distributed_forecasting_tpu.engine.executor import (
            TrainingExecutor,
        )

        if executor is not None:
            return executor.submit(name, prep, dispatch, complete)
        ex = TrainingExecutor()
        with ex:
            handle = ex.submit(name, prep, dispatch, complete)
        return handle.result()

    def run_many(self, specs, pipeline=None) -> Dict[str, Any]:
        """Pipeline several independent experiments through one executor.

        ``specs``: iterable of keyword dicts for :meth:`fine_grained`.  While
        experiment *i*'s completion stage (artifact serialization, tracker
        writes, table save) drains on the writer thread, experiment *i+1* is
        already tensorizing and dispatching — the overlap bench.py's probe
        measures.  Stage C keeps submission order, so tracker/catalog write
        order matches a serial loop.

        Returns ``{"results": [...], "pipeline": stage_metrics}`` with
        results in submission order.  The first completion failure is
        re-raised after the pipeline drains (remaining experiments still
        complete; their handles carry their own outcomes).
        """
        from distributed_forecasting_tpu.engine.executor import (
            TrainingExecutor,
        )

        ex = TrainingExecutor(config=pipeline)
        with ex:
            handles = [
                self.fine_grained(**spec, _executor=ex) for spec in specs
            ]
        return {
            "results": [h.result() for h in handles],
            "pipeline": ex.stage_metrics(),
        }

    # ------------------------------------------------------------------ fine
    def fine_grained(
        self,
        source_table: str,
        output_table: str,
        model: str = "prophet",
        model_conf: Optional[Dict[str, Any]] = None,
        cv_conf: Optional[Dict[str, Any]] = None,
        experiment: str = "finegrain_forecasting",
        horizon: int = 90,
        key_cols=("store", "item"),
        run_cross_validation: bool = True,
        per_series_runs: bool = False,
        tuning: Optional[Dict[str, Any]] = None,
        trace_dir: Optional[str] = None,
        seed: int = 0,
        bucketed: bool = False,
        regressors: Optional[Dict[str, Any]] = None,
        cv_artifact: bool = False,
        calibrate_intervals: bool = False,
        freq: str = "D",
        _executor=None,
    ) -> Dict[str, Any]:
        # ``_executor``: internal pipelining hook (see run_many / engine/
        # executor.py).  When a TrainingExecutor is passed, this submits the
        # experiment and returns its ExperimentHandle instead of blocking —
        # validation errors still raise immediately on this thread.
        if regressors:
            from distributed_forecasting_tpu.models.base import get_model

            if model in ("auto", "blend"):
                raise ValueError(
                    f"training.regressors is not supported together with "
                    f"model={model!r} — the non-curve families in the "
                    f"selection/blend pool cannot use covariates; fit the "
                    f"curve model directly with regressors"
                )
            # unconditional: the tuned path is curve-only, but a conf naming
            # a non-curve model with regressors must still fail loudly
            # rather than silently training a different family
            if not get_model(model).supports_xreg:
                raise ValueError(
                    f"model {model!r} does not accept exogenous regressors; "
                    f"use the curve model ('prophet')"
                )
        if cv_artifact and (model in ("auto", "blend")
                            or (tuning and tuning.get("enabled"))):
            raise ValueError(
                "training.cv_artifact is only supported on the plain "
                "fine-grained path (not model='auto'/'blend' or "
                "tuning.enabled)"
            )
        if calibrate_intervals:
            # scoped to the plain path: the CV pass that calibration reuses
            # runs there; silently ignoring the flag elsewhere would ship
            # uncalibrated bands the operator believes are calibrated
            if model == "auto" or (tuning and tuning.get("enabled")):
                raise ValueError(
                    "training.calibrate_intervals is supported on the plain "
                    "and model='blend' paths (not model='auto' or "
                    "tuning.enabled)"
                )
            if bucketed:
                raise ValueError(
                    "training.calibrate_intervals is not supported together "
                    "with training.bucketed — the bucketed artifact has no "
                    "shared series axis to carry per-series scales"
                )
            if not run_cross_validation and model != "blend":
                # the blend path always runs its own CV pass (weights AND
                # calibration), so the flag is irrelevant there
                raise ValueError(
                    "training.calibrate_intervals requires "
                    "run_cross_validation: the CV residuals ARE the "
                    "calibration set"
                )
        _check_cadence(freq, model, model_conf, regressors=regressors,
                       tuning=tuning)
        if tuning and tuning.get("enabled"):
            if bucketed:
                raise ValueError(
                    "training.bucketed is not supported together with "
                    "tuning.enabled — the tuned path fits on the shared grid"
                )
            return self._fine_grained_tuned(
                source_table, output_table, model_conf, cv_conf, tuning,
                experiment, horizon, key_cols, regressors=regressors,
                _executor=_executor,
            )
        if model in ("auto", "blend"):
            if bucketed:
                raise ValueError(
                    f"training.bucketed is not supported together with "
                    f"model={model!r} — pooled fits run on the shared grid"
                )
            if model == "blend":
                return self._fine_grained_blend(
                    source_table, output_table, model_conf, cv_conf,
                    experiment, horizon, key_cols, seed, freq=freq,
                    calibrate_intervals=calibrate_intervals,
                    _executor=_executor,
                )
            return self._fine_grained_auto(
                source_table, output_table, model_conf, cv_conf,
                experiment, horizon, key_cols, seed, freq=freq,
                _executor=_executor,
            )
        from distributed_forecasting_tpu.utils.profiling import PhaseTimer, device_trace

        # Three pipeline stages (engine/executor.py).  prep and dispatch run
        # on the caller thread; complete runs after the sanctioned
        # device_pull — on the writer thread when pipelined, inline when not.
        # The stages share one mutable state dict; the split moves WHEN the
        # host waits, never WHAT is computed (byte-identity contract).

        def prep() -> Dict[str, Any]:
            timer = PhaseTimer()
            with timer.phase("read"):
                df = self.catalog.read_table(source_table)
            with timer.phase("tensorize"):
                batch = tensorize(df, key_cols=key_cols, freq=freq)
            # fused data prep BEFORE config resolution: the fit sees the
            # cleaned tensor, and a detected season feeds the config the
            # same way season_length: auto would (but from the repaired
            # series — a 30-sigma spike no longer poisons the ACF)
            mconf = model_conf
            prep_report = None
            prep_xreg = None
            prep_frames = None
            from distributed_forecasting_tpu.engine.autoprep import (
                autoprep_config,
            )

            apcfg = autoprep_config()
            if apcfg.enabled and apcfg.any_stage:
                from distributed_forecasting_tpu.engine.autoprep import (
                    autoprep_batch,
                )

                with timer.phase("autoprep"):
                    prep_res = autoprep_batch(batch, apcfg, horizon=horizon)
                prep_report = prep_res.report
                prep_xreg = prep_res.xreg
                if prep_report is not None:
                    # materialize the artifact frames against the RAW batch
                    # before it is swapped for the cleaned tensor —
                    # repairs_frame's y_raw column is the original value
                    prep_frames = {
                        "prep_report.parquet":
                            prep_report.to_frame(batch),
                        "prep_repairs.parquet":
                            prep_report.repairs_frame(batch),
                    }
                batch = prep_res.batch
                if (prep_res.season_length is not None
                        and (mconf or {}).get("season_length") == "auto"):
                    mconf = dict(mconf)
                    mconf["season_length"] = int(prep_res.season_length)
                self.logger.info(
                    "autoprep: %s", prep_report.summary()
                    if prep_report else "{}")
            # config AFTER tensorize: a named holiday calendar resolves over
            # the batch's actual date range (+horizon)
            config = _config_from_conf(
                model, _resolve_model_conf(model, mconf, batch, horizon,
                                           cv_conf)
            )
            if (model_conf or {}).get("season_length") == "auto":
                self.logger.info(
                    "season_length: auto -> detected period %d",
                    config.season_length,
                )
            if (model_conf or {}).get("order") == "auto":
                self.logger.info(
                    "arima order: auto -> selected (p, d, q) = (%d, %d, %d)",
                    config.p, config.d, config.q,
                )
            xreg = None
            if regressors:
                # conf-driven covariates (Prophet add_regressor parity at the
                # task layer): a catalog table with date (+ key cols when
                # per_series) + the named columns, covering history AND horizon
                with timer.phase("tensorize_regressors"):
                    xreg, config = _load_regressors(
                        self.catalog, regressors, batch, horizon, config
                    )
            if prep_xreg is not None:
                # autoprep holiday indicator columns join the regressor
                # tensor exactly like conf-driven covariates (shared
                # calendar: (T+H, Rh)) — names stamped into the config so
                # the artifact records what the fit saw
                import dataclasses as _dc

                hnames = tuple(prep_report.holiday_names)
                if xreg is None:
                    xreg = prep_xreg
                elif xreg.ndim == 3:
                    hx = jnp.broadcast_to(
                        prep_xreg[None],
                        (xreg.shape[0],) + prep_xreg.shape)
                    xreg = jnp.concatenate([xreg, hx], axis=-1)
                else:
                    xreg = jnp.concatenate([xreg, prep_xreg], axis=-1)
                config = _dc.replace(
                    config,
                    n_regressors=int(config.n_regressors) + len(hnames),
                    regressor_names=tuple(config.regressor_names) + hnames,
                )
            self.logger.info(
                "fine-grained fit: %d series x %d days, model=%s%s",
                batch.n_series, batch.n_time, model,
                f", {config.n_regressors} regressors" if xreg is not None
                else "",
            )
            return {"timer": timer, "batch": batch, "config": config,
                    "xreg": xreg, "prep_report": prep_report,
                    "prep_frames": prep_frames}

        def dispatch(state: Dict[str, Any]) -> Dict[str, Any]:
            timer, batch = state["timer"], state["batch"]
            config, xreg = state["config"], state["xreg"]
            t_start = time.time()
            key = jax.random.PRNGKey(seed)
            cv_metrics = None
            cv_frame = None
            cv = CVConfig(**(cv_conf or {})) if run_cross_validation else None
            buckets = params = None
            # every launch below is asynchronous: the phase timers measure
            # dispatch (host trace + launch) only; device wall-clock lands in
            # fit_seconds / pipeline_pull_seconds at the sanctioned pull
            with device_trace(trace_dir):
                if run_cross_validation:
                    with timer.phase("cross_validation"):
                        if cv_artifact:
                            # one CV pass yields metrics AND the raw frame
                            cv_metrics, cv_frame = cross_validate(
                                batch, model=model, config=config, cv=cv,
                                key=key, xreg=xreg, return_frame=True,
                                calibrate=calibrate_intervals,
                            )
                        else:
                            cv_metrics = cross_validate(
                                batch, model=model, config=config, cv=cv,
                                key=key, xreg=xreg,
                                calibrate=calibrate_intervals,
                            )
                with timer.phase("fit_forecast"):
                    if bucketed:
                        # ragged batches: span buckets on trimmed grids (CV
                        # above stays on the shared grid — short buckets may
                        # not cover the CV `initial` window, and masks keep
                        # it correct)
                        from distributed_forecasting_tpu.engine import (
                            fit_forecast_bucketed,
                        )

                        buckets, result = fit_forecast_bucketed(
                            batch, model=model, config=config,
                            horizon=horizon, key=key, xreg=xreg,
                            autoprep=False,  # prep() already cleaned
                        )
                    else:
                        params, result = fit_forecast(
                            batch, model=model, config=config,
                            horizon=horizon, key=key, xreg=xreg,
                            autoprep=False,  # prep() already cleaned
                        )
            state.update(t_start=t_start, cv=cv, cv_metrics=cv_metrics,
                         cv_frame=cv_frame, buckets=buckets, params=params,
                         result=result)
            return state

        def complete(state: Dict[str, Any]) -> Dict[str, Any]:
            timer, batch = state["timer"], state["batch"]
            config = state["config"]
            cv, cv_metrics = state["cv"], state["cv_metrics"]
            buckets, params = state["buckets"], state["params"]
            result = state["result"]
            interval_scale = None
            if calibrate_intervals:
                # widen/tighten the shipped bands by the CV-conformal factor —
                # the forecast table and the serving artifact carry calibrated
                # bands; the logged val_coverage stays the RAW band's coverage
                # and val_coverage_calibrated (from cv.py's calibrate branch)
                # reports the calibrated one, so the before/after is visible
                import dataclasses as _dc

                from distributed_forecasting_tpu.engine import (
                    apply_interval_scale,
                )
                from distributed_forecasting_tpu.models.base import get_model

                interval_scale = cv_metrics["_interval_scale"]
                _, lo_c, hi_c = apply_interval_scale(
                    result.yhat, result.lo, result.hi, interval_scale,
                    floor=get_model(model).band_floor,
                )
                result = _dc.replace(result, lo=lo_c, hi=hi_c)
            fit_seconds = time.time() - state["t_start"]

            ok = np.asarray(result.ok)
            n_failed = int((~ok).sum())
            if n_failed == batch.n_series:
                # the reference's automl post-pass raises when nothing trained
                # (notebooks/automl/...py:151-156)
                raise RuntimeError("no series trained successfully")

            eid = self.tracker.create_experiment(experiment)
            with self.tracker.start_run(
                eid,
                run_name=f"batched_{model}_fit",
                tags={"model": model, "partial_model": str(n_failed > 0)},
            ) as run:
                from distributed_forecasting_tpu.models import prophet_glm

                if bucketed:
                    import dataclasses as _dc

                    run.log_params(_dc.asdict(config))
                    run.log_params({"n_buckets": len(buckets)})
                elif model in ("prophet", "curve"):
                    run.log_params(prophet_glm.extract_params(params, config))
                else:
                    import dataclasses as _dc

                    run.log_params(_dc.asdict(config))
                from distributed_forecasting_tpu.data.tensorize import (
                    resolved_backend,
                )

                run.log_params(
                    {
                        "n_series": batch.n_series,
                        "n_time": batch.n_time,
                        "horizon": horizon,
                        "n_failed_series": n_failed,
                        # which host data plane produced the tensor (the
                        # phase_tensorize_seconds metric is comparable across
                        # backends; see data/tensorize.py)
                        # the native path is daily-only; record what ran
                        "tensorize_backend": (
                            resolved_backend(n_keys=len(key_cols))
                            if batch.freq == "D" else "pandas"
                        ),
                        **_comparability_params(batch, cv),
                    }
                )
                agg = {"fit_seconds": fit_seconds,
                       "series_per_second":
                           batch.n_series / max(fit_seconds, 1e-9)}
                agg.update(timer.metrics())  # per-phase wall-clock tracing
                ps = state.get("pipeline_stage_seconds")
                if ps:
                    # executor stage timings next to the phase_* summary
                    # (timing metrics sit outside the byte-identity contract)
                    agg.update({f"pipeline_{k}_seconds": round(float(v), 4)
                                for k, v in ps.items()})
                series_table = batch.key_frame()
                series_table["fit_ok"] = ok
                if cv_metrics is not None:
                    for name in _METRICS:
                        vals = np.asarray(cv_metrics[name])
                        series_table[name] = vals
                        # nanmean: a per-series NaN (e.g. mase on a constant
                        # training window) must not poison the aggregate
                        agg[f"val_{name}"] = float(np.nanmean(vals[ok])) if ok.any() else float("nan")
                    agg["n_cv_cutoffs"] = cv_metrics["_n_cutoffs"]
                if interval_scale is not None:
                    scales = np.asarray(interval_scale)
                    series_table["interval_scale"] = scales
                    agg["interval_scale_mean"] = float(np.mean(scales[ok])) if ok.any() else float("nan")
                    # raw val_coverage stays above; this is the shipped band's
                    cov_c = np.asarray(cv_metrics["_coverage_calibrated"])
                    series_table["coverage_calibrated"] = cov_c
                    agg["val_coverage_calibrated"] = float(np.mean(cov_c[ok])) if ok.any() else float("nan")
                prep_report = state.get("prep_report")
                if prep_report is not None:
                    # what autoprep did, per batch (metrics), per series
                    # (prep_report) and per repaired point (prep_repairs) —
                    # the inspectability contract: repairs exist in the fit
                    # tensor and in these artifacts, never in stored history
                    agg.update(prep_report.summary())
                    for name, frame in (state.get("prep_frames")
                                        or {}).items():
                        if len(frame):
                            run.log_table(name, frame)
                run.log_metrics(agg)
                run.log_table("series_metrics.parquet", series_table)
                if cv_artifact and run_cross_validation:
                    # raw per-cutoff forecasts (Prophet diagnostics shape),
                    # computed in the cross_validation phase above — opt-in:
                    # at 500x1826x3 it is a ~2.7M-row parquet
                    run.log_table("cv_forecasts.parquet", state["cv_frame"])

                if bucketed:
                    from distributed_forecasting_tpu.serving import (
                        BucketedForecaster,
                    )

                    forecaster = BucketedForecaster.from_bucketed_fit(
                        buckets, model, config
                    )
                else:
                    forecaster = BatchForecaster.from_fit(
                        batch, params, model, config,
                        interval_scale=interval_scale,
                    )
                forecaster.save(run.artifact_path("forecaster"))

                if per_series_runs:
                    self._log_per_series_runs(eid, series_table, run.run_id)

                run_id = run.run_id

            table_df = forecast_frame(batch, result)
            version = self.catalog.save_table(output_table, table_df)
            self.logger.info(
                "wrote %s (version %s): %d rows; fit %.2fs (%.1f series/s); "
                "%d/%d series ok",
                output_table, version, len(table_df), fit_seconds,
                agg["series_per_second"], batch.n_series - n_failed,
                batch.n_series,
            )
            if n_failed:
                self.logger.warning(
                    "partial model: %d series fell back", n_failed)
            return {
                "experiment_id": eid,
                "run_id": run_id,
                "table_version": version,
                "n_series": batch.n_series,
                "n_failed": n_failed,
                "fit_seconds": fit_seconds,
                "metrics": {k: v for k, v in agg.items()},
            }

        return self._run_stages(experiment, prep, dispatch, complete,
                                _executor)

    # ------------------------------------------------------------- tuned fit
    def _fine_grained_tuned(
        self,
        source_table: str,
        output_table: str,
        model_conf: Optional[Dict[str, Any]],
        cv_conf: Optional[Dict[str, Any]],
        tuning: Dict[str, Any],
        experiment: str,
        horizon: int,
        key_cols,
        regressors: Optional[Dict[str, Any]] = None,
        _executor=None,
    ) -> Dict[str, Any]:
        """Per-series hyperparameter-tuned curve-model training (AutoML-path
        parity, ``notebooks/automl/22-09-26...py:107-178``): vectorized
        random search -> per-series winning scales/mode -> refit -> per-mode
        forecasts combined by each series' winning mode."""
        import jax as _jax
        import jax.numpy as _jnp

        from distributed_forecasting_tpu.engine.cv import CVConfig
        from distributed_forecasting_tpu.engine.fit import ForecastResult, forecast_frame
        from distributed_forecasting_tpu.engine.hyper import (
            HyperSearchConfig,
            tune_curve_model,
        )
        from distributed_forecasting_tpu.models import prophet_glm

        def prep() -> Dict[str, Any]:
            df = self.catalog.read_table(source_table)
            batch = tensorize(df, key_cols=key_cols)
            base = _config_from_conf(
                "prophet", _resolve_holidays_conf(model_conf, batch, horizon)
            )
            xreg = None
            if regressors:
                xreg, base = _load_regressors(
                    self.catalog, regressors, batch, horizon, base
                )
            search = HyperSearchConfig(
                n_trials=int(tuning.get("n_trials", 8)),
                metric=tuning.get("metric", "smape"),
                seed=int(tuning.get("seed", 0)),
                # TPE-parity adaptive zoom: rounds > 1 resample per series
                # around incumbents with shrinking width (engine/hyper.py)
                adaptive_rounds=int(tuning.get("adaptive_rounds", 1)),
                zoom_sigma=float(tuning.get("zoom_sigma", 0.8)),
                zoom_factor=float(tuning.get("zoom_factor", 0.5)),
            )
            cv = CVConfig(**(cv_conf or {}))
            return {"batch": batch, "base": base, "xreg": xreg,
                    "search": search, "cv": cv}

        def dispatch(state: Dict[str, Any]) -> Dict[str, Any]:
            batch, base = state["batch"], state["base"]
            xreg, search, cv = state["xreg"], state["search"], state["cv"]
            t_start = time.time()
            # tune sees the (trimmed) history xreg; the refit params carry
            # the regressor coefficients so the serving artifact works with
            # the same covariate table (inference.regressors conf).  The
            # trial loop inside is the deepest pipeline: many independent
            # dispatches per experiment.
            tuned = tune_curve_model(batch, base_config=base, search=search,
                                     cv=cv, xreg=xreg)

            # per-mode forecasts over history+horizon, combined by winning
            # mode (day grid built on device — no scalar pulls)
            from distributed_forecasting_tpu.engine.fit import day_grid

            day_all = day_grid(batch.day, horizon)
            t_end = batch.day[-1].astype(_jnp.float32)
            import dataclasses as _dc

            outs = {}
            for mode, params in tuned.mode_params.items():
                cfg_m = _dc.replace(base, seasonality_mode=mode)
                outs[mode] = prophet_glm.forecast(
                    params, day_all, t_end, cfg_m, _jax.random.PRNGKey(0),
                    xreg=xreg,
                )
            # per-series winning-mode gather stays ON DEVICE: stack per-mode
            # outputs (M, S, T) and index with the (S,) mode-pick vector —
            # only the pick indices (strings, inherently host data) cross
            # the boundary
            modes = list(tuned.mode_params)
            sel = np.asarray(tuned.best_mode)
            pick = _jnp.asarray([modes.index(m) for m in sel])  # (S,)
            arange_s = _jnp.arange(pick.shape[0])
            yhat = _jnp.stack([outs[m][0] for m in modes])[pick, arange_s]
            lo = _jnp.stack([outs[m][1] for m in modes])[pick, arange_s]
            hi = _jnp.stack([outs[m][2] for m in modes])[pick, arange_s]
            # same fail-safe contract as the plain path (engine/fit.py
            # health_fallback): min_points gating + seasonal-naive splice
            # with lead-time-widening bands — a degenerate series gets the
            # fallback, not NaN-free garbage from a tuned refit on two points
            from distributed_forecasting_tpu.engine.fit import (
                DEFAULT_MIN_POINTS,
                health_fallback,
            )

            yhat, lo, hi, ok = health_fallback(
                batch.y, batch.mask, yhat, lo, hi, horizon,
                min_points=DEFAULT_MIN_POINTS,
            )
            result = ForecastResult(
                yhat=yhat, lo=lo, hi=hi, ok=ok, day_all=day_all)
            state.update(t_start=t_start, tuned=tuned, modes=modes, sel=sel,
                         result=result)
            return state

        def complete(state: Dict[str, Any]) -> Dict[str, Any]:
            batch, search, cv = state["batch"], state["search"], state["cv"]
            tuned, modes, sel = state["tuned"], state["modes"], state["sel"]
            result = state["result"]
            fit_seconds = time.time() - state["t_start"]
            ok = result.ok
            n_failed = int((~np.asarray(ok)).sum())
            if n_failed == batch.n_series:
                raise RuntimeError("no series trained successfully")
            if n_failed:
                self.logger.warning(
                    "tuned partial model: %d series fell back", n_failed
                )

            eid = self.tracker.create_experiment(experiment)
            with self.tracker.start_run(
                eid, run_name="tuned_curve_fit",
                tags={"model": "prophet", "tuned": "true",
                      "partial_model": str(n_failed > 0)},
            ) as run:
                run.log_params(
                    {
                        "n_trials": search.n_trials,
                        "selection_metric": search.metric,
                        "n_series": batch.n_series,
                        "horizon": horizon,
                        **_comparability_params(batch, cv),
                    }
                )
                # mean over healthy series with a finite CV score — a
                # fallback series' score is +inf (engine/hyper.py), and a
                # series can be ok (enough history for a forecast) yet have
                # no observed points in any CV eval window, which is also
                # +inf
                scores = np.asarray(tuned.best_score)[np.asarray(ok)]
                scores = scores[np.isfinite(scores)]
                val_score = (
                    float(np.mean(scores)) if scores.size else float("nan"))
                run.log_metrics(
                    {
                        f"val_{search.metric}": val_score,
                        "fit_seconds": fit_seconds,
                        "n_failed_series": float(n_failed),
                    }
                )
                run.log_table("trials.parquet", tuned.trials)
                series_table = batch.key_frame()
                series_table["best_mode"] = sel
                series_table["best_changepoint_prior_scale"] = tuned.best_cp_scale
                series_table["best_seasonality_prior_scale"] = tuned.best_seas_scale
                series_table["best_holidays_prior_scale"] = tuned.best_hol_scale
                series_table[f"best_{search.metric}"] = tuned.best_score
                run.log_table("series_metrics.parquet", series_table)
                forecaster = BatchForecaster.from_fit(
                    batch, tuned.params, "prophet", tuned.config
                )
                forecaster.save(run.artifact_path("forecaster"))
                run_id = run.run_id

            table_df = forecast_frame(batch, result)
            version = self.catalog.save_table(output_table, table_df)
            self.logger.info(
                "tuned fit: %d series, %d trials x %d modes x %d rounds in "
                "%.2fs -> %s v%s",
                batch.n_series, search.n_trials, len(modes),
                search.adaptive_rounds, fit_seconds, output_table, version,
            )
            return {
                "experiment_id": eid,
                "run_id": run_id,
                "table_version": version,
                "n_series": batch.n_series,
                "n_failed": n_failed,
                "fit_seconds": fit_seconds,
                "metrics": {f"val_{search.metric}": val_score},
            }

        return self._run_stages(experiment, prep, dispatch, complete,
                                _executor)

    # ---------------------------------------------------------- auto select
    def _fine_grained_auto(
        self,
        source_table: str,
        output_table: str,
        model_conf: Optional[Dict[str, Any]],
        cv_conf: Optional[Dict[str, Any]],
        experiment: str,
        horizon: int,
        key_cols,
        seed: int,
        freq: str = "D",
        _executor=None,
    ) -> Dict[str, Any]:
        """Per-series best-of across model families (``engine/select.py``) —
        the cross-family analogue of the AutoML path's per-series tuning.
        ``model_conf`` here may carry ``{"families": [...], "metric": ...,
        "configs": {family: {...}}}``."""
        from distributed_forecasting_tpu.engine.select import (
            DEFAULT_FAMILIES,
            fit_forecast_auto,
        )
        from distributed_forecasting_tpu.serving.ensemble import MultiModelForecaster

        mc = model_conf or {}
        families = tuple(mc.get("families", DEFAULT_FAMILIES))
        metric = mc.get("metric", "smape")

        def prep() -> Dict[str, Any]:
            cv = CVConfig(**(cv_conf or {}))
            df = self.catalog.read_table(source_table)
            batch = tensorize(df, key_cols=key_cols, freq=freq)
            configs = {
                name: _config_from_conf(
                    name, _resolve_model_conf(name, c, batch, horizon,
                                              cv_conf)
                )
                for name, c in (mc.get("configs") or {}).items()
            }
            return {"cv": cv, "batch": batch, "configs": configs}

        def dispatch(state: Dict[str, Any]) -> Dict[str, Any]:
            t_start = time.time()
            params_by_family, selection, result = fit_forecast_auto(
                state["batch"], models=families, configs=state["configs"],
                metric=metric, cv=state["cv"], horizon=horizon,
                key=jax.random.PRNGKey(seed),
            )
            state.update(t_start=t_start, params_by_family=params_by_family,
                         selection=selection, result=result)
            return state

        def complete(state: Dict[str, Any]) -> Dict[str, Any]:
            batch, cv, configs = (
                state["batch"], state["cv"], state["configs"])
            params_by_family = state["params_by_family"]
            selection, result = state["selection"], state["result"]
            fit_seconds = time.time() - state["t_start"]

            eid = self.tracker.create_experiment(experiment)
            return self._complete_auto(
                eid, batch, cv, configs, params_by_family, selection, result,
                fit_seconds, families, metric, horizon, output_table,
            )

        return self._run_stages(experiment, prep, dispatch, complete,
                                _executor)

    def _complete_auto(self, eid, batch, cv, configs, params_by_family,
                       selection, result, fit_seconds, families, metric,
                       horizon, output_table) -> Dict[str, Any]:
        from distributed_forecasting_tpu.serving.ensemble import MultiModelForecaster

        with self.tracker.start_run(
            eid, run_name="auto_select_fit",
            tags={"model": "auto", "families": ",".join(families)},
        ) as run:
            run.log_params(
                {
                    "families": list(families),
                    "selection_metric": metric,
                    "n_series": batch.n_series,
                    "horizon": horizon,
                    **_comparability_params(batch, cv),
                }
            )
            counts = selection.counts()
            valid = selection.valid
            # mean over series with at least one finite CV score — the same
            # value is logged to the tracker and returned in the summary
            val_metric = (
                float(np.mean(selection.best_score[valid]))
                if valid.any() else float("nan")
            )
            run.log_metrics(
                {
                    f"val_{metric}": val_metric,
                    "n_invalid_series": float((~valid).sum()),
                    "fit_seconds": fit_seconds,
                    **{f"n_chosen_{name}": float(counts.get(name, 0))
                       for name in families},
                }
            )
            series_table = batch.key_frame()
            series_table["chosen_model"] = selection.chosen
            series_table[f"best_{metric}"] = selection.best_score
            for name in families:
                series_table[f"{metric}_{name}"] = selection.scores[name].to_numpy()
            run.log_table("series_metrics.parquet", series_table)
            mm = MultiModelForecaster.from_fit(
                batch, params_by_family, configs, selection
            )
            mm.save(run.artifact_path("forecaster"))
            run_id = run.run_id

        table_df = forecast_frame(batch, result)
        version = self.catalog.save_table(output_table, table_df)
        self.logger.info(
            "auto-select fit: %d series over %s in %.2fs (chosen: %s) -> %s v%s",
            batch.n_series, list(families), fit_seconds, counts,
            output_table, version,
        )
        return {
            "experiment_id": eid,
            "run_id": run_id,
            "table_version": version,
            "n_series": batch.n_series,
            "n_failed": int((~np.asarray(result.ok)).sum()),
            "fit_seconds": fit_seconds,
            "chosen_counts": counts,
            "metrics": {f"val_{metric}": val_metric},
        }

    # ---------------------------------------------------------- blended fit
    def _fine_grained_blend(
        self,
        source_table: str,
        output_table: str,
        model_conf: Optional[Dict[str, Any]],
        cv_conf: Optional[Dict[str, Any]],
        experiment: str,
        horizon: int,
        key_cols,
        seed: int,
        freq: str = "D",
        calibrate_intervals: bool = False,
        _executor=None,
    ) -> Dict[str, Any]:
        """Per-series weighted cross-family pool (``engine/blend``) — where
        the auto path picks each series' single winner, this combines all
        families with inverse-CV-error weights (the M-competition result:
        combinations beat members on mixed catalogs).  ``model_conf`` may
        carry ``{"families": [...], "metric": ..., "temperature": ...,
        "configs": {family: {...}}}``."""
        from distributed_forecasting_tpu.engine.blend import fit_forecast_blend
        from distributed_forecasting_tpu.engine.select import DEFAULT_FAMILIES
        from distributed_forecasting_tpu.serving.ensemble import BlendedForecaster

        mc = model_conf or {}
        families = tuple(mc.get("families", DEFAULT_FAMILIES))
        metric = mc.get("metric", "smape")
        temperature = float(mc.get("temperature", 1.0))

        def prep() -> Dict[str, Any]:
            cv = CVConfig(**(cv_conf or {}))
            df = self.catalog.read_table(source_table)
            batch = tensorize(df, key_cols=key_cols, freq=freq)
            configs = {
                name: _config_from_conf(
                    name, _resolve_model_conf(name, c, batch, horizon,
                                              cv_conf)
                )
                for name, c in (mc.get("configs") or {}).items()
            }
            return {"cv": cv, "batch": batch, "configs": configs}

        def dispatch(state: Dict[str, Any]) -> Dict[str, Any]:
            t_start = time.time()
            params_by_family, blend, result = fit_forecast_blend(
                state["batch"], models=families, configs=state["configs"],
                metric=metric, cv=state["cv"], horizon=horizon,
                key=jax.random.PRNGKey(seed), temperature=temperature,
                calibrate=calibrate_intervals,
            )
            state.update(t_start=t_start, params_by_family=params_by_family,
                         blend=blend, result=result)
            return state

        def complete(state: Dict[str, Any]) -> Dict[str, Any]:
            fit_seconds = time.time() - state["t_start"]
            eid = self.tracker.create_experiment(experiment)
            return self._complete_blend(
                eid, state["batch"], state["cv"], state["configs"],
                state["params_by_family"], state["blend"], state["result"],
                fit_seconds, families, metric, temperature, horizon,
                output_table,
            )

        return self._run_stages(experiment, prep, dispatch, complete,
                                _executor)

    def _complete_blend(self, eid, batch, cv, configs, params_by_family,
                        blend, result, fit_seconds, families, metric,
                        temperature, horizon, output_table) -> Dict[str, Any]:
        from distributed_forecasting_tpu.serving.ensemble import BlendedForecaster

        with self.tracker.start_run(
            eid, run_name="blended_fit",
            tags={"model": "blend", "families": ",".join(families)},
        ) as run:
            run.log_params(
                {
                    "families": list(families),
                    "blend_metric": metric,
                    "temperature": temperature,
                    "n_series": batch.n_series,
                    "horizon": horizon,
                    **_comparability_params(batch, cv),
                }
            )
            valid = blend.valid
            # the pool's CV score as the weighted member scores — the
            # linear-pool approximation (the pool's own CV error is
            # bounded above by this for convex metrics); this is what
            # promotion gates compare (tasks/promote.py)
            score_mat = blend.scores[list(blend.models)].to_numpy(float)
            blended_score = np.nansum(blend.weights * score_mat, axis=1)
            # nansum over an all-NaN row is 0.0 — a "perfect" score for
            # exactly the BROKEN series; surface NaN instead
            blended_score = np.where(valid, blended_score, np.nan)
            val_metric = (
                float(np.nanmean(blended_score[valid]))
                if valid.any() else float("nan")
            )
            run.log_metrics(
                {
                    f"val_{metric}": val_metric,
                    "n_invalid_series": float((~valid).sum()),
                    "fit_seconds": fit_seconds,
                    **{f"mean_weight_{name}": w
                       for name, w in blend.mean_weights().items()},
                }
            )
            series_table = batch.key_frame()
            series_table[f"blended_{metric}"] = blended_score
            if blend.interval_scale is not None:
                series_table["interval_scale"] = blend.interval_scale
                run.log_metrics({"interval_scale_mean": float(
                    np.nanmean(blend.interval_scale[valid])
                ) if valid.any() else float("nan")})
            for i, name in enumerate(blend.models):
                series_table[f"weight_{name}"] = blend.weights[:, i]
                series_table[f"{metric}_{name}"] = blend.scores[name].to_numpy()
            run.log_table("series_metrics.parquet", series_table)
            bf = BlendedForecaster.from_fit(
                batch, params_by_family, configs, blend
            )
            bf.save(run.artifact_path("forecaster"))
            run_id = run.run_id

        table_df = forecast_frame(batch, result)
        version = self.catalog.save_table(output_table, table_df)
        self.logger.info(
            "blended fit: %d series over %s in %.2fs (mean weights: %s) -> %s v%s",
            batch.n_series, list(families), fit_seconds,
            {k: round(v, 3) for k, v in blend.mean_weights().items()},
            output_table, version,
        )
        return {
            "experiment_id": eid,
            "run_id": run_id,
            "table_version": version,
            "n_series": batch.n_series,
            "n_failed": int((~np.asarray(result.ok)).sum()),
            "fit_seconds": fit_seconds,
            "mean_weights": blend.mean_weights(),
            "metrics": {f"val_{metric}": val_metric,
                        **{f"mean_weight_{k}": v
                           for k, v in blend.mean_weights().items()}},
        }

    def _log_per_series_runs(self, eid: str, series_table: pd.DataFrame, parent: str):
        """Optional reference-shaped drill-down: one run per series, named
        ``run_item_{item}_store_{store}`` (reference ``02_training.py:160-161``).

        Where the reference logs one serialized Prophet model per series run
        (``02_training.py:193-196``), the model here is ONE batched artifact
        on the parent run — so each per-series run links its slice: the
        parent run id, the artifact path, and the series' row index into
        every leading-S parameter array (``serving/predictor.py`` loads the
        pytree; ``gather_params([row])`` extracts exactly this slice).

        This is an O(S) host loop over filesystem run directories — fine at
        the reference's 500-series scale, pathological at 50k.  Above
        ``_PER_SERIES_RUNS_WARN`` it warns; above the hard cap (default
        20000, override ``DFTPU_PER_SERIES_RUNS_MAX``) it raises and points
        at the ``series_metrics.parquet`` artifact, which already carries
        every per-series metric in one table.
        """
        import os

        n = len(series_table)
        cap = int(os.environ.get("DFTPU_PER_SERIES_RUNS_MAX", "20000"))
        if n > cap:
            raise ValueError(
                f"per_series_runs requested for {n} series, above the "
                f"{cap}-run cap: one filesystem run-dir per series does not "
                f"scale. The parent run's series_metrics.parquet artifact "
                f"already holds every per-series metric; raise "
                f"DFTPU_PER_SERIES_RUNS_MAX to override."
            )
        if n > _PER_SERIES_RUNS_WARN:
            self.logger.warning(
                "per_series_runs: creating %d tracker run directories (an "
                "O(S) host loop) — prefer the batched run's "
                "series_metrics.parquet at this scale", n,
            )
        # one buffered batch append + one directory fsync for the whole
        # experiment (tracking/filestore.py log_runs_batch), instead of
        # ~8 file ops per series in the hot loop
        rows = []
        for i, row in enumerate(series_table.itertuples(index=False)):
            d = row._asdict()
            rows.append({
                "run_name": f"run_item_{d.get('item')}_store_{d.get('store')}",
                "tags": {
                    "parent_run_id": parent,
                    "artifact_run_id": parent,
                    "artifact_path": "forecaster",
                    "series_index": str(i),
                },
                "metrics": {k: float(v) for k, v in d.items()
                            if k in _METRICS and np.isfinite(v)},
            })
        self.tracker.log_runs_batch(eid, rows)

    # ------------------------------------------------------------- allocated
    def allocated(
        self,
        source_table: str,
        output_table: str,
        model: str = "prophet",
        model_conf: Optional[Dict[str, Any]] = None,
        experiment: str = "allocated_forecasting",
        horizon: int = 90,
        seed: int = 0,
        freq: str = "D",
    ) -> Dict[str, Any]:
        """Item-level fit + store-share allocation.

        Reference steps (``02_training.py:225-254``): sum sales per item
        across stores; fit one model per item; compute each store's
        historical share ``sales / SUM(sales) OVER (PARTITION BY item)``;
        scale item forecasts down to (store, item) granularity.
        """
        _check_cadence(freq, model, model_conf)
        df = self.catalog.read_table(source_table)

        item_df = (
            df.groupby(["date", "item"], as_index=False)["sales"].sum()
        )
        batch = tensorize(item_df, key_cols=("item",), freq=freq)
        config = _config_from_conf(
            model, _resolve_model_conf(model, model_conf, batch, horizon)
        )
        key = jax.random.PRNGKey(seed)
        params, result = fit_forecast(
            batch, model=model, config=config, horizon=horizon, key=key
        )
        item_fc = forecast_frame(batch, result)  # [ds, item, y, yhat, ...]

        # store share of each item's historical sales
        totals = df.groupby(["store", "item"], as_index=False)["sales"].sum()
        item_totals = totals.groupby("item")["sales"].transform("sum")
        totals["ratio"] = totals["sales"] / item_totals
        ratios = totals[["store", "item", "ratio"]]

        merged = item_fc.merge(ratios, on="item", how="inner")
        for col in ("y", "yhat", "yhat_upper", "yhat_lower"):
            merged[col] = merged[col] * merged["ratio"]
        out = merged[
            ["ds", "store", "item", "y", "yhat", "yhat_upper", "yhat_lower",
             "training_date"]
        ]

        eid = self.tracker.create_experiment(experiment)
        with self.tracker.start_run(eid, run_name=f"allocated_{model}_fit") as run:
            run.log_params({"n_items": batch.n_series, "horizon": horizon})
            forecaster = BatchForecaster.from_fit(batch, params, model, config)
            forecaster.save(run.artifact_path("forecaster"))
            run_id = run.run_id

        version = self.catalog.save_table(output_table, out)
        self.logger.info(
            "allocated forecasts: %d items -> %d (store,item) rows -> %s v%s",
            batch.n_series, len(out), output_table, version,
        )
        return {
            "experiment_id": eid,
            "run_id": run_id,
            "table_version": version,
            "n_items": batch.n_series,
        }
