from distributed_forecasting_tpu.workflows.runner import WorkflowRunner, run_workflow_file

__all__ = ["WorkflowRunner", "run_workflow_file"]
