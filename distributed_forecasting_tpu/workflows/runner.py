"""YAML workflow runner — the dbx/Databricks-jobs stand-in (L6).

The reference deploys YAML-defined workflows of tasks with dependencies via
``dbx deploy/launch`` (``conf/deployment.yml:19-58`` — including the
commented-out multitask etl -> ml job the new framework should honor,
SURVEY.md §2.4 "Pipeline parallelism" row), launched by ``make deploy/run``
(``Makefile:1-5``).  No cluster manager is needed for a single-host TPU, so
the runner is in-process: topological execution of task nodes with explicit
``depends_on`` edges, per-task conf (inline or ``conf_file``), shared ``env``
roots, and fail-fast with a structured result report.

Workflow YAML::

    env:
      root: ./dftpu_store
    workflows:
      - name: forecasting-e2e
        tasks:
          - name: catalog
            task: catalog                # key into TASK_TYPES
            conf: {output: {catalog_name: hackathon, schema_name: sales}}
          - name: etl
            task: ingest
            depends_on: [catalog]
            conf_file: conf/tasks/ingest_config.yml
          - name: train
            task: train
            depends_on: [etl]
            ...
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from distributed_forecasting_tpu.utils import get_logger, load_conf


class WorkflowError(RuntimeError):
    pass


class WorkflowRunner:
    def __init__(self, spec: Dict[str, Any], env: Optional[Dict[str, Any]] = None):
        self.spec = spec
        self.env = {**(spec.get("env", {}) or {}), **(env or {})}
        self.logger = get_logger("WorkflowRunner")

    def _workflow(self, name: Optional[str]) -> Dict[str, Any]:
        flows = self.spec.get("workflows", [])
        if not flows:
            raise WorkflowError("no workflows defined")
        if name is None:
            return flows[0]
        for wf in flows:
            if wf.get("name") == name:
                return wf
        raise WorkflowError(f"workflow {name!r} not found")

    @staticmethod
    def _topo_order(tasks: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        by_name = {t["name"]: t for t in tasks}
        order: List[Dict[str, Any]] = []
        state: Dict[str, int] = {}  # 0 visiting, 1 done

        def visit(name: str, chain=()):
            if name in chain:
                raise WorkflowError(f"dependency cycle at {name!r}")
            if state.get(name) == 1:
                return
            node = by_name.get(name)
            if node is None:
                raise WorkflowError(f"unknown dependency {name!r}")
            for dep in node.get("depends_on", []) or []:
                visit(dep, chain + (name,))
            state[name] = 1
            order.append(node)

        for t in tasks:
            visit(t["name"])
        return order

    def run(self, workflow: Optional[str] = None) -> Dict[str, Any]:
        from distributed_forecasting_tpu.tasks import TASK_TYPES

        wf = self._workflow(workflow)
        order = self._topo_order(wf.get("tasks", []))
        self.logger.info(
            "workflow %s: %d tasks (%s)",
            wf.get("name"), len(order), " -> ".join(t["name"] for t in order),
        )
        results: Dict[str, Any] = {}
        for node in order:
            ttype = node.get("task")
            if ttype not in TASK_TYPES:
                raise WorkflowError(
                    f"task {node['name']!r}: unknown task type {ttype!r} "
                    f"(known: {sorted(TASK_TYPES)})"
                )
            conf: Dict[str, Any] = {}
            if node.get("conf_file"):
                conf.update(load_conf(node["conf_file"]))
            if node.get("conf"):
                conf.update(node["conf"])
            if self.env:
                conf.setdefault("env", {}).update(
                    {k: v for k, v in self.env.items() if k not in conf.get("env", {})}
                )
            t0 = time.time()
            self.logger.info("task %s (%s) starting", node["name"], ttype)
            try:
                out = TASK_TYPES[ttype](init_conf=conf).launch()
            except Exception as e:
                self.logger.error("task %s failed: %s", node["name"], e)
                results[node["name"]] = {"status": "FAILED", "error": str(e)}
                raise WorkflowError(f"task {node['name']} failed: {e}") from e
            results[node["name"]] = {
                "status": "OK",
                "seconds": time.time() - t0,
                "result": out,
            }
        return results


def run_workflow_file(path: str, workflow: Optional[str] = None,
                      env: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return WorkflowRunner(load_conf(path), env=env).run(workflow)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser("dftpu-workflow")
    p.add_argument("--file", "-f", required=True, help="workflow YAML")
    p.add_argument("--workflow", "-w", default=None, help="workflow name")
    p.add_argument("--env-root", default=None, help="override env.root")
    args = p.parse_args(argv)
    env = {"root": args.env_root} if args.env_root else None
    results = run_workflow_file(args.file, args.workflow, env=env)
    for name, r in results.items():
        print(f"{name}: {r['status']} ({r.get('seconds', 0):.2f}s)")


if __name__ == "__main__":
    main()
