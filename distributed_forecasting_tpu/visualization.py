"""Forecast visualization — plot parity with the reference's AutoML cells.

The reference AutoML notebook renders the fitted Prophet forecast with
changepoints overlaid (``notebooks/automl/22-09-26...py:231-253``).  These
helpers do the same from this framework's artifacts: history + forecast with
interval band, learned changepoint magnitudes, and decomposed components
(trend / weekly / yearly) recovered from the curve model's linear basis.

matplotlib is imported lazily (headless 'Agg' backend) so the library never
requires a display and the dependency stays optional.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def plot_forecast(
    batch,
    result,
    series_index: int = 0,
    ax=None,
    title: Optional[str] = None,
):
    """History points + forecast line with the interval band (one series)."""
    plt = _plt()
    if ax is None:
        _, ax = plt.subplots(figsize=(10, 4))
    import pandas as pd

    dates = pd.to_datetime(np.asarray(result.day_all, "int64"), unit="D")
    T_hist = batch.n_time
    y = np.asarray(batch.y[series_index])
    m = np.asarray(batch.mask[series_index]) > 0
    ax.plot(batch.dates()[m], y[m], "k.", ms=2, label="observed")
    ax.plot(dates, np.asarray(result.yhat[series_index]), lw=1.2, label="yhat")
    ax.fill_between(
        dates,
        np.asarray(result.lo[series_index]),
        np.asarray(result.hi[series_index]),
        alpha=0.25, linewidth=0, label="interval",
    )
    ax.axvline(batch.dates()[T_hist - 1], ls="--", lw=0.8, color="grey")
    keys = dict(zip(batch.key_names, batch.keys[series_index]))
    ax.set_title(title or f"forecast {keys}")
    ax.legend(loc="best", fontsize=8)
    return ax


def plot_changepoints(params, config, series_index: int = 0, ax=None):
    """Learned changepoint slope deltas over the changepoint grid — the
    reference's changepoint overlay, shown as the model actually stores it."""
    from distributed_forecasting_tpu.models.prophet_glm import _n_cp

    plt = _plt()
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 3))
    k = _n_cp(config)
    deltas = np.asarray(params.beta[series_index, 2 : 2 + k])
    if config.changepoint_days:
        # explicit sites: scaled by the training span the params carry
        t0, t1 = float(params.t0), float(params.t1)
        grid = (
            np.asarray(sorted(config.changepoint_days), float) - t0
        ) / max(t1 - t0, 1.0)
    else:
        grid = np.arange(1, k + 1) / (k + 1) * config.changepoint_range
    ax.bar(grid, deltas, width=0.8 / (k + 1))
    ax.set_xlabel("scaled time of changepoint")
    ax.set_ylabel("slope delta")
    ax.set_title("changepoint magnitudes")
    return ax


def plot_components(params, config, day_all, series_index: int = 0,
                    xreg=None, t_end=None):
    """Trend / weekly / yearly decomposition from the linear basis (the
    Prophet components plot equivalent).  Returns the figure."""
    import jax.numpy as jnp

    from distributed_forecasting_tpu.models.prophet_glm import decompose

    plt = _plt()
    import pandas as pd

    dates = pd.to_datetime(np.asarray(day_all, "int64"), unit="D")
    comps = {
        name: np.asarray(vals[series_index])
        for name, vals in decompose(
            params, jnp.asarray(day_all, dtype=jnp.int32), config, xreg=xreg,
            t_end=None if t_end is None else jnp.float32(t_end),
        ).items()
    }

    fig, axes = plt.subplots(len(comps), 1, figsize=(9, 2.2 * len(comps)),
                             sharex=True)
    if len(comps) == 1:
        axes = [axes]
    for ax, (name, vals) in zip(axes, comps.items()):
        if name == "weekly":
            ax.plot(dates[:15], vals[:15])  # two weeks is enough to read
        else:
            ax.plot(dates, vals)
        ax.set_ylabel(name)
    fig.tight_layout()
    return fig
