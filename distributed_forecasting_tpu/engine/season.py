"""Dominant-seasonality detection for conf-level ``season_length: auto``.

The reference's workload hardcodes weekly seasonality (daily retail data,
``Prophet(weekly_seasonality=True)``), and this framework's scan families
default to ``season_length=7`` the same way.  Real catalogs mix cadences —
weekly SKUs, monthly wholesale, hourly-aggregated-to-day patterns — and an
operator writing a task YAML should be able to say ``season_length: auto``
instead of guessing.

Method: masked autocorrelation of the FIRST-DIFFERENCED series, computed
by FFT.  Differencing kills trend (an undifferenced ACF decays slowly from
lag 1 and drowns seasonal peaks).  The masked pairwise products at every
lag are two self-correlations — ``irfft(|rfft(z)|^2)`` for the
mean-centered masked values and the same for the mask — so the whole lag
axis costs one O(T log T) transform pair per batch instead of an unrolled
per-lag reduction graph (an earlier slice-per-lag version compiled
~linearly in max_lag; ``ops/solve.yule_walker_masked`` keeps its explicit
per-lag loop because its K is small and it feeds a Toeplitz solve — at
K ~ 400 the FFT route is the right tool).  Each series normalizes by its
own pairwise-counted lag-0 autocovariance, then scores average over
series; only the (L,) score vector leaves the device.

Period selection runs on host because the result must be a static Python
int (``season_length`` is a frozen-config field that shapes compiled
programs), and single-lag rules fail in measured ways: the ACF of a
periodic signal peaks at EVERY multiple of the period and noise decides
which harmonic wins the raw argmax (observed: 180 over a true 30); a
smooth near-sinusoidal ACF is high at SMALL lags, so
smallest-above-threshold collapses to d=2; per-lag sample noise shifts
peaks by +-1 for long periods (59 for a true 60).  The selector that
survives all three is a HARMONIC COMB (pitch-detection style): each
candidate m scores the mean ACF at its first <=3 multiples minus the mean
at its anti-phase half-multiples; the argmax of that comb locates the
period (the comb curve is smooth in m — tolerance rules drift to m-1), a
full-comb rescoring of m*+-2 pins the exact lag (misalignment compounds
with the tooth index), and a near-submultiple within ``harmonic_tol``
takes precedence when the argmax sits on a harmonic.

This is batch-level detection by design: one period for the whole batch
keeps every compiled shape static (per-series periods would force a
recompile per value; series genuinely mixing cadences belong in separate
batches or span buckets).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_MIN_LAG = 2


@partial(jax.jit, static_argnames=("max_lag",))
def _acf_scores(y, mask, max_lag: int):
    """(max_lag+1,) batch-mean masked ACF of diff(y) at lags 0..max_lag."""
    dy = y[:, 1:] - y[:, :-1]
    dm = mask[:, 1:] * mask[:, :-1]
    n = jnp.maximum(jnp.sum(dm, axis=1, keepdims=True), 1.0)
    mu = jnp.sum(dy * dm, axis=1, keepdims=True) / n
    z = (dy - mu) * dm
    T = z.shape[1]
    L = int(2 ** np.ceil(np.log2(T + max_lag + 1)))  # linear, not circular
    fz = jnp.fft.rfft(z, n=L, axis=1)
    fm = jnp.fft.rfft(dm, n=L, axis=1)
    num = jnp.fft.irfft(fz * jnp.conj(fz), n=L, axis=1)[:, : max_lag + 1]
    cnt = jnp.fft.irfft(fm * jnp.conj(fm), n=L, axis=1)[:, : max_lag + 1]
    acov = num / jnp.maximum(cnt, 1.0)            # (S, max_lag+1)
    a0 = acov[:, :1]
    r = jnp.where(a0 > 1e-12, acov / jnp.maximum(a0, 1e-12), 0.0)
    return jnp.mean(r, axis=0)


def detect_season_length(
    batch,
    max_lag: int = 400,
    default: int = 7,
    min_score: float = 0.1,
    harmonic_tol: float = 0.85,
) -> int:
    """Pick the batch's dominant seasonal period as a static Python int.

    Scans lags 2..max_lag (clamped to T/3); candidate periods need two
    comb teeth inside that window, so detection requires ``T >= ~6m`` and
    periods below 4 are out of range.  Returns ``default`` when the best
    comb score stays under ``min_score`` (a genuinely non-seasonal batch
    should get the domain default, not an argmax over noise).  See the
    module docstring for the selection rationale.
    """
    T = batch.n_time
    max_lag = int(min(max_lag, max(T // 3, _MIN_LAG)))
    if max_lag < 4:
        return int(default)
    raw = np.asarray(_acf_scores(batch.y, batch.mask, max_lag))
    # 3-point smoothing: differencing attenuates a period-m signal by
    # 2 sin(pi/m), so long periods sit near the noise floor and per-lag
    # sample noise (~1/sqrt(S*T)) makes peaks jagged (measured: raw argmax
    # at 59 for a true 60)
    s = raw.copy()
    s[1:-1] = (raw[:-2] + raw[1:-1] + raw[2:]) / 3.0

    # Harmonic comb score per candidate period m (pitch-detection style):
    # mean ACF at the first <=3 multiples of m MINUS mean at the anti-phase
    # half-multiples (0.5m, 1.5m, 2.5m — deep troughs for a true period).
    # Teeth are capped at 3 and candidates need >= 2 multiples in range:
    # distant single-tooth candidates otherwise cherry-pick one aligned
    # peak + one deep trough and outscore the diluted many-teeth
    # fundamental (measured: 189 over a true 7).  The final rule is
    # smallest-within-tolerance OF THE COMB score — odd multiples of the
    # fundamental (91 = 13x7) can edge out its comb by a few percent with
    # two cherry teeth, but the fundamental always scores within
    # ``harmonic_tol`` of them and is smaller.
    cand = np.arange(4, max_lag // 2 + 1)
    if cand.size == 0:
        return int(default)
    combs = np.full(cand.shape, -np.inf)
    for i, m in enumerate(cand):
        ks = np.arange(1, min(3, max_lag // m) + 1)
        peaks_idx = ks * m
        trough_idx = np.clip(np.round((ks - 0.5) * m).astype(int), 1, max_lag)
        combs[i] = float(np.mean(s[peaks_idx]) - np.mean(s[trough_idx]))
    best_i = int(np.argmax(combs))
    m_star, c_star = int(cand[best_i]), float(combs[best_i])
    if c_star < min_score:
        return int(default)

    def full_comb(m: int) -> float:
        # every tooth in range: a +-1 misalignment compounds with the
        # tooth index (89 vs 90 differ by 4 lags at the 4th tooth), so
        # the full comb pins the exact period where the 3-tooth scan
        # cannot (measured: 89 for a true 90 at T=1080)
        ks = np.arange(1, max_lag // m + 1)
        trough = np.clip(np.round((ks - 0.5) * m).astype(int), 1, max_lag)
        return float(np.mean(s[ks * m]) - np.mean(s[trough]))

    refine = [m for m in range(m_star - 2, m_star + 3)
              if cand[0] <= m <= cand[-1]]
    m_star = max(refine, key=full_comb)
    best_i = int(m_star - cand[0])
    c_star = float(combs[best_i])
    # the comb curve is SMOOTH in m, so the argmax — not a
    # smallest-within-tolerance rule, which drifts to m-1 — locates the
    # period; what remains is the argmax landing on a HARMONIC of the
    # true period, so prefer the smallest near-submultiple (ratio >= 2,
    # off-grid by at most one lag) whose comb is within harmonic_tol
    for d in cand[: best_i]:
        ratio = round(m_star / d)
        if ratio >= 2 and abs(m_star - ratio * d) <= 1:
            if combs[d - cand[0]] >= harmonic_tol * c_star:
                return int(d)
    return int(m_star)
