"""Dominant-seasonality detection for conf-level ``season_length: auto``.

The reference's workload hardcodes weekly seasonality (daily retail data,
``Prophet(weekly_seasonality=True)``), and this framework's scan families
default to ``season_length=7`` the same way.  Real catalogs mix cadences —
weekly SKUs, monthly wholesale, hourly-aggregated-to-day patterns — and an
operator writing a task YAML should be able to say ``season_length: auto``
instead of guessing.

Method: masked autocorrelation of the FIRST-DIFFERENCED series, computed
by FFT.  Differencing kills trend (an undifferenced ACF decays slowly from
lag 1 and drowns seasonal peaks).  The masked pairwise products at every
lag are two self-correlations — ``irfft(|rfft(z)|^2)`` for the
mean-centered masked values and the same for the mask — so the whole lag
axis costs one O(T log T) transform pair per batch instead of an unrolled
per-lag reduction graph (an earlier slice-per-lag version compiled
~linearly in max_lag; ``ops/solve.yule_walker_masked`` keeps its explicit
per-lag loop because its K is small and it feeds a Toeplitz solve — at
K ~ 400 the FFT route is the right tool).  Each series normalizes by its
own pairwise-counted lag-0 autocovariance, then scores average over
series; only the (L,) score vector leaves the device.

Period selection runs on host because the result must be a static Python
int (``season_length`` is a frozen-config field that shapes compiled
programs).  A harmonic-comb score (mean ACF at a candidate's first
multiples minus its anti-phase half-multiples) GATES detection — a
non-seasonal batch falls back to the default instead of an argmax over
noise — and the period itself is the argmax of a matched cosine filter
over the whole lag axis.  Simpler per-lag rules were each implemented
and measured wrong (harmonic argmaxes, smallest-above-threshold
collapsing to lag 2, +-1 noise shifts at long periods, comb-vs-comb
tolerance defeated by odd-half-multiples that coincide with the signal
at every sampled lag); the matched filter integrates every lag
coherently, is harmonic-safe by construction, and its period precision
supports exact selection beyond ~8 observed cycles (+-1 below that —
see ``detect_season_length``).

This is batch-level detection by design: one period for the whole batch
keeps every compiled shape static (per-series periods would force a
recompile per value; series genuinely mixing cadences belong in separate
batches or span buckets).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_MIN_LAG = 2


def acf_scores_per_series(y, mask, max_lag: int):
    """Per-series masked ACF of diff(y): ``(r (S, max_lag+1), nonempty
    (S,) bool)``.

    Plain traceable array code (no jit of its own): ``acf_scores_impl``
    reduces it to the batch mean for standalone detection, and
    ``engine/autoprep``'s fused prep program inlines it with a
    padding-aware mean so seasonality scoring rides the same single
    dispatch as the cleaning stages (zero-padded filler rows must not
    dilute the batch score under the comb gate).

    The differenced values are winsorized at 6 robust sigmas (MAD) per
    series before correlating: a few percent of promo/glitch spike days
    carry squared magnitudes hundreds of times the signal's, swamp the
    variance normalization, and push every true lag's ACF under the noise
    floor (measured: a 15-amplitude monthly cycle became undetectable at
    3% spike days).  Winsorizing bounds each day's leverage and touches a
    clean Gaussian series only in its extreme tail (6 MAD ~ 4 sigma,
    ~5e-5 of points).  A series whose MEDIAN |diff| is zero — intermittent
    demand, zero most days — gets no clipping at all: its spike days ARE
    the seasonal signal there, and a 1e-9-scaled clip would zero the
    series out of detection entirely.
    """
    dy = y[:, 1:] - y[:, :-1]
    dm = mask[:, 1:] * mask[:, :-1]
    from distributed_forecasting_tpu.ops.solve import masked_mad_scale

    mad = masked_mad_scale(dy, dm)[:, None]
    lim = jnp.where(mad > 0, 6.0 * mad, jnp.inf)
    dy = jnp.clip(dy, -lim, lim)
    n = jnp.maximum(jnp.sum(dm, axis=1, keepdims=True), 1.0)
    mu = jnp.sum(dy * dm, axis=1, keepdims=True) / n
    z = (dy - mu) * dm
    T = z.shape[1]
    # static shape math: T comes from z.shape, max_lag is static_argnames —
    # this int() concretizes trace-time Python ints, never a tracer
    L = int(2 ** np.ceil(np.log2(T + max_lag + 1)))  # linear, not circular  # dflint: disable=host-sync-in-hot-path
    fz = jnp.fft.rfft(z, n=L, axis=1)
    fm = jnp.fft.rfft(dm, n=L, axis=1)
    num = jnp.fft.irfft(fz * jnp.conj(fz), n=L, axis=1)[:, : max_lag + 1]
    cnt = jnp.fft.irfft(fm * jnp.conj(fm), n=L, axis=1)[:, : max_lag + 1]
    acov = num / jnp.maximum(cnt, 1.0)            # (S, max_lag+1)
    a0 = acov[:, :1]
    r = jnp.where(a0 > 1e-12, acov / jnp.maximum(a0, 1e-12), 0.0)
    return r, jnp.sum(mask, axis=1) > 0


def acf_scores_impl(y, mask, max_lag: int):
    """(max_lag+1,) batch-mean masked ACF of diff(y) at lags 0..max_lag —
    every series counted in the mean, matching the original batch-level
    detection semantics (a flat/invalid series contributes its zero row)."""
    r, _ = acf_scores_per_series(y, mask, max_lag)
    return jnp.mean(r, axis=0)


_acf_scores = partial(jax.jit, static_argnames=("max_lag",))(acf_scores_impl)


def detect_season_length(
    batch,
    max_lag: int = 400,
    default: int = 7,
    min_score: float = 0.1,
) -> int:
    """Pick the batch's dominant seasonal period as a static Python int.

    Scans lags 2..max_lag (clamped to T/3); candidate periods need two
    comb teeth inside that window, so detection requires ``T >= ~6m`` and
    periods below 4 are out of range.  Returns ``default`` when the best
    comb score stays under ``min_score`` (a genuinely non-seasonal batch
    should get the domain default, not an argmax over noise).  See the
    module docstring for the selection rationale.
    """
    T = batch.n_time
    max_lag = clamp_max_lag(max_lag, T)
    if max_lag < 4:
        return int(default)
    raw = np.asarray(_acf_scores(batch.y, batch.mask, max_lag))
    return select_period(raw, max_lag, default=default, min_score=min_score)


def clamp_max_lag(max_lag: int, n_time: int) -> int:
    """Shared lag-window clamp: candidates need >= 2 comb teeth in range,
    so the scan never exceeds T/3 periods."""
    return int(min(max_lag, max(n_time // 3, _MIN_LAG)))


def select_period(raw: np.ndarray, max_lag: int, default: int = 7,
                  min_score: float = 0.1) -> int:
    """Host-side period selection over a precomputed (max_lag+1,) ACF
    score vector — the second half of :func:`detect_season_length`, split
    out so ``engine/autoprep`` can feed it the ACF its fused prep program
    already computed (same gate, same matched filter, one dispatch)."""
    if max_lag < 4 or raw.shape[0] < max_lag + 1:
        return int(default)
    raw = np.asarray(raw[: max_lag + 1], dtype=np.float64)

    # Harmonic comb score per candidate period m (pitch-detection style):
    # mean ACF at the first <=3 multiples of m MINUS mean at the anti-phase
    # half-multiples (0.5m, 1.5m, 2.5m — deep troughs for a true period).
    # Teeth are capped at 3 and candidates need >= 2 multiples in range:
    # distant single-tooth candidates otherwise cherry-pick one aligned
    # peak + one deep trough and outscore the diluted many-teeth
    # fundamental (measured: 189 over a true 7).
    #
    # Peak teeth read max(raw, 3-pt smoothed): smoothing restores the
    # +-1-jittered jagged peaks of long noisy periods (measured: raw
    # argmax at 59 for a true 60) while the raw side preserves the sharp
    # single-lag peaks of bursty series that averaging destroys (0.97
    # flanked by -0.48 smooths to ~0).  Window-max variants were tried
    # and measured worse: a fixed +-1 window let comb(4)'s teeth at 8/12
    # steal the weekly 0.83s at 7/13, and lag-proportional windows
    # re-broke the small-m cases.  Troughs read the RAW value: windowing
    # or smoothing a trough blends in the flank beside a harmonic's sharp
    # peak (measured: comb(14) beat comb(7) on weekly bursts via
    # min(raw[6..8]) = -0.48), defeating the harmonic suppression the
    # troughs exist for.
    smooth = raw.copy()
    smooth[1:-1] = (raw[:-2] + raw[1:-1] + raw[2:]) / 3.0
    peak_s = np.maximum(raw, smooth)

    def comb(m: int) -> float:
        ks = np.arange(1, min(3, max_lag // m) + 1)
        trough = np.clip(np.round((ks - 0.5) * m).astype(int), 1, max_lag)
        return float(np.mean(peak_s[ks * m]) - np.mean(raw[trough]))

    cand = np.arange(4, max_lag // 2 + 1)
    if cand.size == 0:
        return int(default)
    combs = np.asarray([comb(m) for m in cand])
    if float(np.max(combs)) < min_score:
        return int(default)

    # The comb only GATES (is there seasonality at all?); the period
    # itself is the argmax of a matched cosine filter over the whole lag
    # axis, sum(raw[d] cos(2 pi d / m)).  The matched filter is the
    # estimator every cheaper rule kept approximating badly (each variant
    # below was implemented and measured off):
    #  * it is harmonic-safe by construction — a 2m candidate's crests
    #    skip half the true peaks and its troughs LAND on them; an
    #    odd-half-multiple like 150 for a true 30 coincides with the
    #    signal at every lag it samples, which defeated comb-vs-comb
    #    submultiple tolerance rules, but the filter also integrates the
    #    lags such a candidate IGNORES (30, 60, 90... all high) and those
    #    decide it;
    #  * per-lag peak rules (argmax, divisors, windowed extrema,
    #    harmonic-position medians) all went off-by-one for long periods,
    #    where per-lag curvature (~0.001) drowns under sample noise
    #    (~0.03) — the ~T/3-lag coherent sum is the only statistic here
    #    whose period precision (CRB well under one lag beyond ~8
    #    observed cycles) supports exact selection;
    #  * sharp burst combs maximize it at m too (crests on every
    #    multiple), so intermittent series need no special case.
    d_ax = np.arange(_MIN_LAG, max_lag + 1)

    def matched(m: int) -> float:
        return float(np.sum(raw[_MIN_LAG:] * np.cos(2.0 * np.pi * d_ax / m)))

    return int(max((int(m) for m in cand), key=matched))
