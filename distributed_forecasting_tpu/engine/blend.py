"""Per-series weighted cross-family blending (linear opinion pool).

``engine/select`` answers "which ONE family serves each series"; this
module answers the M-competition finding that a weighted COMBINATION of
families beats every single member on mixed catalogs (simple combination
is the classic forecasting result — Clemen 1989's review; the M4 winners
are weighted ensembles).  Weights are per series and data-driven: each
family's rolling-origin CV error (the same one compiled CV pass per
family that selection uses) maps to an inverse-error weight, so a series
whose demand is intermittent leans croston while its seasonal neighbor
leans HW — smoothly, instead of the winner-take-all cut.

Combination rules, deliberately simple and closed-form:

* point path: ``yhat = sum_f w_f yhat_f`` — the linear pool;
* bands: half-widths combine LINEARLY, ``hi - yhat = sum_f w_f (hi_f -
  yhat_f)`` — the perfectly-correlated assumption.  Family errors on the
  same series are strongly positively correlated (they all miss the same
  demand shocks), so the independence rule (root-sum-square) would
  under-state uncertainty; the linear rule is the honest conservative
  choice and keeps every band closed-form.
* a family with a non-finite CV metric on a series gets weight 0 there
  (``train_with_fail_safe`` semantics, at any temperature); a series where
  EVERY family is non-finite falls back to equal weights and is surfaced
  through ``ok=False``; and because the blend SUMS every member in, a
  series is ``ok`` only if every family CARRYING WEIGHT on it fit
  healthily — a 0.6-weight member that fell back to seasonal-naive makes
  the series not-ok, unlike the winner-gather auto path.

Everything is one (S, F) weight matrix applied to F batched forecasts —
no per-series Python, same compiled programs the auto path runs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import SeriesBatch
from distributed_forecasting_tpu.engine.cv import CVConfig
from distributed_forecasting_tpu.engine.fit import ForecastResult, fit_forecast
from distributed_forecasting_tpu.engine.select import (
    DEFAULT_FAMILIES,
    _HIGHER_BETTER,
    select_model,
)

_EPS = 1e-9


@dataclasses.dataclass
class BlendResult:
    models: Tuple[str, ...]   # family names, the weight matrix's column space
    weights: np.ndarray       # (S, F) convex weights per series
    scores: pd.DataFrame      # (S, F) per-family CV metric
    metric: str
    valid: np.ndarray         # (S,) bool — at least one family scored finite
    # (S,) split-conformal band scale for the POOLED forecast, filled by
    # fit_forecast_blend(calibrate=True); None = uncalibrated
    interval_scale: Optional[np.ndarray] = None

    def mean_weights(self) -> Dict[str, float]:
        return {
            name: float(self.weights[:, i].mean())
            for i, name in enumerate(self.models)
        }


def blend_weights(
    batch: SeriesBatch,
    models: Sequence[str] = DEFAULT_FAMILIES,
    configs: Optional[Dict[str, object]] = None,
    metric: str = "smape",
    cv: CVConfig = CVConfig(),
    key: Optional[jax.Array] = None,
    temperature: float = 1.0,
) -> BlendResult:
    """Per-series inverse-CV-error weights: ``w_f ∝ (1/err_f)^temperature``.

    ``temperature`` sharpens (>1) or flattens (<1) the pool; 1.0 is the
    classical inverse-error rule, and temperature -> inf recovers
    winner-take-all selection.
    """
    # one CV-scoring contract for selection AND blending: select_model owns
    # the per-family CV loop (key folding, metric extraction), so the
    # weights here always correspond to the scores the auto path would
    # have selected on
    sel = select_model(
        batch, models=models, configs=configs, metric=metric, cv=cv, key=key
    )
    table = sel.scores[list(models)].to_numpy(dtype=np.float64)  # (S, F)
    finite = np.isfinite(table)
    if metric in _HIGHER_BETTER:
        # a score like coverage is already "bigger is better" and
        # non-negative: weight proportional to the score itself (the
        # inverse-error rule applies to errors, not negated scores)
        base = np.maximum(table, 0.0)
    else:
        base = 1.0 / np.maximum(table, _EPS)
    # normalize by the per-row max before the temperature power: base can
    # reach ~1/_EPS, and e.g. 1e9**34 overflows float64 to inf (inf/inf ->
    # NaN weights).  Weights are scale-invariant under the row
    # normalization below, so dividing by the max first changes nothing
    # except keeping every temperature finite
    rowmax = np.where(finite, base, 0.0).max(axis=1, keepdims=True)
    base = base / np.maximum(rowmax, _EPS)
    # finite mask applied AFTER the temperature power: 0**0 == 1 would
    # hand a non-finite family equal weight at temperature=0
    inv = np.where(finite, base ** temperature, 0.0)
    tot = inv.sum(axis=1, keepdims=True)
    equal = np.full_like(inv, 1.0 / len(models))
    weights = np.where(tot > 0, inv / np.maximum(tot, _EPS), equal)
    return BlendResult(
        models=tuple(models),
        weights=weights,
        scores=sel.scores,
        metric=metric,
        valid=sel.valid,
    )


def _blend_conformal_scale(batch, blend: BlendResult, configs, cv, key):
    """Split-conformal scale for the POOLED band: blend each family's CV
    paths with the per-series weights (the same linear rules the final
    forecast uses), then score the pooled residuals against the pooled
    half-band — so the calibration set is exactly the forecast being
    shipped, not any single member's.

    Materializes F sets of (C, S, T) paths (one cross-family CV pass);
    diagnostics-scale by design, like ``cv_artifact`` — the 50k regime
    should calibrate per family or not at all.
    """
    from distributed_forecasting_tpu.engine.calibrate import (
        config_interval_width,
        conformal_scale_from_paths,
    )
    from distributed_forecasting_tpu.engine.cv import (
        _cv_entry,
        _cv_paths_impl,
        cutoff_indices,
    )

    # resolve configs (cheap) and fail fast on mixed widths BEFORE any
    # expensive CV path materializes: a pooled band calibrated "at 95%"
    # while one member prices 80% would be a silent, ill-defined target
    resolved = {}
    widths = {}
    for i, name in enumerate(blend.models):
        config, k, _ = _cv_entry(batch, name, configs.get(name),
                                 jax.random.fold_in(key, i), None,
                                 "fit_forecast_blend(calibrate=True)")
        resolved[name] = (config, k)
        widths[name] = config_interval_width(config)
    if len(set(widths.values())) > 1:
        raise ValueError(
            f"calibrate=True needs ONE interval_width across the pool, got "
            f"{widths}; align the member configs"
        )

    w = blend.weights
    yhat_b = up_b = None
    eval_masks = None
    for i, name in enumerate(blend.models):
        config, k = resolved[name]
        cuts = cutoff_indices(batch.n_time, cv)
        yhat, lo, hi, em, _ = _cv_paths_impl(
            batch.y, batch.mask, batch.day, k,
            model=name, config=config, cuts=tuple(cuts), horizon=cv.horizon,
        )
        wf = jnp.asarray(w[:, i])[None, :, None]  # broadcast over (C, S, T)
        if yhat_b is None:
            yhat_b = wf * yhat
            up_b = wf * (hi - yhat)
            eval_masks = em
        else:
            yhat_b = yhat_b + wf * yhat
            up_b = up_b + wf * (hi - yhat)
    return np.asarray(conformal_scale_from_paths(
        batch.y, yhat_b, yhat_b + up_b, eval_masks,
        interval_width=next(iter(widths.values())),
    ))


def blend_band_floor(models) -> object:
    """The pooled band's hard floor: the loosest bound EVERY member
    guarantees (min over declared floors), or None when any member is
    unbounded below — shared by the engine result and serving so the two
    cannot drift."""
    from distributed_forecasting_tpu.models.base import get_model

    floors = [get_model(name).band_floor for name in models]
    if any(f is None for f in floors):
        return None
    return min(floors)


def fit_forecast_blend(
    batch: SeriesBatch,
    models: Sequence[str] = DEFAULT_FAMILIES,
    configs: Optional[Dict[str, object]] = None,
    metric: str = "smape",
    cv: CVConfig = CVConfig(),
    horizon: int = 90,
    key: Optional[jax.Array] = None,
    blend: Optional[BlendResult] = None,
    temperature: float = 1.0,
    calibrate: bool = False,
) -> Tuple[Dict[str, object], BlendResult, ForecastResult]:
    """Weight per series, fit every family on full history, combine.

    Returns ``(params_by_family, blend, result)``; the params dict plus
    ``blend.weights`` feed ``serving.BlendedForecaster``.  With
    ``calibrate=True`` the pooled band is split-conformal calibrated from
    the pooled CV residuals (``blend.interval_scale``; applied to the
    returned result's bands).
    """
    configs = configs or {}
    if key is None:
        key = jax.random.PRNGKey(0)
    if blend is None:
        blend = blend_weights(
            batch, models=models, configs=configs, metric=metric, cv=cv,
            key=key, temperature=temperature,
        )
    if calibrate and blend.interval_scale is None:
        blend = dataclasses.replace(
            blend,
            interval_scale=_blend_conformal_scale(batch, blend, configs, cv,
                                                  jax.random.fold_in(key, 77)),
        )

    params_by_family: Dict[str, object] = {}
    w = jnp.asarray(blend.weights)
    yhat = up = dn = None
    ok = day_all = None
    for i, name in enumerate(blend.models):
        params, res = fit_forecast(
            batch, model=name, config=configs.get(name), horizon=horizon,
            key=jax.random.fold_in(key, 1000 + i),
        )
        params_by_family[name] = params
        wf = w[:, i][:, None]
        # a family only vouches for series it actually carries: the blend
        # SUMS every family in (unlike the auto path's winner gather), so
        # ok must AND over weight-carrying families — a 0.6-weight member
        # whose fit fell back to seasonal-naive ships 60% fallback and the
        # series must surface as not-ok, even if another member fit fine
        carries_ok = res.ok | (w[:, i] <= 1e-6)
        if yhat is None:
            yhat = wf * res.yhat
            up = wf * (res.hi - res.yhat)
            dn = wf * (res.yhat - res.lo)
            ok, day_all = carries_ok, res.day_all
        else:
            yhat = yhat + wf * res.yhat
            up = up + wf * (res.hi - res.yhat)
            dn = dn + wf * (res.yhat - res.lo)
            ok = ok & carries_ok
    ok = ok & jnp.asarray(blend.valid)
    lo_b, hi_b = yhat - dn, yhat + up
    if blend.interval_scale is not None:
        from distributed_forecasting_tpu.engine.calibrate import (
            apply_interval_scale,
        )

        _, lo_b, hi_b = apply_interval_scale(
            yhat, lo_b, hi_b, jnp.asarray(blend.interval_scale),
            floor=blend_band_floor(blend.models),
        )
    result = ForecastResult(
        yhat=yhat, lo=lo_b, hi=hi_b, ok=ok, day_all=day_all
    )
    return params_by_family, blend, result
