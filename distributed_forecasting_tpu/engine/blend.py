"""Per-series weighted cross-family blending (linear opinion pool).

``engine/select`` answers "which ONE family serves each series"; this
module answers the M-competition finding that a weighted COMBINATION of
families beats every single member on mixed catalogs (simple combination
is the classic forecasting result — Clemen 1989's review; the M4 winners
are weighted ensembles).  Weights are per series and data-driven: each
family's rolling-origin CV error (the same one compiled CV pass per
family that selection uses) maps to an inverse-error weight, so a series
whose demand is intermittent leans croston while its seasonal neighbor
leans HW — smoothly, instead of the winner-take-all cut.

Combination rules, deliberately simple and closed-form:

* point path: ``yhat = sum_f w_f yhat_f`` — the linear pool;
* bands: half-widths combine LINEARLY, ``hi - yhat = sum_f w_f (hi_f -
  yhat_f)`` — the perfectly-correlated assumption.  Family errors on the
  same series are strongly positively correlated (they all miss the same
  demand shocks), so the independence rule (root-sum-square) would
  under-state uncertainty; the linear rule is the honest conservative
  choice and keeps every band closed-form.
* a family with a non-finite CV metric on a series gets weight 0 there
  (``train_with_fail_safe`` semantics, at any temperature); a series where
  EVERY family is non-finite falls back to equal weights and is surfaced
  through ``ok=False``; and because the blend SUMS every member in, a
  series is ``ok`` only if every family CARRYING WEIGHT on it fit
  healthily — a 0.6-weight member that fell back to seasonal-naive makes
  the series not-ok, unlike the winner-gather auto path.

Everything is one (S, F) weight matrix applied to F batched forecasts —
no per-series Python, same compiled programs the auto path runs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import SeriesBatch
from distributed_forecasting_tpu.engine.cv import CVConfig
from distributed_forecasting_tpu.engine.fit import ForecastResult, fit_forecast
from distributed_forecasting_tpu.engine.select import (
    DEFAULT_FAMILIES,
    _HIGHER_BETTER,
    select_model,
)

_EPS = 1e-9


@dataclasses.dataclass
class BlendResult:
    models: Tuple[str, ...]   # family names, the weight matrix's column space
    weights: np.ndarray       # (S, F) convex weights per series
    scores: pd.DataFrame      # (S, F) per-family CV metric
    metric: str
    valid: np.ndarray         # (S,) bool — at least one family scored finite

    def mean_weights(self) -> Dict[str, float]:
        return {
            name: float(self.weights[:, i].mean())
            for i, name in enumerate(self.models)
        }


def blend_weights(
    batch: SeriesBatch,
    models: Sequence[str] = DEFAULT_FAMILIES,
    configs: Optional[Dict[str, object]] = None,
    metric: str = "smape",
    cv: CVConfig = CVConfig(),
    key: Optional[jax.Array] = None,
    temperature: float = 1.0,
) -> BlendResult:
    """Per-series inverse-CV-error weights: ``w_f ∝ (1/err_f)^temperature``.

    ``temperature`` sharpens (>1) or flattens (<1) the pool; 1.0 is the
    classical inverse-error rule, and temperature -> inf recovers
    winner-take-all selection.
    """
    # one CV-scoring contract for selection AND blending: select_model owns
    # the per-family CV loop (key folding, metric extraction), so the
    # weights here always correspond to the scores the auto path would
    # have selected on
    sel = select_model(
        batch, models=models, configs=configs, metric=metric, cv=cv, key=key
    )
    table = sel.scores[list(models)].to_numpy(dtype=np.float64)  # (S, F)
    finite = np.isfinite(table)
    if metric in _HIGHER_BETTER:
        # a score like coverage is already "bigger is better" and
        # non-negative: weight proportional to the score itself (the
        # inverse-error rule applies to errors, not negated scores)
        base = np.maximum(table, 0.0)
    else:
        base = 1.0 / np.maximum(table, _EPS)
    # finite mask applied AFTER the temperature power: 0**0 == 1 would
    # hand a non-finite family equal weight at temperature=0
    inv = np.where(finite, base ** temperature, 0.0)
    tot = inv.sum(axis=1, keepdims=True)
    equal = np.full_like(inv, 1.0 / len(models))
    weights = np.where(tot > 0, inv / np.maximum(tot, _EPS), equal)
    return BlendResult(
        models=tuple(models),
        weights=weights,
        scores=sel.scores,
        metric=metric,
        valid=sel.valid,
    )


def fit_forecast_blend(
    batch: SeriesBatch,
    models: Sequence[str] = DEFAULT_FAMILIES,
    configs: Optional[Dict[str, object]] = None,
    metric: str = "smape",
    cv: CVConfig = CVConfig(),
    horizon: int = 90,
    key: Optional[jax.Array] = None,
    blend: Optional[BlendResult] = None,
    temperature: float = 1.0,
) -> Tuple[Dict[str, object], BlendResult, ForecastResult]:
    """Weight per series, fit every family on full history, combine.

    Returns ``(params_by_family, blend, result)``; the params dict plus
    ``blend.weights`` feed ``serving.BlendedForecaster``.
    """
    configs = configs or {}
    if key is None:
        key = jax.random.PRNGKey(0)
    if blend is None:
        blend = blend_weights(
            batch, models=models, configs=configs, metric=metric, cv=cv,
            key=key, temperature=temperature,
        )

    params_by_family: Dict[str, object] = {}
    w = jnp.asarray(blend.weights)
    yhat = up = dn = None
    ok = day_all = None
    for i, name in enumerate(blend.models):
        params, res = fit_forecast(
            batch, model=name, config=configs.get(name), horizon=horizon,
            key=jax.random.fold_in(key, 1000 + i),
        )
        params_by_family[name] = params
        wf = w[:, i][:, None]
        # a family only vouches for series it actually carries: the blend
        # SUMS every family in (unlike the auto path's winner gather), so
        # ok must AND over weight-carrying families — a 0.6-weight member
        # whose fit fell back to seasonal-naive ships 60% fallback and the
        # series must surface as not-ok, even if another member fit fine
        carries_ok = res.ok | (w[:, i] <= 1e-6)
        if yhat is None:
            yhat = wf * res.yhat
            up = wf * (res.hi - res.yhat)
            dn = wf * (res.yhat - res.lo)
            ok, day_all = carries_ok, res.day_all
        else:
            yhat = yhat + wf * res.yhat
            up = up + wf * (res.hi - res.yhat)
            dn = dn + wf * (res.yhat - res.lo)
            ok = ok & carries_ok
    ok = ok & jnp.asarray(blend.valid)
    result = ForecastResult(
        yhat=yhat, lo=yhat - dn, hi=yhat + up, ok=ok, day_all=day_all
    )
    return params_by_family, blend, result
