"""Fused automatic data-prep: one batched pre-fit program per (S, T) batch.

ARIMA_PLUS's pitch (arXiv 2510.24452) is that the cleaning most teams
hand-roll — dead-zero stretches, holiday effects, level shifts, spike
outliers, seasonality choice — happens *inside* the training pipeline as
declared, inspectable stages.  This module is that subsystem: the
``engine.autoprep`` conf block arms it, ``autoprep_batch`` runs every
armed stage over the dense batch in ONE jitted dispatch (the kernels in
``ops/clean.py``), and the result is

* a cleaned :class:`~distributed_forecasting_tpu.data.tensorize.SeriesBatch`
  for the fit (the STORED history is never mutated — repairs and
  re-levelings exist only in the fit tensor),
* a per-series :class:`PrepReport` with every repair recorded per point
  (``repairs_frame``) for run artifacts,
* an optional batch season length (the fused program's ACF through
  ``engine/season.select_period``) and holiday regressor matrix.

Dispatch discipline matches the fit entrypoints: the program routes
through ``engine/compile_cache.aot_call`` under the entry
``autoprep:<S-bucket>x<T>`` with the series axis padded to a pow2 bucket
(T stays exact — interpolation distances and ACF lags are time-grid
semantics and must not see filler periods), so warm processes load the
serialized executable with its cost fingerprint instead of recompiling.

When every stage gate is off the call short-circuits before any device
work and returns the input batch object itself — byte-identity with
no-prep is structural, not numerical.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_forecasting_tpu.data.tensorize import SeriesBatch
from distributed_forecasting_tpu.engine.compile_cache import aot_call
from distributed_forecasting_tpu.engine.season import (
    acf_scores_per_series,
    clamp_max_lag,
    select_period,
)
from distributed_forecasting_tpu.ops import clean


@dataclasses.dataclass(frozen=True)
class AutoprepConfig:
    """The strict ``engine.autoprep`` conf block (flat keys, one per knob,
    so the config-drift lint maps YAML to consumption exactly).

    ``enabled`` arms the subsystem; each stage has its own gate so
    operators can, say, repair outliers without trusting changepoint
    re-leveling.  All thresholds are robust-z units (MAD sigmas).
    """

    enabled: bool = False
    # gap/zero-run masking (data/quality.py's dead-feed semantics)
    zero_run_mask: bool = True
    zero_run_min: int = 14
    # MAD spike scoring + interpolation repair
    outlier_repair: bool = True
    outlier_threshold: float = 6.0
    outlier_window: int = 7
    # CUSUM level-shift detection (+ optional fit-tensor re-leveling)
    changepoints: bool = True
    changepoint_threshold: float = 8.0
    align_level_shifts: bool = False
    # holiday-effect regressors (data/holidays.py specs)
    holiday_regressors: bool = False
    holiday_calendar: str = "US"
    holiday_lower_window: int = 0
    holiday_upper_window: int = 0
    # spectral seasonality selection (engine/season.py)
    season_detect: bool = False
    season_max_lag: int = 400
    season_min_score: float = 0.1
    season_default: int = 7

    def __post_init__(self):
        if self.zero_run_min < 2:
            raise ValueError(
                f"zero_run_min must be >= 2 (a single observed zero is "
                f"ordinary intermittent demand), got {self.zero_run_min}")
        if self.outlier_window < 1:
            raise ValueError(
                f"outlier_window must be >= 1, got {self.outlier_window}")
        if self.outlier_threshold <= 0 or self.changepoint_threshold <= 0:
            raise ValueError("outlier/changepoint thresholds must be > 0")
        if self.holiday_lower_window < 0 or self.holiday_upper_window < 0:
            raise ValueError("holiday windows must be >= 0")
        if self.season_max_lag < 4:
            raise ValueError(
                f"season_max_lag must be >= 4, got {self.season_max_lag}")

    @property
    def any_stage(self) -> bool:
        """True when at least one stage would do work — the all-gates-off
        short-circuit key."""
        return bool(self.zero_run_mask or self.outlier_repair
                    or self.changepoints or self.holiday_regressors
                    or self.season_detect)

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "AutoprepConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            # a typo like outlier_treshold must not silently keep a default
            raise ValueError(
                f"unknown engine.autoprep conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


_active_config = AutoprepConfig()


def configure_autoprep(conf) -> AutoprepConfig:
    """Install the process-wide autoprep config (tasks/common parses the
    ``engine.autoprep`` conf block into this).  Accepts a dict or an
    :class:`AutoprepConfig`; returns the installed config."""
    global _active_config
    cfg = conf if isinstance(conf, AutoprepConfig) \
        else AutoprepConfig.from_conf(conf)
    _active_config = cfg
    return cfg


def autoprep_config() -> AutoprepConfig:
    return _active_config


@dataclasses.dataclass
class PrepReport:
    """What autoprep did to one batch — per series, and per point for
    repairs.  Arrays are host numpy; nothing here feeds a compiled
    program, it is the inspectability artifact."""

    config: AutoprepConfig
    n_series: int
    n_time: int
    masked_zero_cells: np.ndarray     # (S,) cells dropped by zero-run mask
    outlier_score: np.ndarray         # (S, T) robust spike z per point
    outlier_scale: np.ndarray         # (S,) MAD residual scale
    repaired: np.ndarray              # (S, T) bool — repaired in fit tensor
    repair_value: np.ndarray          # (S, T) value used where repaired
    cp_index: np.ndarray              # (S,) int split cell, -1 = none
    cp_shift: np.ndarray              # (S,) level shift (after - before)
    cp_score: np.ndarray              # (S,) CUSUM z-score
    season_length: Optional[int] = None
    holiday_names: Tuple[str, ...] = ()

    def summary(self) -> Dict:
        """Aggregates for ``run.log_metrics`` / smoke gates."""
        return {
            "prep_masked_zero_cells": int(self.masked_zero_cells.sum()),
            "prep_repaired_points": int(self.repaired.sum()),
            "prep_series_repaired": int(self.repaired.any(axis=1).sum()),
            "prep_series_with_changepoint": int((self.cp_index >= 0).sum()),
            "prep_season_length": int(self.season_length or 0),
            "prep_holiday_regressors": len(self.holiday_names),
        }

    def to_frame(self, batch: SeriesBatch):
        """Per-series report rows for the ``prep_report.parquet`` run
        artifact: keys + what each stage found."""
        frame = batch.key_frame()
        frame["masked_zero_cells"] = self.masked_zero_cells.astype(np.int64)
        frame["repaired_points"] = self.repaired.sum(axis=1).astype(np.int64)
        frame["max_outlier_score"] = self.outlier_score.max(axis=1)
        frame["outlier_scale"] = self.outlier_scale
        frame["cp_index"] = self.cp_index.astype(np.int64)
        frame["cp_shift"] = self.cp_shift
        frame["cp_score"] = self.cp_score
        return frame

    def repairs_frame(self, batch: SeriesBatch):
        """Long frame of every repaired point: keys, ds, the original
        value, the repair, and its spike score — the per-point record the
        "never silently applied" contract requires."""
        import pandas as pd

        sidx, tidx = np.nonzero(self.repaired)
        keys = np.asarray(batch.keys)[sidx]
        dates = batch.dates()[tidx]
        y_raw = np.asarray(batch.y)[sidx, tidx]
        frame = pd.DataFrame(keys, columns=list(batch.key_names))
        frame["ds"] = dates
        frame["y_raw"] = y_raw
        frame["y_repaired"] = self.repair_value[sidx, tidx]
        frame["outlier_score"] = self.outlier_score[sidx, tidx]
        return frame


@dataclasses.dataclass(frozen=True)
class PrepResult:
    batch: SeriesBatch                # cleaned fit tensor (or the input)
    report: Optional[PrepReport]
    season_length: Optional[int]      # None unless season_detect found one
    xreg: Optional[jax.Array]         # (T+horizon, R) holiday indicators


def _bucket(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _autoprep_impl(y, mask, day_all, hol_days, *, zero_run_mask,
                   zero_run_min, outlier_repair, outlier_threshold,
                   outlier_window, changepoints, changepoint_threshold,
                   align_level_shifts, season_detect, acf_max_lag):
    """The fused prep program: every armed stage over the padded (Sb, T)
    batch, one dispatch.  Static gate args shape the traced graph, so each
    gate combination is its own program under the same AOT entry."""
    S, T = y.shape
    mask_clean = mask
    dropped = jnp.zeros((S, T), bool)
    if zero_run_mask:
        mask_clean, dropped = clean.mask_zero_runs(y, mask, zero_run_min)

    score = jnp.zeros((S, T), y.dtype)
    scale = jnp.zeros((S,), y.dtype)
    repaired = jnp.zeros((S, T), bool)
    y_clean = y
    if outlier_repair:
        score, scale = clean.mad_outlier_scores(y, mask_clean,
                                                outlier_window)
        flag = score > outlier_threshold
        y_clean, repaired = clean.interpolate_repair(y, mask_clean, flag)

    cp_index = jnp.full((S,), -1, jnp.int32)
    cp_shift = jnp.zeros((S,), y.dtype)
    cp_score = jnp.zeros((S,), y.dtype)
    if changepoints:
        # detect on the REPAIRED tensor: a 30-sigma promo spike otherwise
        # dominates the cumsum statistic and masquerades as a level shift
        cp_index, cp_shift, cp_score = clean.cusum_level_shift(
            y_clean, mask_clean, changepoint_threshold)
        if align_level_shifts:
            y_clean = clean.align_level_shift(
                y_clean, mask_clean, cp_index, cp_shift)

    if season_detect:
        # padding-aware batch mean: zero-filled bucket rows must not
        # dilute the comb gate the host selection applies
        r, nonempty = acf_scores_per_series(y_clean, mask_clean,
                                            acf_max_lag)
        w = nonempty.astype(y.dtype)
        acf = jnp.sum(r * w[:, None], axis=0) / jnp.maximum(
            jnp.sum(w), 1.0)
    else:
        acf = jnp.zeros((1,), y.dtype)

    hol = clean.holiday_indicators(day_all, hol_days)
    return (y_clean, mask_clean, dropped, score, scale, repaired,
            cp_index, cp_shift, cp_score, acf, hol)


_autoprep_jit = jax.jit(
    _autoprep_impl,
    static_argnames=("zero_run_mask", "zero_run_min", "outlier_repair",
                     "outlier_threshold", "outlier_window", "changepoints",
                     "changepoint_threshold", "align_level_shifts",
                     "season_detect", "acf_max_lag"))


def _holiday_days_array(batch: SeriesBatch, horizon: int,
                        config: AutoprepConfig,
                        spec=None) -> Tuple[np.ndarray, Tuple[str, ...]]:
    """Resolve the holiday spec over the batch grid + horizon into the
    padded (R, Dmax) int32 day array the fused program broadcasts
    against.  ``spec`` overrides (the training pipeline passes its
    tenant-resolved calendar); otherwise the config's named calendar is
    resolved over the grid's date range."""
    if spec is None:
        from distributed_forecasting_tpu.data.holidays import (
            holiday_spec_for_range,
        )

        dates = batch.dates()
        end = dates[-1] + (dates[-1] - dates[0]) / max(len(dates) - 1, 1) \
            * horizon
        spec = holiday_spec_for_range(
            dates[0], end, calendar=config.holiday_calendar,
            lower_window=config.holiday_lower_window,
            upper_window=config.holiday_upper_window)
    names = tuple(name for name, _ in spec)
    if not names:
        return np.zeros((0, 1), np.int32), ()
    dmax = max(len(days) for _, days in spec)
    out = np.full((len(names), dmax), -1, np.int32)
    for i, (_, days) in enumerate(spec):
        out[i, : len(days)] = np.asarray(days, np.int32)
    return out, names


def autoprep_batch(
    batch: SeriesBatch,
    config: Optional[AutoprepConfig] = None,
    horizon: int = 0,
    holiday_spec=None,
) -> PrepResult:
    """Run the armed prep stages over ``batch`` in one fused dispatch.

    Returns a :class:`PrepResult`; when the config is disabled or every
    stage gate is off, ``result.batch is batch`` (the short-circuit that
    makes no-op prep byte-identical by construction).  ``horizon``
    extends the holiday regressor grid past history so the same matrix
    serves fit AND forecast (the xreg contract of ``fit_forecast``).
    """
    cfg = config if config is not None else autoprep_config()
    if not cfg.enabled or not cfg.any_stage:
        return PrepResult(batch=batch, report=None, season_length=None,
                          xreg=None)
    S, T = batch.n_series, batch.n_time
    Sb = _bucket(S)
    y = batch.y
    mask = batch.mask
    if Sb != S:
        pad = Sb - S
        y = jnp.concatenate([y, jnp.zeros((pad, T), y.dtype)])
        mask = jnp.concatenate([mask, jnp.zeros((pad, T), mask.dtype)])

    if cfg.holiday_regressors:
        hol_days, hol_names = _holiday_days_array(batch, horizon, cfg,
                                                  holiday_spec)
    else:
        hol_days, hol_names = np.zeros((0, 1), np.int32), ()
    day0 = int(batch.day[0])
    day_all = jnp.asarray(np.arange(day0, day0 + T + horizon,
                                    dtype=np.int32))
    acf_max_lag = clamp_max_lag(cfg.season_max_lag, T) \
        if cfg.season_detect else 1

    statics = dict(
        zero_run_mask=cfg.zero_run_mask, zero_run_min=cfg.zero_run_min,
        outlier_repair=cfg.outlier_repair,
        outlier_threshold=cfg.outlier_threshold,
        outlier_window=cfg.outlier_window, changepoints=cfg.changepoints,
        changepoint_threshold=cfg.changepoint_threshold,
        align_level_shifts=cfg.align_level_shifts,
        season_detect=cfg.season_detect, acf_max_lag=acf_max_lag)
    # ONE dispatch per (S-bucket, T) batch, AOT-cached with cost capture
    # exactly like the fit entrypoints (engine/compile_cache)
    (y_clean, mask_clean, dropped, score, scale, repaired, cp_index,
     cp_shift, cp_score, acf, hol) = aot_call(
        f"autoprep:{Sb}x{T}", _autoprep_jit,
        args=(y, mask, day_all, jnp.asarray(hol_days)),
        static_kwargs=statics,
    )

    season_length = None
    if cfg.season_detect and acf_max_lag >= 4:
        season_length = select_period(
            np.asarray(acf), acf_max_lag, default=cfg.season_default,
            min_score=cfg.season_min_score)

    xreg = None
    if cfg.holiday_regressors and len(hol_names):
        xreg = hol

    rep_mask = np.asarray(repaired[:S])
    report = PrepReport(
        config=cfg, n_series=S, n_time=T,
        masked_zero_cells=np.asarray(
            jnp.sum(dropped[:S], axis=1), np.int64),
        outlier_score=np.asarray(score[:S]),
        outlier_scale=np.asarray(scale[:S]),
        repaired=rep_mask,
        repair_value=np.where(rep_mask, np.asarray(y_clean[:S]), 0.0),
        cp_index=np.asarray(cp_index[:S]),
        cp_shift=np.asarray(cp_shift[:S]),
        cp_score=np.asarray(cp_score[:S]),
        season_length=season_length,
        holiday_names=hol_names,
    )
    clean_batch = dataclasses.replace(
        batch, y=y_clean[:S], mask=mask_clean[:S])
    return PrepResult(batch=clean_batch, report=report,
                      season_length=season_length, xreg=xreg)
