from distributed_forecasting_tpu.engine.fit import (
    ForecastResult,
    fit_forecast,
    fit_forecast_bucketed,
    fit_forecast_chunked,
    forecast_frame,
    seasonal_naive,
)
from distributed_forecasting_tpu.engine.cv import CVConfig, cross_validate, cv_forecast_frame
from distributed_forecasting_tpu.engine.calibrate import (
    apply_interval_scale,
    conformal_interval_scale,
)
from distributed_forecasting_tpu.engine.season import detect_season_length
from distributed_forecasting_tpu.engine.autoprep import (
    AutoprepConfig,
    PrepReport,
    PrepResult,
    autoprep_batch,
    autoprep_config,
    configure_autoprep,
)
from distributed_forecasting_tpu.engine.order import select_arima_order
from distributed_forecasting_tpu.engine.blend import (
    BlendResult,
    blend_weights,
    fit_forecast_blend,
)
from distributed_forecasting_tpu.engine.hyper import (
    HyperSearchConfig,
    TuneResult,
    tune_curve_model,
)
from distributed_forecasting_tpu.engine.select import (
    SelectionResult,
    fit_forecast_auto,
    select_model,
)
from distributed_forecasting_tpu.engine.compile_cache import (
    AOTStore,
    CompileCacheConfig,
    aot_call,
    cache_stats,
    configure_compile_cache,
)
from distributed_forecasting_tpu.engine.windowed import (
    WindowedConfig,
    WindowedSeriesStateStore,
    configure_windowed,
    plan_windows,
    should_window,
    windowed_config,
    windowed_fit_forecast,
)
from distributed_forecasting_tpu.engine.executor import (
    ExperimentHandle,
    PipelineConfig,
    TrainingExecutor,
    configure_pipeline,
    device_pull,
    pipeline_config,
    prefetch_to_device,
    sanctioned_pull,
)

__all__ = [
    "ExperimentHandle",
    "PipelineConfig",
    "TrainingExecutor",
    "configure_pipeline",
    "device_pull",
    "pipeline_config",
    "prefetch_to_device",
    "sanctioned_pull",
    "AOTStore",
    "CompileCacheConfig",
    "aot_call",
    "cache_stats",
    "configure_compile_cache",
    "SelectionResult",
    "fit_forecast_auto",
    "select_model",
    "HyperSearchConfig",
    "TuneResult",
    "tune_curve_model",
    "ForecastResult",
    "fit_forecast",
    "fit_forecast_bucketed",
    "fit_forecast_chunked",
    "forecast_frame",
    "seasonal_naive",
    "CVConfig",
    "cross_validate",
    "cv_forecast_frame",
    "apply_interval_scale",
    "conformal_interval_scale",
    "detect_season_length",
    "AutoprepConfig",
    "PrepReport",
    "PrepResult",
    "autoprep_batch",
    "autoprep_config",
    "configure_autoprep",
    "select_arima_order",
    "BlendResult",
    "blend_weights",
    "fit_forecast_blend",
    "WindowedConfig",
    "WindowedSeriesStateStore",
    "configure_windowed",
    "plan_windows",
    "should_window",
    "windowed_config",
    "windowed_fit_forecast",
]
