from distributed_forecasting_tpu.engine.fit import (
    ForecastResult,
    fit_forecast,
    forecast_frame,
    seasonal_naive,
)
from distributed_forecasting_tpu.engine.cv import CVConfig, cross_validate

__all__ = [
    "ForecastResult",
    "fit_forecast",
    "forecast_frame",
    "seasonal_naive",
    "CVConfig",
    "cross_validate",
]
