"""Vectorized hyperparameter search — the AutoML-path equivalent.

The reference's AutoML notebook tunes each series separately with hyperopt
TPE over ``changepoint_prior_scale``, ``seasonality_prior_scale``,
``holidays_prior_scale`` (log-uniform) and ``seasonality_mode`` (choice),
scoring smape over CV folds, one process per series
(``notebooks/automl/22-09-26...py:107-125``).

On TPU the search is just more batch: candidate prior scales are TRACED
inputs to the curve-model fit (see ``models/prophet_glm._prior_precision``),
so all trials x all series x all CV cutoffs run inside one compiled program
per seasonality mode — no TPE needed when the full random-search sweep costs
less than one Stan fit.  Selection is per-series argmin of CV-mean smape
(matching the reference's per-series tuning granularity), followed by one
refit of every series with its own winning scales (a per-series (S, F) ridge
precision — one more batched solve).

Fault tolerance: a trial whose metrics go non-finite scores +inf and can
never win (``train_with_fail_safe`` semantics, ``...py:131-136``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import SeriesBatch
from distributed_forecasting_tpu.engine.cv import (
    CVConfig,
    cutoff_indices,
    cv_windows,
)
from distributed_forecasting_tpu.models import prophet_glm
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig, CurveParams
from distributed_forecasting_tpu.ops import metrics as metrics_ops


@dataclasses.dataclass(frozen=True)
class HyperSearchConfig:
    n_trials: int = 8
    metric: str = "smape"  # selection metric (reference automl: val_smape)
    cp_scale_range: Tuple[float, float] = (0.001, 0.5)
    seas_scale_range: Tuple[float, float] = (0.01, 10.0)
    # reference automl sweeps holidays_prior_scale log-uniform alongside the
    # other two scales (notebooks/automl/22-09-26...py:111-123); a no-op
    # when the model config has no holiday features
    hol_scale_range: Tuple[float, float] = (0.01, 10.0)
    modes: Tuple[str, ...] = ("additive", "multiplicative")
    seed: int = 0


@dataclasses.dataclass
class TuneResult:
    params: CurveParams          # refit with per-series best scales
    config: CurveModelConfig     # config used for the refit/serving
    best_cp_scale: np.ndarray    # (S,)
    best_seas_scale: np.ndarray  # (S,)
    best_hol_scale: np.ndarray   # (S,)
    best_mode: np.ndarray        # (S,) str
    best_score: np.ndarray       # (S,) CV-mean selection metric
    trials: pd.DataFrame         # trial table (mode, scales, mean score)
    mode_params: Dict[str, CurveParams]  # per-mode refit params (serving)


def _log_uniform(key, lo, hi, n):
    u = jax.random.uniform(key, (n,))
    return jnp.exp(jnp.log(lo) + u * (jnp.log(hi) - jnp.log(lo)))


def _cv_scores(batch: SeriesBatch, config: CurveModelConfig, cv: CVConfig,
               cp_scales, seas_scales, hol_scales, metric: str, xreg=None):
    """CV-mean metric for every (trial, series).  Returns (C_trials, S)."""
    cuts = cutoff_indices(batch.n_time, cv)
    train_masks, eval_masks, t_ends = cv_windows(
        batch.mask, batch.day, cuts, cv.horizon
    )
    fn = metrics_ops.METRIC_FNS[metric]

    def one_trial(cp, seas, hol):
        def one_cutoff(train_mask, eval_mask, t_end):
            params = prophet_glm.fit(
                batch.y, train_mask, batch.day, config,
                prior_scales=(cp, seas, hol), xreg=xreg,
            )
            yhat, _, _ = prophet_glm.forecast(
                params, batch.day, t_end, config, jax.random.PRNGKey(0),
                xreg=xreg,
            )
            return fn(batch.y, yhat, eval_mask)

        per_cut = jax.vmap(one_cutoff)(train_masks, eval_masks, t_ends)  # (C, S)
        score = jnp.mean(per_cut, axis=0)
        return jnp.where(jnp.isfinite(score), score, jnp.inf)

    return jax.vmap(one_trial)(cp_scales, seas_scales, hol_scales)


def tune_curve_model(
    batch: SeriesBatch,
    base_config: Optional[CurveModelConfig] = None,
    search: HyperSearchConfig = HyperSearchConfig(),
    cv: CVConfig = CVConfig(),
    xreg=None,
) -> TuneResult:
    """``xreg``: history-grid regressor values (longer fit_forecast-style
    tensors trimmed) when ``base_config.n_regressors > 0`` — the sweep holds
    the covariates fixed and tunes the prior scales around them; the refit
    uses them too, so ``TuneResult.mode_params`` serve with the same xreg."""
    base_config = base_config or CurveModelConfig()
    from distributed_forecasting_tpu.engine.fit import validate_xreg
    from distributed_forecasting_tpu.models.base import get_model

    xreg = validate_xreg(get_model("prophet"), "prophet", base_config, xreg,
                         None, "tune_curve_model", trim_to=batch.n_time)
    key = jax.random.PRNGKey(search.seed)
    k_cp, k_seas, k_hol = jax.random.split(key, 3)
    cp_scales = _log_uniform(k_cp, *search.cp_scale_range, search.n_trials)
    seas_scales = _log_uniform(k_seas, *search.seas_scale_range, search.n_trials)
    hol_scales = _log_uniform(k_hol, *search.hol_scale_range, search.n_trials)

    S = batch.n_series
    all_scores = []  # list of (n_trials, S) per mode
    trial_rows = []
    for mode in search.modes:
        cfg = dataclasses.replace(base_config, seasonality_mode=mode)
        scores = _cv_scores(batch, cfg, cv, cp_scales, seas_scales, hol_scales,
                            search.metric, xreg=xreg)
        all_scores.append(np.asarray(scores))
        for t in range(search.n_trials):
            trial_rows.append(
                {
                    "mode": mode,
                    "changepoint_prior_scale": float(cp_scales[t]),
                    "seasonality_prior_scale": float(seas_scales[t]),
                    "holidays_prior_scale": float(hol_scales[t]),
                    f"mean_{search.metric}": float(np.mean(all_scores[-1][t])),
                }
            )

    stacked = np.stack(all_scores)  # (n_modes, n_trials, S)
    flat = stacked.reshape(-1, S)
    best_flat = np.argmin(flat, axis=0)  # (S,)
    best_mode_idx = best_flat // search.n_trials
    best_trial_idx = best_flat % search.n_trials
    cp_np = np.asarray(cp_scales)
    seas_np = np.asarray(seas_scales)
    hol_np = np.asarray(hol_scales)
    best_cp = cp_np[best_trial_idx]
    best_seas = seas_np[best_trial_idx]
    best_hol = hol_np[best_trial_idx]
    best_mode = np.asarray(search.modes)[best_mode_idx]
    best_score = flat[best_flat, np.arange(S)]

    # refit every series with its own winning scales, once per mode (mode is
    # a static code path); serving keeps per-mode params + a mode vector.
    mode_params: Dict[str, CurveParams] = {}
    for mi, mode in enumerate(search.modes):
        cfg = dataclasses.replace(base_config, seasonality_mode=mode)
        mode_params[mode] = prophet_glm.fit(
            batch.y, batch.mask, batch.day, cfg,
            prior_scales=(jnp.asarray(best_cp), jnp.asarray(best_seas),
                          jnp.asarray(best_hol)),
            xreg=xreg,
        )

    # primary params: majority mode (used where a single CurveParams is needed)
    counts = {m: int((best_mode == m).sum()) for m in search.modes}
    major = max(counts, key=counts.get)
    return TuneResult(
        params=mode_params[major],
        config=dataclasses.replace(base_config, seasonality_mode=major),
        best_cp_scale=best_cp,
        best_seas_scale=best_seas,
        best_hol_scale=best_hol,
        best_mode=best_mode,
        best_score=best_score,
        trials=pd.DataFrame(trial_rows),
        mode_params=mode_params,
    )
