"""Vectorized hyperparameter search — the AutoML-path equivalent.

The reference's AutoML notebook tunes each series separately with hyperopt
TPE over ``changepoint_prior_scale``, ``seasonality_prior_scale``,
``holidays_prior_scale`` (log-uniform) and ``seasonality_mode`` (choice),
scoring smape over CV folds, one process per series
(``notebooks/automl/22-09-26...py:107-125``).

On TPU the search is just more batch: candidate prior scales are TRACED
inputs to the curve-model fit (see ``models/prophet_glm._prior_precision``),
so all trials x all series x all CV cutoffs run inside one compiled program
per seasonality mode — a full random-search sweep costs less than one Stan
fit.  Selection is per-series argmin of CV-mean smape (matching the
reference's per-series tuning granularity), followed by one refit of every
series with its own winning scales (a per-series (S, F) ridge precision —
one more batched solve).

ADAPTIVE search (``adaptive_rounds > 1``) recovers TPE's
exploit-the-posterior behavior the TPU-native way: after the log-uniform
round, each further round resamples every series' scales log-normally
AROUND THAT SERIES' OWN INCUMBENT with a geometrically shrinking width —
per-series zoom, the same granularity hyperopt gets from one TPE process
per series, at batch cost: prior scales are data, so every round reuses
ONE compiled program per mode ((n_trials, S)-shaped trials; the incumbent
update is an elementwise min).  Round 0 explores the box; later rounds
exploit; the box clips every proposal.

Fault tolerance: a trial whose metrics go non-finite scores +inf and can
never win (``train_with_fail_safe`` semantics, ``...py:131-136``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from distributed_forecasting_tpu.data.tensorize import SeriesBatch
from distributed_forecasting_tpu.engine.cv import (
    CVConfig,
    cutoff_indices,
    cv_windows,
)
from distributed_forecasting_tpu.models import prophet_glm
from distributed_forecasting_tpu.models.prophet_glm import CurveModelConfig, CurveParams
from distributed_forecasting_tpu.ops import metrics as metrics_ops


@dataclasses.dataclass(frozen=True)
class AutoMLConfig:
    """The strict ``engine.automl`` conf block: the cross-family
    successive-halving sweep (engine/select.py,
    :func:`~distributed_forecasting_tpu.engine.select.successive_halving_select`).

    Successive halving in the auto-sktime spirit (arXiv 2312.08528): rung
    r evaluates the surviving families on a ``base_series * eta**r``-sized
    series subset and the last ``base_cutoffs * eta**r`` CV cutoffs, then
    keeps the best ``1/eta`` fraction.  Subset sizes follow the shared
    pow2 shape-bucket ladder, so every rung (and every later sweep) reuses
    the same compiled CV programs per family.  ``budget_device_seconds``
    is a LAUNCH GATE against the PR-10 cost-attribution counters: no new
    family evaluation starts once the sweep's attributed device-seconds
    meter reads >= budget (docs/automl.md#budget-accounting).
    """

    enabled: bool = False
    budget_device_seconds: float = 60.0
    eta: int = 2
    rungs: int = 3
    base_series: int = 64
    base_cutoffs: int = 1
    metric: str = "smape"
    families: tuple = ("prophet", "holt_winters", "theta", "croston",
                       "arima", "arnet")

    def __post_init__(self):
        if self.eta < 2:
            raise ValueError(f"eta must be >= 2, got {self.eta}")
        if self.rungs < 1:
            raise ValueError(f"rungs must be >= 1, got {self.rungs}")
        if self.budget_device_seconds <= 0:
            raise ValueError(
                f"budget_device_seconds must be > 0, got "
                f"{self.budget_device_seconds}")
        if self.base_series < 1:
            raise ValueError(
                f"base_series must be >= 1, got {self.base_series}")
        if self.base_cutoffs < 1:
            raise ValueError(
                f"base_cutoffs must be >= 1, got {self.base_cutoffs}")
        if not self.families:
            raise ValueError("families must name at least one family")

    @classmethod
    def from_conf(cls, conf: Optional[dict]) -> "AutoMLConfig":
        conf = conf or {}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(conf) - known
        if unknown:
            raise ValueError(
                f"unknown engine.automl conf key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        kwargs = {
            f.name: type(f.default)(conf[f.name])
            for f in dataclasses.fields(cls)
            if f.name in conf and conf[f.name] is not None
        }
        return cls(**kwargs)


_active_automl = AutoMLConfig()


def configure_automl(conf) -> AutoMLConfig:
    """Install the process-wide AutoML sweep config (tasks/common parses
    the ``engine.automl`` conf block into this)."""
    global _active_automl
    cfg = conf if isinstance(conf, AutoMLConfig) \
        else AutoMLConfig.from_conf(conf)
    _active_automl = cfg
    return cfg


def automl_config() -> AutoMLConfig:
    return _active_automl


@dataclasses.dataclass(frozen=True)
class HyperSearchConfig:
    n_trials: int = 8
    metric: str = "smape"  # selection metric (reference automl: val_smape)
    cp_scale_range: Tuple[float, float] = (0.001, 0.5)
    seas_scale_range: Tuple[float, float] = (0.01, 10.0)
    # reference automl sweeps holidays_prior_scale log-uniform alongside the
    # other two scales (notebooks/automl/22-09-26...py:111-123); a no-op
    # when the model config has no holiday features
    hol_scale_range: Tuple[float, float] = (0.01, 10.0)
    modes: Tuple[str, ...] = ("additive", "multiplicative")
    seed: int = 0
    # adaptive zoom (TPE-parity): total rounds including the log-uniform
    # round; each later round samples per-series log-normal around that
    # series' incumbent with width zoom_sigma * zoom_factor**(round-1),
    # clipped to the box.  1 = plain random search.
    adaptive_rounds: int = 1
    zoom_sigma: float = 0.8
    zoom_factor: float = 0.5


@dataclasses.dataclass
class TuneResult:
    params: CurveParams          # refit with per-series best scales
    config: CurveModelConfig     # config used for the refit/serving
    best_cp_scale: np.ndarray    # (S,)
    best_seas_scale: np.ndarray  # (S,)
    best_hol_scale: np.ndarray   # (S,)
    best_mode: np.ndarray        # (S,) str
    best_score: np.ndarray       # (S,) CV-mean selection metric
    trials: pd.DataFrame         # trial table (mode, scales, mean score)
    mode_params: Dict[str, CurveParams]  # per-mode refit params (serving)


def _log_uniform(key, lo, hi, n):
    u = jax.random.uniform(key, (n,))
    return jnp.exp(jnp.log(lo) + u * (jnp.log(hi) - jnp.log(lo)))


def _cv_scores(batch: SeriesBatch, config: CurveModelConfig, cv: CVConfig,
               cp_scales, seas_scales, hol_scales, metric: str, xreg=None):
    """CV-mean metric for every (trial, series).  Returns (C_trials, S)."""
    cuts = cutoff_indices(batch.n_time, cv)
    train_masks, eval_masks, t_ends = cv_windows(
        batch.mask, batch.day, cuts, cv.horizon
    )
    fn = metrics_ops.METRIC_FNS[metric]

    def one_trial(cp, seas, hol):
        def one_cutoff(train_mask, eval_mask, t_end):
            params = prophet_glm.fit(
                batch.y, train_mask, batch.day, config,
                prior_scales=(cp, seas, hol), xreg=xreg,
            )
            yhat, _, _ = prophet_glm.forecast(
                params, batch.day, t_end, config, jax.random.PRNGKey(0),
                xreg=xreg,
            )
            return fn(batch.y, yhat, eval_mask)

        per_cut = jax.vmap(one_cutoff)(train_masks, eval_masks, t_ends)  # (C, S)
        score = jnp.mean(per_cut, axis=0)
        return jnp.where(jnp.isfinite(score), score, jnp.inf)

    return jax.vmap(one_trial)(cp_scales, seas_scales, hol_scales)


def tune_curve_model(
    batch: SeriesBatch,
    base_config: Optional[CurveModelConfig] = None,
    search: HyperSearchConfig = HyperSearchConfig(),
    cv: CVConfig = CVConfig(),
    xreg=None,
) -> TuneResult:
    """``xreg``: history-grid regressor values (longer fit_forecast-style
    tensors trimmed) when ``base_config.n_regressors > 0`` — the sweep holds
    the covariates fixed and tunes the prior scales around them; the refit
    uses them too, so ``TuneResult.mode_params`` serve with the same xreg."""
    base_config = base_config or CurveModelConfig()
    from distributed_forecasting_tpu.engine.fit import validate_xreg
    from distributed_forecasting_tpu.models.base import get_model

    xreg = validate_xreg(get_model("prophet"), "prophet", base_config, xreg,
                         None, "tune_curve_model", trim_to=batch.n_time)
    key = jax.random.PRNGKey(search.seed)
    S = batch.n_series
    n = search.n_trials
    ranges = (search.cp_scale_range, search.seas_scale_range,
              search.hol_scale_range)

    # per-series incumbent state; round 0 always updates it (inf scores lose
    # to anything finite; the geometric box midpoints only survive if every
    # single trial went non-finite for a series)
    best_score = np.full(S, np.inf)
    best_cp, best_seas, best_hol = (
        np.full(S, float(np.sqrt(lo * hi))) for lo, hi in ranges
    )
    best_mode_idx = np.zeros(S, dtype=int)

    trial_rows = []
    rounds = max(1, int(search.adaptive_rounds))
    for r in range(rounds):
        if r == 0:
            key, k_cp, k_seas, k_hol = jax.random.split(key, 4)
            trials = [
                _log_uniform(k, lo, hi, n)  # (n,) shared across series
                for k, (lo, hi) in zip((k_cp, k_seas, k_hol), ranges)
            ]
        else:
            # zoom: per-series log-normal around each series' incumbent,
            # geometrically narrowing, clipped to the box.  (n, S)-shaped
            # trial values are DATA, so every zoom round reuses the same
            # compiled program per mode.
            sigma = search.zoom_sigma * search.zoom_factor ** (r - 1)
            key, k_cp, k_seas, k_hol = jax.random.split(key, 4)
            trials = []
            for k, (lo, hi), inc in zip(
                (k_cp, k_seas, k_hol), ranges, (best_cp, best_seas, best_hol)
            ):
                eps = jax.random.normal(k, (n, S))
                prop = jnp.exp(jnp.log(jnp.asarray(inc))[None, :] + sigma * eps)
                trials.append(jnp.clip(prop, lo, hi))
        cp_t, seas_t, hol_t = trials
        cp_np, seas_np, hol_np = (np.asarray(v) for v in trials)

        for mi, mode in enumerate(search.modes):
            cfg = dataclasses.replace(base_config, seasonality_mode=mode)
            scores = np.asarray(
                _cv_scores(batch, cfg, cv, cp_t, seas_t, hol_t,
                           search.metric, xreg=xreg)
            )  # (n, S)
            for t in range(n):
                finite = np.isfinite(scores[t])
                trial_rows.append(
                    {
                        "round": r,
                        "mode": mode,
                        # zoom rounds carry per-series scales; the table
                        # reports the geometric mean as the trial's location
                        "changepoint_prior_scale": float(
                            np.exp(np.mean(np.log(cp_np[t])))
                        ),
                        "seasonality_prior_scale": float(
                            np.exp(np.mean(np.log(seas_np[t])))
                        ),
                        "holidays_prior_scale": float(
                            np.exp(np.mean(np.log(hol_np[t])))
                        ),
                        f"mean_{search.metric}": float(
                            np.mean(scores[t][finite])
                        ) if finite.any() else float("inf"),
                    }
                )
            t_best = np.argmin(scores, axis=0)  # (S,)
            sc = scores[t_best, np.arange(S)]
            upd = sc < best_score

            def pick(vals, t_best=t_best):
                return vals[t_best] if vals.ndim == 1 else vals[t_best,
                                                               np.arange(S)]

            best_cp = np.where(upd, pick(cp_np), best_cp)
            best_seas = np.where(upd, pick(seas_np), best_seas)
            best_hol = np.where(upd, pick(hol_np), best_hol)
            best_mode_idx = np.where(upd, mi, best_mode_idx)
            best_score = np.minimum(best_score, sc)

    best_mode = np.asarray(search.modes)[best_mode_idx]

    # refit every series with its own winning scales, once per mode (mode is
    # a static code path); serving keeps per-mode params + a mode vector.
    mode_params: Dict[str, CurveParams] = {}
    for mi, mode in enumerate(search.modes):
        cfg = dataclasses.replace(base_config, seasonality_mode=mode)
        mode_params[mode] = prophet_glm.fit(
            batch.y, batch.mask, batch.day, cfg,
            prior_scales=(jnp.asarray(best_cp), jnp.asarray(best_seas),
                          jnp.asarray(best_hol)),
            xreg=xreg,
        )

    # primary params: majority mode (used where a single CurveParams is needed)
    counts = {m: int((best_mode == m).sum()) for m in search.modes}
    major = max(counts, key=counts.get)
    return TuneResult(
        params=mode_params[major],
        config=dataclasses.replace(base_config, seasonality_mode=major),
        best_cp_scale=best_cp,
        best_seas_scale=best_seas,
        best_hol_scale=best_hol,
        best_mode=best_mode,
        best_score=best_score,
        trials=pd.DataFrame(trial_rows),
        mode_params=mode_params,
    )
