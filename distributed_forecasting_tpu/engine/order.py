"""Automatic ARIMA order selection — ``model_conf: {order: auto}``.

pmdarima's ``auto_arima`` (the tool a reference user would reach for next
to Prophet) steps through (p, d, q) candidates refitting per series; with
this framework's closed-form Hannan-Rissanen fit, EVERY candidate order is
one compiled batched fit+CV over all series, so a small grid sweep is
seconds, not minutes, and needs no stepwise heuristics.

Selection is by rolling-origin CV (the framework's one validation
currency — information criteria would need exact likelihoods the HR fit
does not produce, and CV compares across ``d`` where in-sample
likelihoods cannot).  The winner is the order minimizing the batch-mean
metric over series with finite scores; the decision table is returned so
the pipeline can log what lost and by how much.

Like ``season_length: auto`` (engine/season), the result must be STATIC —
(p, d, q) shape the compiled programs — so selection runs once on the
host and the config carries plain ints.  Batch-level by design: per-series
orders would mean one compiled program per distinct order at serving time
(that is what ``model: auto``'s family dispatch is for).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from distributed_forecasting_tpu.engine.cv import CVConfig, cross_validate

# the default ladder: every (p, q) in a compact box at both d values,
# skipping the degenerate (0, d, 0) white-noise/drift orders
DEFAULT_ORDERS: Tuple[Tuple[int, int, int], ...] = tuple(
    (p, d, q)
    for d in (0, 1)
    for p in (0, 1, 2, 3)
    for q in (0, 1, 2)
    if (p, q) != (0, 0)
)


def select_arima_order(
    batch,
    orders: Sequence[Tuple[int, int, int]] = DEFAULT_ORDERS,
    base_conf: Optional[dict] = None,
    metric: str = "smape",
    cv: CVConfig = CVConfig(),
    key=None,
):
    """CV every candidate (p, d, q); return ``(best_order, table)``.

    ``base_conf``: the rest of the ArimaConfig fields (seasonal terms,
    method, ...) shared by every candidate.  ``table`` rows:
    ``((p, d, q), score, n_finite)`` sorted best-first, where ``score``
    is the batch-mean metric over finite-scoring series.
    """
    from distributed_forecasting_tpu.models.arima import ArimaConfig

    if key is None:
        key = jax.random.PRNGKey(0)
    base = dict(base_conf or {})
    base.pop("order", None)
    rows = []
    for i, (p, d, q) in enumerate(orders):
        config = ArimaConfig(p=int(p), d=int(d), q=int(q), **base)
        res = cross_validate(
            batch, model="arima", config=config, cv=cv,
            key=jax.random.fold_in(key, i),
        )
        vals = np.asarray(res[metric], dtype=np.float64)
        finite = np.isfinite(vals)
        score = float(np.mean(vals[finite])) if finite.any() else np.inf
        rows.append(((int(p), int(d), int(q)), score, int(finite.sum())))
    rows.sort(key=lambda r: r[1])
    best, best_score, _ = rows[0]
    if not np.isfinite(best_score):
        raise ValueError(
            "no candidate order produced a finite CV score — the batch may "
            "be too short for the CV config, or the series degenerate"
        )
    return best, rows


def resolve_order_conf(model_conf, batch, cv_conf=None) -> Optional[dict]:
    """Translate ``order: auto`` (or an explicit ``order: [p, d, q]``) in an
    arima ``model_conf`` into plain p/d/q fields — the ``_resolve_*_conf``
    sibling of the season/holiday translators (pipelines/training.py).

    Optional sibling keys (popped here, never reaching ArimaConfig):
    ``order_candidates`` restricts the ladder; ``order_metric`` picks the
    selection metric (default smape — set it to match an auto/blend conf's
    ``metric`` so the two selection mechanisms agree).

    Note on cost: when the pipeline later cross-validates the winning
    config, that pass re-runs — but against the jit cache (same static
    config as the sweep's winner), so it costs one execution, not a
    compile; threading the sweep's per-series metrics through every
    pipeline path was judged not worth the coupling.
    """
    if not model_conf:
        return model_conf
    if "order" not in model_conf:
        stray = [k for k in ("order_candidates", "order_metric")
                 if k in model_conf]
        if stray:
            # without "order" these would pass through to ArimaConfig and
            # die as an opaque unexpected-keyword TypeError
            raise ValueError(
                f"{' / '.join(stray)} only take effect alongside an "
                f"'order' key (e.g. order: auto) — add one or drop them"
            )
        return model_conf
    out = dict(model_conf)
    spec = out.pop("order")
    candidates = out.pop("order_candidates", None)
    metric = out.pop("order_metric", "smape")
    if isinstance(spec, str) and spec == "auto":
        base = {k: v for k, v in out.items() if k not in ("p", "d", "q")}
        cv = CVConfig(**(cv_conf or {}))
        orders = (
            tuple(tuple(int(x) for x in o) for o in candidates)
            if candidates else DEFAULT_ORDERS
        )
        (p, d, q), _ = select_arima_order(batch, orders=orders,
                                          base_conf=base, cv=cv,
                                          metric=metric)
        out.update(p=p, d=d, q=q)
        return out
    if isinstance(spec, (list, tuple)) and len(spec) == 3:
        if candidates is not None or "order_metric" in (model_conf or {}):
            # a leftover pin next to an intended sweep: silently running
            # only the pinned order would let the user believe the grid
            # was searched
            raise ValueError(
                f"order: {list(spec)} pins the order — order_candidates/"
                f"order_metric would be ignored; use order: auto to sweep "
                f"or drop them"
            )
        out.update(p=int(spec[0]), d=int(spec[1]), q=int(spec[2]))
        return out
    raise ValueError(
        f"arima order must be 'auto' or a [p, d, q] triple, got {spec!r}"
    )
