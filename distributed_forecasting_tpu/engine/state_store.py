"""Per-series filter state behind the streaming ingest path.

ARIMA_PLUS (arXiv:2510.24452) keeps model state IN the database so ingest
and forecast share one source of truth; this module is that state holder
for the batched JAX world: one :class:`SeriesStateStore` per served
forecaster, owning the live param pytree (level/trend/seasonal for
holt_winters, SES level for theta, demand/interval carries for croston),
the update-aux moments the fit does not persist, a padded fitted-path
buffer, and the pending buffer of not-yet-applied points.

Shape discipline — the whole point of routing streaming through here:

- the SERIES axis keeps the forecaster's existing bucket ladder untouched
  (states are full-(S,) arrays; requests gather);
- the NEW-DAY axis K is padded to a power of two (``ops/update
  .column_bucket``) with per-column ``valid`` flags, so the stream of
  single-day and burst applies reuses a handful of compiled programs;
- the TIME axis of the fitted/history buffers grows in ``time_bucket``
  increments, and the forecaster's predict grid pads to the same bucket
  (``BatchForecaster.time_bucket``), so a day-1 apply does not recompile
  every predict program.

Concurrency contract (the dflint blocking-under-lock rules apply):
``_lock`` guards the in-memory pending buffer, the installed-state
references, and the history buffers' late-point writes and grow-swap —
snapshot-then-release, never held across a device dispatch or file I/O; ``_apply_gate`` is a capacity-1 ``BoundedSemaphore``
serializing state WRITERS (apply_pending, the refit install) against
each other so their read-modify-write of the param pytree is atomic — a
semaphore, not a lock, deliberately: writers legitimately hold the gate
across the update dispatch (which can reach the AOT store's disk I/O),
which is exactly the capacity-limiter pattern the lock-order lint
exempts.  Readers (predict) take neither — they see state through
``BatchForecaster.swap_state``'s atomic snapshot.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_forecasting_tpu.engine.compile_cache import donated_variant
from distributed_forecasting_tpu.models.base import get_model
from distributed_forecasting_tpu.monitoring import sanitizer
from distributed_forecasting_tpu.monitoring.failpoints import failpoint
from distributed_forecasting_tpu.monitoring.trace import get_tracer
from distributed_forecasting_tpu.ops.update import apply_update, column_bucket
from distributed_forecasting_tpu.utils import get_logger


def time_cap(t: int, bucket: int) -> int:
    """Smallest multiple of ``bucket`` >= t (minimum one bucket)."""
    b = max(int(bucket), 1)
    return max((int(t) + b - 1) // b, 1) * b


class SeriesStateStore:
    """Live filter state + pending points for one streamed forecaster."""

    def __init__(self, forecaster, time_bucket: int = 32,
                 history_y: Optional[np.ndarray] = None,
                 history_mask: Optional[np.ndarray] = None,
                 metrics=None, max_pending_days: int = 366):
        fns = get_model(forecaster.model)
        if fns.update_state is None or fns.init_update_aux is None:
            raise ValueError(
                f"model {forecaster.model!r} has no streaming update kernel; "
                f"ingest supports holt_winters, theta, and croston"
            )
        self._fc = forecaster
        self._fns = fns
        self.model = forecaster.model
        self.config = forecaster.config
        self.day0 = int(forecaster.day0)
        self.time_bucket = max(int(time_bucket), 1)
        self.max_pending_days = max(int(max_pending_days), 1)
        self.metrics = metrics
        self.logger = get_logger("SeriesStateStore")

        self._lock = threading.Lock()        # pending + installed-state refs
        self._apply_gate = threading.BoundedSemaphore(1)  # state writers
        # one locked snapshot: attaching to a forecaster that is already
        # serving must not pair post-swap params with a pre-swap day1
        params, day1 = forecaster._state_snapshot()
        self._day_cur = int(day1)
        self._pending: Dict[int, Dict[int, float]] = {}
        self._applied_since_refit = 0
        self._late_points = 0
        self._last_refit_monotonic = time.monotonic()
        S, T0 = params.fitted.shape
        self.n_series = S
        t_cap = time_cap(T0, self.time_bucket)
        fitted = jnp.pad(jnp.asarray(params.fitted),
                         ((0, 0), (0, t_cap - T0)))
        self._params = dataclasses.replace(params, fitted=fitted)
        # history buffers: required for full refits (and for folding late
        # points in); optional for pure incremental serving
        if history_y is not None and history_mask is not None:
            self._y = np.zeros((S, t_cap), np.float32)
            self._mask = np.zeros((S, t_cap), np.float32)
            self._y[:, :T0] = np.asarray(history_y, np.float32)
            self._mask[:, :T0] = np.asarray(history_mask, np.float32)
            aux_args = {"y": jnp.asarray(self._y[:, :T0]),
                        "mask": jnp.asarray(self._mask[:, :T0])}
        else:
            self._y = None
            self._mask = None
            aux_args = {}
        self._aux = fns.init_update_aux(self._params, **aux_args)
        # install: predicts now pad their grid on the same time bucket and
        # serve from the padded fitted buffer (padding rows are never read
        # — history_splice only gathers days <= t_fit_end)
        forecaster.time_bucket = self.time_bucket
        forecaster.swap_state(params=self._params, day1=self._day_cur)
        # dftsan (no-op unless DFTPU_TSAN armed): the pending-points buffer
        # every ingest/apply/stats path reads or mutates
        sanitizer.attach(self, cls=SeriesStateStore, guards={
            "_lock": ("_pending",)})

    # -- introspection -------------------------------------------------------
    @property
    def day_cur(self) -> int:
        with self._lock:
            return self._day_cur

    @property
    def can_refit(self) -> bool:
        """Full refits need the training history (serving from a bare
        artifact has only params — incremental updates still work)."""
        return self._y is not None

    def stats(self) -> Dict:
        with self._lock:
            dirty = set()
            for points in self._pending.values():
                dirty.update(points)
            return {
                "day_cur": self._day_cur,
                "pending_days": len(self._pending),
                "dirty_series": len(dirty),
                "pending_points": sum(
                    len(p) for p in self._pending.values()),
                "applied_since_refit": self._applied_since_refit,
                "late_points": self._late_points,
                "seconds_since_refit":
                    time.monotonic() - self._last_refit_monotonic,
            }

    # -- ingest --------------------------------------------------------------
    def ingest(self, points: List[Tuple[int, int, float]]) -> Dict[str, int]:
        """Buffer ``(series_idx, day, y)`` observations.

        Days past the applied frontier go to the pending buffer (last write
        wins per (series, day)); days inside the applied window fold into
        the history buffers only — they are "late" and reach model state at
        the next full refit, exactly like a warehouse backfill; days before
        the training grid OR beyond ``day_cur + max_pending_days`` are
        rejected — the apply densifies ``max_day - day_cur`` columns, so
        one typo'd far-future ordinal would otherwise size multi-GB host
        and device buffers and silently advance the frontier past every
        real day.  In-memory only: callers persist to the WAL first
        (serving/ingest) — this buffer is reconstructible by replay.
        """
        accepted = late = rejected = 0
        with self._lock:
            day_cur = self._day_cur
            horizon = day_cur + self.max_pending_days
            for sidx, day, y in points:
                if day > horizon:
                    rejected += 1
                elif day > day_cur:
                    self._pending.setdefault(int(day), {})[int(sidx)] = \
                        float(y)
                    accepted += 1
                elif day >= self.day0:
                    if self._y is not None:
                        row = int(day) - self.day0
                        self._y[int(sidx), row] = float(y)
                        self._mask[int(sidx), row] = 1.0
                    late += 1
                    self._late_points += 1
                else:
                    rejected += 1
        return {"accepted": accepted, "late": late, "rejected": rejected}

    # -- the batched apply ---------------------------------------------------
    def apply_pending(self) -> Dict[str, int]:
        """Apply every pending point in ONE batched update dispatch.

        Builds dense (S, K) day-columns from the pending buffer — all
        series, masked where no point arrived, covering every day up to
        the pending frontier (gap days are all-masked columns: the same
        rows a full refit's extended contiguous grid would contain) — and
        routes them through ``ops/update.apply_update``.  K pads to the
        column bucket; the state installs atomically into the forecaster.
        """
        with self._apply_gate:
            with self._lock:
                if not self._pending:
                    return {"days": 0, "points": 0}
                day_cur = self._day_cur
                pending, self._pending = self._pending, {}
            t0 = time.monotonic()
            max_day = max(pending)
            horizon = day_cur + self.max_pending_days
            if max_day > horizon:
                # ingest() already rejects beyond-horizon days; this guards
                # direct callers and WALs written before the horizon
                # existed, whose replay must not OOM every follower
                dropped = sum(len(p) for d, p in pending.items()
                              if d > horizon)
                self.logger.warning(
                    "dropping %d pending point(s) beyond the %d-day "
                    "horizon (max day %d, frontier %d)", dropped,
                    self.max_pending_days, max_day, day_cur)
                pending = {d: p for d, p in pending.items() if d <= horizon}
                if not pending:
                    return {"days": 0, "points": 0}
                max_day = max(pending)
            k = max_day - day_cur
            n_points = sum(len(p) for p in pending.values())
            k_alloc = column_bucket(k)
            y_new = np.zeros((self.n_series, k_alloc), np.float32)
            m_new = np.zeros((self.n_series, k_alloc), np.float32)
            for day, points in pending.items():
                col = day - day_cur - 1
                for sidx, y in points.items():
                    y_new[sidx, col] = y
                    m_new[sidx, col] = 1.0
            valid = np.zeros((k_alloc,), np.float32)
            valid[:k] = 1.0
            day_new = np.arange(day_cur + 1, day_cur + 1 + k_alloc,
                                dtype=np.int32)

            params2, aux2, preds = apply_update(
                self.model, self.config, self._params, self._aux,
                jnp.asarray(y_new), jnp.asarray(m_new), jnp.asarray(valid),
                jnp.asarray(day_new),
            )
            t_len = day_cur - self.day0 + 1
            fitted = self._grown_fitted(params2.fitted, t_len + k)
            fitted = jax.lax.dynamic_update_slice(
                fitted, preds[:, :k], (0, t_len))
            params2 = dataclasses.replace(params2, fitted=fitted)
            if self._y is not None:
                self._grow_history(t_len + k)
                self._y[:, t_len:t_len + k] = y_new[:, :k]
                self._mask[:, t_len:t_len + k] = m_new[:, :k]
            with self._lock:
                self._params = params2
                self._aux = aux2
                self._day_cur = max_day
                self._applied_since_refit += n_points
            # fault site between the store's commit and the forecaster's:
            # a crash HERE is the worst apply-path moment (store advanced,
            # serving snapshot not yet swapped) — what WAL replay must heal
            failpoint("state.swap")
            self._fc.swap_state(params=params2, day1=max_day)
            if self.metrics is not None:
                self.metrics.update_seconds.observe(time.monotonic() - t0)
                self.metrics.applied_points_total.inc(n_points)
            return {"days": k, "points": n_points}

    def _grown_fitted(self, fitted, t_need: int):
        t_cap = int(fitted.shape[1])
        if t_need <= t_cap:
            return fitted
        new_cap = time_cap(t_need, self.time_bucket)
        return jnp.pad(fitted, ((0, 0), (0, new_cap - t_cap)))

    def _grow_history(self, t_need: int) -> None:
        t_cap = self._y.shape[1]
        if t_need <= t_cap:
            return
        new_cap = time_cap(t_need, self.time_bucket)
        pad = new_cap - t_cap
        # pad-and-swap under _lock: ingest() writes late points into
        # self._y under the same lock, and a copy-then-reassign outside it
        # would drop any write landing in the old buffer mid-copy — the
        # next refit would silently train without that point.  Memory-only
        # work, so holding the lock here stays within the contract.
        with self._lock:
            self._y = np.pad(self._y, ((0, 0), (0, pad)))
            self._mask = np.pad(self._mask, ((0, 0), (0, pad)))

    # -- background full refit ----------------------------------------------
    def refit_stages(self):
        """(prep, dispatch, complete) closures for ``TrainingExecutor
        .submit`` — a full refit as a background pipeline experiment.

        prep snapshots the history under ``_lock``; dispatch launches the
        family's grid-search fit on the real (unpadded) extended grid;
        complete — on the executor's ordered writer thread — REPLAYS any
        columns applied while the fit ran (exact continuation through the
        same update kernel), rebuilds the fitted buffer, and swaps the
        fresh state in under a ``refit.swap`` span.  ``interval_scale`` is
        left as fit originally calibrated it (re-calibration needs a CV
        pass, out of streaming scope — docs/streaming.md).
        """
        if not self.can_refit:
            raise ValueError(
                "refit needs the training history; this store was attached "
                "without (history_y, history_mask)")

        def prep():
            with self._lock:
                day_snap = self._day_cur
                t_len = day_snap - self.day0 + 1
                y = self._y[:, :t_len].copy()
                mask = self._mask[:, :t_len].copy()
            return {"day_snap": day_snap, "y": y, "mask": mask,
                    "t0": time.monotonic()}

        def dispatch(prepared):
            day = jnp.arange(self.day0, prepared["day_snap"] + 1,
                             dtype=jnp.int32)
            # the (S, T) y/mask staging buffers are donated: prep() made
            # them as private copies, nothing reads them after this call,
            # and fit's dominant output (params.fitted, same shape/dtype
            # as y) can then be written in place of the history instead of
            # doubling the refit's working set
            fit_donated = donated_variant(
                self._fns.fit, donate_argnums=(0, 1),
                static_argnames=("config",))
            params = fit_donated(
                jnp.asarray(prepared["y"]), jnp.asarray(prepared["mask"]),
                day, self.config)
            return {**prepared, "params": params}

        def complete(state):
            with self._apply_gate:
                self._install_refit(state)
            return {"day_snap": state["day_snap"]}

        return prep, dispatch, complete

    def _install_refit(self, state) -> None:
        """Replay-and-swap under ``_apply_gate`` (caller holds it)."""
        # fault site before any mutation: an injected failure leaves the
        # last-good state fully installed, the invariant chaos asserts
        failpoint("refit.install")
        day_snap = int(state["day_snap"])
        params = state["params"]
        t_snap = day_snap - self.day0 + 1
        aux = self._fns.init_update_aux(
            params, y=jnp.asarray(self._y[:, :t_snap]),
            mask=jnp.asarray(self._mask[:, :t_snap]))
        with self._lock:
            day_now = self._day_cur
        delta = day_now - day_snap
        t_cap = time_cap(day_now - self.day0 + 1, self.time_bucket)
        fitted = jnp.pad(params.fitted, ((0, 0), (0, t_cap - t_snap)))
        if delta > 0:
            # columns applied while the fit ran: replay them through the
            # same update kernel so the installed state is the exact
            # continuation of the new fit over everything seen so far
            k_alloc = column_bucket(delta)
            y_new = np.zeros((self.n_series, k_alloc), np.float32)
            m_new = np.zeros((self.n_series, k_alloc), np.float32)
            y_new[:, :delta] = self._y[:, t_snap:t_snap + delta]
            m_new[:, :delta] = self._mask[:, t_snap:t_snap + delta]
            valid = np.zeros((k_alloc,), np.float32)
            valid[:delta] = 1.0
            day_new = np.arange(day_snap + 1, day_snap + 1 + k_alloc,
                                dtype=np.int32)
            params, aux, preds = apply_update(
                self.model, self.config,
                dataclasses.replace(params, fitted=fitted), aux,
                jnp.asarray(y_new), jnp.asarray(m_new), jnp.asarray(valid),
                jnp.asarray(day_new),
            )
            fitted = jax.lax.dynamic_update_slice(
                params.fitted, preds[:, :delta], (0, t_snap))
        params = dataclasses.replace(params, fitted=fitted)
        with get_tracer().span("refit.swap", model=self.model,
                               day_snap=day_snap, replayed_days=delta):
            with self._lock:
                self._params = params
                self._aux = aux
                self._applied_since_refit = 0
                self._late_points = 0
                self._last_refit_monotonic = time.monotonic()
            self._fc.swap_state(params=params, day1=day_now)
        if self.metrics is not None:
            self.metrics.refits_total.inc()
            self.metrics.refit_seconds.observe(
                time.monotonic() - state["t0"])
        self.logger.info(
            "refit installed through day %d (replayed %d day(s))",
            day_now, delta)
