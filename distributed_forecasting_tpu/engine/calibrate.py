"""Split-conformal calibration of forecast intervals from CV residuals.

The reference *measures* interval quality (the AutoML path logs a
``coverage`` metric per series, ``notebooks/automl/22-09-26...py:91-105``)
but nothing ever closes the loop — a model whose 95% band covers 80% ships
that band.  This module closes it with split conformal prediction (Vovk et
al.; Romano et al.'s CQR is the quantile-regression cousin — public
methods): the rolling-origin CV forecasts the engine already produces
(``engine/cv``) serve as the calibration set, and the model's own band
half-width is the conformity scale, so the calibrated interval is the
parametric one multiplied per series by the smallest factor that would have
covered ``interval_width`` of the CV residuals.

Why this shape of conformal (scaled-band, not raw-residual):

* normalizing each residual by the model's half-band at that (series, lead)
  keeps the band's *shape* — lead-time widening, level scaling — and
  corrects only its overall miscalibration, which is the failure mode of a
  Gaussian band on heavy-tailed demand;
* the score reduces to one sorted reduction per series — TPU-friendly, no
  refits, no extra model passes (the CV paths are already materialized when
  ``cross_validate(..., calibrate=True)``);
* per-series quantiles need enough CV points: series whose eval windows are
  mostly masked fall back to the POOLED quantile across all series
  (``min_points``), conformal's exchangeability argument applying across
  the batch instead.

Everything is a pure reduction over the (C, S, T) CV paths — no Python
loops, jit-compiled, and independent of the model family (any registered
family whose forecast returns (yhat, lo, hi) calibrates identically).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from distributed_forecasting_tpu.engine.cv import (
    CVConfig,
    _cv_entry,
    _cv_paths_impl,
    cutoff_indices,
)

_EPS = 1e-9


@partial(jax.jit, static_argnames=("interval_width", "min_points"))
def _conformal_scale_impl(y, yhat, hi, eval_masks, interval_width: float,
                          min_points: int):
    """Per-series conformal scale from (C, S, T) CV paths.

    Score r = |y - yhat| / (hi - yhat): the residual in units of the
    model's UPPER half-band (the lower one may be clamped — croston floors
    at 0, multiplicative bands are asymmetric; same rationale as
    ``monitoring.monitor.detect_anomalies``).  The conformal quantile is
    the ceil((n+1) * width)-th order statistic — the finite-sample-valid
    rank, giving >= width coverage on exchangeable data.
    """
    half = hi - yhat
    # validity: observed AND a non-degenerate band.  A cutoff that predates
    # a late-starting series' history produces a degenerate fit there
    # (hi == yhat) while the eval window IS observed — dividing by the eps
    # floor would inject ~1e9 scores that the rank quantile then lands on,
    # widening the shipped band astronomically (and polluting the pooled
    # fallback).  Such points carry no band information; exclude them from
    # the calibration set (a fully-degenerate series has n = 0 and takes
    # the pooled scale).  The threshold is RELATIVE to the point path so a
    # legitimately tiny-magnitude series (rates ~1e-7) keeps its genuine
    # small bands in the set; only true hi == yhat collapse is excluded.
    obs = (eval_masks > 0) & (half > 1e-6 * (jnp.abs(yhat) + _EPS))
    r = jnp.abs(y[None] - yhat) / jnp.maximum(half, _EPS)    # (C, S, T)
    r = jnp.where(obs, r, jnp.inf)
    S = r.shape[1]
    r_s = jnp.sort(jnp.swapaxes(r, 0, 1).reshape(S, -1), axis=1)  # (S, C*T)
    n = jnp.sum(obs, axis=(0, 2)).astype(jnp.float32)        # (S,)
    k = jnp.ceil((n + 1.0) * interval_width).astype(jnp.int32) - 1
    k = jnp.clip(k, 0, jnp.maximum(n.astype(jnp.int32) - 1, 0))
    q = jnp.take_along_axis(r_s, k[:, None], axis=1)[:, 0]

    # pooled fallback for thin series (and the k > n-1 clip above means a
    # thin series' own quantile would under-cover anyway)
    r_all = jnp.sort(r_s.reshape(-1))
    n_tot = jnp.sum(n)
    k_tot = jnp.ceil((n_tot + 1.0) * interval_width).astype(jnp.int32) - 1
    k_tot = jnp.clip(k_tot, 0, jnp.maximum(n_tot.astype(jnp.int32) - 1, 0))
    q_pool = r_all[k_tot]
    q = jnp.where(n >= min_points, q, q_pool)
    # no calibration data at all (or degenerate inf quantile): identity
    q = jnp.where(jnp.isfinite(q) & (n_tot > 0), q, 1.0)
    return q


def config_interval_width(config) -> float:
    """The width a config's bands target — single source for every
    calibration route (standalone, cross_validate, fused CV impl)."""
    return float(getattr(config, "interval_width", 0.95))


def conformal_scale_from_paths(y, yhat, hi, eval_masks,
                               interval_width: float = 0.95,
                               min_points: int = 30):
    """Per-series interval scale factors from already-computed CV paths
    (the ``cross_validate(..., calibrate=True)`` route — one CV pass feeds
    metrics, the diagnostics frame, AND calibration)."""
    return _conformal_scale_impl(
        y, yhat, hi, eval_masks,
        # both are declared static on the impl, so the casts run at trace
        # time — they canonicalize the jit cache key (0.95 vs np.float64)
        # dflint: disable=host-sync-in-hot-path (trace-time static canonicalization)
        float(interval_width), int(min_points))


def conformal_interval_scale(
    batch,
    model: str = "prophet",
    config=None,
    cv: CVConfig = CVConfig(),
    key=None,
    xreg=None,
    min_points: int = 30,
):
    """Standalone entry: run the rolling-origin CV pass and return the (S,)
    conformal scale for ``config.interval_width``.  Prefer
    ``cross_validate(..., calibrate=True)`` when CV metrics are being
    computed anyway."""
    config, key, xreg = _cv_entry(batch, model, config, key, xreg,
                                  "conformal_interval_scale")
    cuts = cutoff_indices(batch.n_time, cv)
    yhat, lo, hi, eval_masks, _ = _cv_paths_impl(
        batch.y, batch.mask, batch.day, key,
        model=model, config=config, cuts=tuple(cuts), horizon=cv.horizon,
        xreg=xreg,
    )
    return conformal_scale_from_paths(batch.y, yhat, hi, eval_masks,
                                      interval_width=config_interval_width(config),
                                      min_points=min_points)


def apply_interval_scale(yhat, lo, hi, scale: Optional[jax.Array],
                         floor: Optional[float] = None):
    """Widen (or tighten) both half-bands multiplicatively around the point
    path: lo' = yhat - s (yhat - lo), hi' = yhat + s (hi - yhat).  A
    ``scale`` of None or all-ones is the identity.  ``floor`` re-applies a
    family's hard lower clamp after widening (croston's demand >= 0 —
    ``ModelFns.band_floor``): widening a floored band with s > 1 would
    otherwise push the lower bound below the floor the model guarantees."""
    if scale is None:
        return yhat, lo, hi
    s = jnp.asarray(scale)[:, None]
    lo2 = yhat - s * (yhat - lo)
    hi2 = yhat + s * (hi - yhat)
    if floor is not None:
        lo2 = jnp.maximum(lo2, floor)
    return yhat, lo2, hi2
